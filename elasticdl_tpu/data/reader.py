"""Data reader contract (reference data/reader/data_reader.py:9-49).

A reader exposes:
- ``create_shards()`` -> {shard_name: (start, num_records)} — called once by
  the master at job start to build the task table,
- ``read_records(task)`` -> iterator of raw record payloads for one task,
- ``metadata`` -> arbitrary dict forwarded to the user ``dataset_fn``.
"""

import csv
import glob
import os
from abc import ABC, abstractmethod
from typing import Dict, Iterator, Tuple

from elasticdl_tpu.data.record_file import (
    RecordFileScanner,
    num_records_in_file,
)


class Metadata:
    def __init__(self, column_names=None, **extra):
        self.column_names = column_names
        self.extra = extra


class AbstractDataReader(ABC):
    def __init__(self, **kwargs):
        self._kwargs = kwargs

    @abstractmethod
    def read_records(self, task) -> Iterator[bytes]:
        """Yield raw record payloads for ``task``'s shard range."""

    @abstractmethod
    def create_shards(self) -> Dict[str, Tuple[int, int]]:
        """Return {shard_name: (start_index, num_records)}."""

    @property
    def records_output_type(self) -> str:
        return "bytes"

    @property
    def metadata(self) -> Metadata:
        return Metadata()


def _expand_paths(data_origin: str):
    """A data origin is a file, a directory, or a glob."""
    if os.path.isdir(data_origin):
        paths = sorted(
            p for p in glob.glob(os.path.join(data_origin, "*"))
            if os.path.isfile(p)
        )
    else:
        paths = sorted(glob.glob(data_origin))
        if not paths and os.path.exists(data_origin):
            paths = [data_origin]
    if not paths:
        raise FileNotFoundError(f"No data files match {data_origin!r}")
    return paths


class RecordFileDataReader(AbstractDataReader):
    """Shards RecordFiles by record ranges (reference recordio_reader.py)."""

    def __init__(self, data_origin: str, **kwargs):
        super().__init__(**kwargs)
        self._data_origin = data_origin

    # Below this mean record size the native mmap reader wins (~5x: the
    # per-record Python interpreter overhead dominates); above it, the
    # buffered sequential scanner is already memcpy-bound and mmap page
    # faults make the native path slightly slower. Measured on this
    # image at 60B (4.8x faster) vs 3.3KB (0.88x).
    NATIVE_READ_MAX_MEAN_RECORD_BYTES = 1024

    def read_records(self, task) -> Iterator[bytes]:
        # Hot loop: the C extension reads the whole task range through
        # one mmap pass, building list[bytes] in C
        # (native/record_codec.py), when record granularity favors it.
        from elasticdl_tpu.native.record_codec import (
            native_record_reader_available,
            read_range,
        )

        if native_record_reader_available():
            total = num_records_in_file(task.shard_name)
            mean = os.path.getsize(task.shard_name) / max(total, 1)
            if mean <= self.NATIVE_READ_MAX_MEAN_RECORD_BYTES:
                # Clamp like RecordFileScanner does (a shard table built
                # before a file was rewritten shorter must not fail the
                # task on one path and succeed on the other).
                start = min(max(task.start, 0), total)
                end = min(task.end, total)
                yield from read_range(
                    task.shard_name, start, max(end - start, 0)
                )
                return
        with RecordFileScanner(
            task.shard_name, task.start, task.end - task.start
        ) as scanner:
            yield from scanner

    def create_shards(self) -> Dict[str, Tuple[int, int]]:
        # One (start, count) range per file; the task dispatcher splits
        # ranges into records_per_task-sized tasks (reference semantics:
        # recordio_reader.py create_shards + task_dispatcher.create_tasks).
        return {
            path: (0, num_records_in_file(path))
            for path in _expand_paths(self._data_origin)
        }


class CSVDataReader(AbstractDataReader):
    """CSV rows as records; shardable — parsed rows are cached per path (the
    reference's CSV reader is local-only, csv_reader.py:13-29)."""

    def __init__(self, data_origin: str, sep: str = ",", **kwargs):
        super().__init__(**kwargs)
        self._data_origin = data_origin
        self._sep = sep
        self._columns = None
        self._cache = {}  # path -> (mtime, header, rows)

    def _read_rows(self, path):
        mtime = os.path.getmtime(path)
        cached = self._cache.get(path)
        if cached is not None and cached[0] == mtime:
            return cached[1], cached[2]
        with open(path, newline="") as f:
            reader = csv.reader(f, delimiter=self._sep)
            rows = list(reader)
        header, body = (rows[0], rows[1:]) if rows else ([], [])
        self._cache[path] = (mtime, header, body)
        return header, body

    def read_records(self, task) -> Iterator[bytes]:
        header, rows = self._read_rows(task.shard_name)
        self._columns = header
        for row in rows[task.start:task.end]:
            yield self._sep.join(row).encode("utf-8")

    def create_shards(self) -> Dict[str, Tuple[int, int]]:
        flat = {}
        for path in _expand_paths(self._data_origin):
            header, rows = self._read_rows(path)
            self._columns = header
            flat[path] = (0, len(rows))
        return flat

    @property
    def records_output_type(self) -> str:
        return "csv"

    @property
    def metadata(self) -> Metadata:
        return Metadata(column_names=self._columns, sep=self._sep)
