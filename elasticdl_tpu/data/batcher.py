"""Static-shape batching for XLA.

The reference feeds variable final batches into eager TF
(worker/task_data_service.py → tf.data). XLA compiles one program per input
shape, so this framework pads every batch to ``batch_size`` and carries a
float ``mask`` (1.0 = real row, 0.0 = padding) that the loss and metrics
weight by. Padding replicates row 0 so dtypes/shapes are trivially right.
"""

from typing import Any, Callable, Dict, Iterator, List

import numpy as np


def pad_batch(features, labels, actual: int, batch_size: int):
    """Pad feature/label pytrees along axis 0 up to batch_size + build mask."""

    def _pad(arr):
        arr = np.asarray(arr)
        if arr.shape[0] == batch_size:
            return arr
        pad_rows = np.repeat(arr[:1], batch_size - arr.shape[0], axis=0)
        return np.concatenate([arr, pad_rows], axis=0)

    import jax

    mask = np.zeros((batch_size,), np.float32)
    mask[:actual] = 1.0
    return {
        "features": jax.tree.map(_pad, features),
        "labels": jax.tree.map(_pad, labels),
        "mask": mask,
    }


def batch_records(
    records: Iterator[Any],
    batch_size: int,
    dataset_fn: Callable,
    mode: str,
    metadata,
    drop_remainder: bool = False,
) -> Iterator[Dict[str, Any]]:
    """Group raw records into padded, masked batches via the user dataset_fn.

    ``dataset_fn(records, mode, metadata) -> (features, labels)`` converts a
    list of raw payloads into numpy pytrees (the JAX-native analog of the
    reference's tf.data map stage).
    """
    buf: List[Any] = []
    for record in records:
        buf.append(record)
        if len(buf) == batch_size:
            features, labels = dataset_fn(buf, mode, metadata)
            yield pad_batch(features, labels, batch_size, batch_size)
            buf = []
    if buf and not drop_remainder:
        features, labels = dataset_fn(buf, mode, metadata)
        yield pad_batch(features, labels, len(buf), batch_size)


def masked_mean(values, mask) -> Any:
    """Mean over real rows only — helper for user losses/metrics."""
    import jax.numpy as jnp

    values = values * mask
    return jnp.sum(values) / jnp.maximum(jnp.sum(mask), 1.0)
