"""Append-only record streams for online / continual learning
(docs/online_learning.md).

Batch readers (data/reader.py) expose a FINITE shard table and the
dispatcher walks it per epoch; a stream has no epochs. The contract
here is deliberately tiny so real buses (Kafka, Pub/Sub, a CDC tail)
can slot in behind it:

- a stream is a set of named **partitions**, each an append-only
  sequence of records with dense integer **offsets** ``0..end``;
- ``end_offset(partition)`` is the exclusive high-water mark — it only
  grows;
- ``read(partition, start, end)`` must serve any offset range that has
  not fallen off the retention horizon, byte-identical on every call
  (replays after a worker SIGKILL re-read the same bytes);
- ``append_time(partition, offset)`` is the record's ingest timestamp,
  feeding the ``stream_ingest_watermark_lag_seconds`` gauge.

The reference implementation is a **file tail**: one append-only frame
file per partition (``<dir>/<partition>.edlstream``), written by
``StreamWriter`` and tailed by ``FileTailStream``. Frames are
``[u32 len][u32 crc][f64 ts][payload]`` — a torn tail (crash mid-append)
is detected by length/crc and treated as end-of-stream, mirroring the
master journal's torn-frame discipline (master/journal.py). Recent
payloads stay in a bounded ``ReplayBuffer`` so the common case (a task
reading just-appended records) never touches disk twice; older ranges
fall back to the retained per-offset byte index and re-read the file.

Watermarks live in the MASTER's journal, not here: the committed
watermark for a partition advances only when the journal records the
resolving task report (master/stream_ingest.py), so a relaunched
pipeline resumes from what was durably acknowledged — never from what
a dead worker had merely read.
"""

import os
import struct
import threading
import zlib
from abc import ABC, abstractmethod
from collections import deque
from typing import Dict, Iterator, List, Tuple

from elasticdl_tpu.data.reader import AbstractDataReader, Metadata

STREAM_SUFFIX = ".edlstream"

# Frame header: payload length (u32) + crc32 of body (u32); body is
# an 8-byte little-endian ingest timestamp followed by the payload.
_HEADER = struct.Struct("<II")
_TS = struct.Struct("<d")


class StreamTruncatedError(Exception):
    """A requested offset range fell off the retention horizon (the
    backing file was truncated or rotated away under the tail)."""


class ReplayBuffer:
    """Bounded in-memory tail of one partition: the newest
    ``capacity`` payloads keyed by offset. Reads inside the window are
    pure memory; reads behind it miss (the source falls back to its
    durable store). Not a durability mechanism — just the cache that
    keeps steady-state ingestion off the disk read path."""

    def __init__(self, capacity: int):
        if capacity <= 0:
            raise ValueError(f"replay buffer capacity must be > 0, "
                             f"got {capacity}")
        self.capacity = int(capacity)
        self._payloads = deque()  # leftmost is self._base
        self._base = 0  # offset of _payloads[0]

    def push(self, offset: int, payload: bytes):
        if self._payloads and offset != self._base + len(self._payloads):
            raise ValueError(
                f"non-contiguous append: offset {offset}, "
                f"expected {self._base + len(self._payloads)}"
            )
        if not self._payloads:
            self._base = offset
        self._payloads.append(payload)
        while len(self._payloads) > self.capacity:
            self._payloads.popleft()
            self._base += 1

    def get_range(self, start: int, end: int):
        """payloads for [start, end) or ``None`` if any offset is
        outside the buffered window (caller re-reads durably)."""
        if start < self._base or end > self._base + len(self._payloads):
            return None
        return [self._payloads[i - self._base]
                for i in range(start, end)]

    @property
    def span(self) -> Tuple[int, int]:
        return self._base, self._base + len(self._payloads)


class StreamSource(ABC):
    """Abstract append-only record stream (see module docstring for
    the contract)."""

    @abstractmethod
    def partitions(self) -> List[str]:
        """Known partition names (may grow over time)."""

    @abstractmethod
    def end_offset(self, partition: str) -> int:
        """Exclusive high-water offset — monotonically nondecreasing."""

    @abstractmethod
    def read(self, partition: str, start: int, end: int) -> List[bytes]:
        """Payloads for offsets [start, end); raises
        ``StreamTruncatedError`` when the range fell off retention."""

    def append_time(self, partition: str, offset: int) -> float:
        """Epoch-seconds ingest time of ``offset`` (0.0 if unknown)."""
        return 0.0


class StreamWriter:
    """Producer side of the file-tail reference stream: append records
    to per-partition frame files. ``append`` returns the record's
    offset. ``fsync=True`` makes the append durable before returning
    (the drills' acked-producer mode)."""

    def __init__(self, stream_dir: str):
        self.stream_dir = stream_dir
        os.makedirs(stream_dir, exist_ok=True)
        self._lock = threading.Lock()
        self._files: Dict[str, object] = {}
        self._counts: Dict[str, int] = {}

    def _path(self, partition: str) -> str:
        if "/" in partition or partition.startswith("."):
            raise ValueError(f"bad partition name: {partition!r}")
        return os.path.join(self.stream_dir, partition + STREAM_SUFFIX)

    def append(self, partition: str, payload: bytes,
               ts: float = None, fsync: bool = False) -> int:
        import time as _time

        body = _TS.pack(_time.time() if ts is None else float(ts))
        body += bytes(payload)
        frame = _HEADER.pack(
            len(body), zlib.crc32(body) & 0xFFFFFFFF
        ) + body
        with self._lock:
            fh = self._files.get(partition)
            if fh is None:
                path = self._path(partition)
                count, pos, _idx = _scan_stream_file(path)
                fh = open(path, "ab")
                if fh.tell() != pos:
                    # Torn tail from a crashed producer: overwrite it
                    # so the next frame starts on a valid boundary.
                    fh.truncate(pos)
                    fh.seek(pos)
                self._files[partition] = fh
                self._counts[partition] = count
            offset = self._counts[partition]
            fh.write(frame)
            fh.flush()
            if fsync:
                os.fsync(fh.fileno())
            self._counts[partition] = offset + 1
            return offset

    def close(self):
        with self._lock:
            for fh in self._files.values():
                fh.close()
            self._files.clear()


def _scan_stream_file(path: str, start_pos: int = 0,
                      start_offset: int = 0):
    """Scan frames from ``start_pos``; returns (record_count,
    clean_end_pos, [(offset, byte_pos, ts)]). A torn or corrupt tail
    frame ends the scan (it is not yet part of the stream)."""
    index: List[Tuple[int, int, float]] = []
    if not os.path.exists(path):
        return start_offset, start_pos, index
    size = os.path.getsize(path)
    offset, pos = start_offset, start_pos
    with open(path, "rb") as fh:
        fh.seek(pos)
        while pos + _HEADER.size <= size:
            length, crc = _HEADER.unpack(fh.read(_HEADER.size))
            if length < _TS.size or pos + _HEADER.size + length > size:
                break  # torn tail
            body = fh.read(length)
            if (zlib.crc32(body) & 0xFFFFFFFF) != crc:
                break  # corrupt tail frame: stop before it
            (ts,) = _TS.unpack_from(body, 0)
            index.append((offset, pos, ts))
            pos += _HEADER.size + length
            offset += 1
    return offset, pos, index


class FileTailStream(StreamSource):
    """Tail ``<dir>/*.edlstream`` files as a live stream. Each
    ``poll()`` (or any read-path call) picks up newly appended frames
    and newly created partitions. Per-offset byte positions and ingest
    timestamps are retained for the whole stream (16B/record); payload
    bytes are cached only inside the bounded replay buffer."""

    def __init__(self, stream_dir: str,
                 replay_buffer_records: int = 4096):
        self.stream_dir = stream_dir
        self._lock = threading.Lock()
        self._replay_capacity = int(replay_buffer_records)
        # partition -> {"end": int, "pos": int, "index": [(pos, ts)],
        #               "buffer": ReplayBuffer}
        self._parts: Dict[str, dict] = {}

    # ---- tailing ------------------------------------------------------

    def poll(self) -> Dict[str, int]:
        """Absorb new partitions/frames; returns {partition: end}."""
        with self._lock:
            self._poll_locked()
            return {p: st["end"] for p, st in self._parts.items()}

    def _poll_locked(self):
        try:
            names = sorted(os.listdir(self.stream_dir))
        except OSError:
            names = []
        for name in names:
            if not name.endswith(STREAM_SUFFIX):
                continue
            partition = name[: -len(STREAM_SUFFIX)]
            st = self._parts.get(partition)
            if st is None:
                st = {"end": 0, "pos": 0, "index": [],
                      "buffer": ReplayBuffer(self._replay_capacity)}
                self._parts[partition] = st
            path = os.path.join(self.stream_dir, name)
            if os.path.getsize(path) <= st["pos"]:
                continue
            end, pos, fresh = _scan_stream_file(
                path, st["pos"], st["end"]
            )
            if fresh:
                with open(path, "rb") as fh:
                    for offset, byte_pos, ts in fresh:
                        fh.seek(byte_pos)
                        length, _crc = _HEADER.unpack(
                            fh.read(_HEADER.size)
                        )
                        payload = fh.read(length)[_TS.size:]
                        st["index"].append((byte_pos, ts))
                        st["buffer"].push(offset, payload)
            st["end"], st["pos"] = end, pos

    # ---- StreamSource -------------------------------------------------

    def partitions(self) -> List[str]:
        with self._lock:
            self._poll_locked()
            return sorted(self._parts)

    def end_offset(self, partition: str) -> int:
        with self._lock:
            self._poll_locked()
            st = self._parts.get(partition)
            return st["end"] if st else 0

    def read(self, partition: str, start: int, end: int) -> List[bytes]:
        if end < start or start < 0:
            raise ValueError(f"bad range [{start}, {end})")
        with self._lock:
            self._poll_locked()
            st = self._parts.get(partition)
            if st is None or end > st["end"]:
                raise StreamTruncatedError(
                    f"{partition}: [{start}, {end}) beyond appended "
                    f"end {st['end'] if st else 0}"
                )
            cached = st["buffer"].get_range(start, end)
            if cached is not None:
                return cached
            index = [st["index"][i] for i in range(start, end)]
        # Cache miss: re-read from the durable file (outside the lock —
        # frames are immutable once scanned).
        path = os.path.join(self.stream_dir, partition + STREAM_SUFFIX)
        out = []
        try:
            with open(path, "rb") as fh:
                for byte_pos, _ts in index:
                    fh.seek(byte_pos)
                    head = fh.read(_HEADER.size)
                    length, crc = _HEADER.unpack(head)
                    body = fh.read(length)
                    if (zlib.crc32(body) & 0xFFFFFFFF) != crc:
                        raise StreamTruncatedError(
                            f"{partition}: frame at byte {byte_pos} "
                            "no longer matches its crc"
                        )
                    out.append(body[_TS.size:])
        except OSError as err:
            raise StreamTruncatedError(
                f"{partition}: backing file unreadable ({err})"
            )
        return out

    def append_time(self, partition: str, offset: int) -> float:
        with self._lock:
            st = self._parts.get(partition)
            if st is None or offset >= len(st["index"]):
                self._poll_locked()
                st = self._parts.get(partition)
            if st is None or not (0 <= offset < len(st["index"])):
                return 0.0
            return st["index"][offset][1]


class StreamDataReader(AbstractDataReader):
    """Worker-side reader for STREAM tasks: ``task.shard_name`` is the
    partition, ``task.start``/``task.end`` the offset range. There is
    no static shard table (``create_shards`` is empty — the master's
    stream ingestor generates tasks from the live tail instead), which
    is exactly why the dispatcher's streaming mode never reports
    ``finished`` while the source is live."""

    def __init__(self, stream_dir: str = "", source: StreamSource = None,
                 fallback=None, **kwargs):
        super().__init__(**kwargs)
        if source is None:
            if not stream_dir:
                raise ValueError("stream_dir or source required")
            source = FileTailStream(stream_dir)
        self._source = source
        # A streaming job can still run watermark-triggered eval rounds
        # over a finite --validation_data shard table; those tasks are
        # not stream-tagged and read through the batch reader.
        self._fallback = fallback

    @property
    def source(self) -> StreamSource:
        return self._source

    def read_records(self, task) -> Iterator[bytes]:
        extended = getattr(task, "extended_config", None) or {}
        if not extended.get("stream"):
            if self._fallback is None:
                raise ValueError(
                    f"non-stream task {task.shard_name!r} but no "
                    "fallback reader (pass --validation_data on the "
                    "worker too)"
                )
            yield from self._fallback.read_records(task)
            return
        for payload in self._source.read(
            task.shard_name, task.start, task.end
        ):
            yield payload

    def create_shards(self) -> Dict[str, Tuple[int, int]]:
        return {}

    @property
    def metadata(self) -> Metadata:
        return Metadata(stream=True)
