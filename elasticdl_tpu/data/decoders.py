"""Common record decoders for model-zoo dataset_fns.

The reference zoo repeats a TFRecord image parse in every image model
(mnist/cifar10/resnet50 dataset_fns); here the shared shape lives in the
framework so zoo modules stay one-liners and stay in lockstep.
"""

import numpy as np

from elasticdl_tpu.common import tensor_utils
from elasticdl_tpu.common.constants import Mode


def image_classification_dataset_fn(records, mode, metadata,
                                    image_key="image", label_key="label",
                                    scale=255.0):
    """Decode {image, label} records into (B,H,W[,C]) float features in
    [0,1] plus int32 labels (zeroed for PREDICTION)."""
    images, labels = [], []
    for payload in records:
        rec = tensor_utils.loads(payload)
        images.append(np.asarray(rec[image_key], np.float32) / scale)
        labels.append(int(rec.get(label_key, 0)))
    features = np.stack(images).astype(np.float32)
    labels = np.asarray(labels, np.int32)
    if mode == Mode.PREDICTION:
        return features, np.zeros_like(labels)
    return features, labels


def argmax_accuracy_metrics():
    """{'accuracy': fn} for softmax-logit classifiers."""
    return {
        "accuracy": lambda labels, outputs: float(
            np.mean(np.argmax(outputs, axis=1) == labels)
        )
    }
