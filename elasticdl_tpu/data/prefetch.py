"""Background batch prefetch: overlap host IO/decode with device steps.

The reference gets pipelining from ``tf.data`` prefetch
(``worker.py:1022-1027`` ``.prefetch(1)``); here a bounded background
thread plays that role: while the device executes step N, the thread
reads records and runs the user ``dataset_fn`` for step N+1.

Stages chain: ``staged(upstream, fn)`` runs ``fn`` over an upstream
iterator on its own thread, so a pipeline like decode → prepare →
device-place keeps every stage concurrently busy (the host-tier sparse
path uses this for its ``jax.device_put`` stage — see
``embedding/host_engine.prepared_batches``). Closing a downstream stage
closes the whole chain.

Producer exceptions re-raise in the consumer (a bad record must fail
the task, not hang it). ``close()`` stops the producer even mid-queue —
abandoned iterators (worker error paths) must not leak a blocked
thread — and iterators are context managers so abandonment is
explicit.
"""

import queue
import threading
from typing import Callable, Iterator, Optional

_SENTINEL = object()


class PrefetchIterator:
    def __init__(self, source: Iterator, depth: int = 2,
                 upstream: Optional["PrefetchIterator"] = None):
        # ``upstream``: a previous pipeline stage this iterator consumes
        # (via ``source`` wrapping it); close() cascades to it so
        # abandoning the last stage tears down the whole chain.
        self._queue: "queue.Queue" = queue.Queue(maxsize=max(depth, 1))
        self._stop = threading.Event()
        self._error = None
        self._done = False
        self._upstream = upstream
        self._thread = threading.Thread(
            target=self._produce, args=(source,), daemon=True
        )
        self._thread.start()

    def _produce(self, source):
        try:
            for item in source:
                while not self._stop.is_set():
                    try:
                        self._queue.put(item, timeout=0.1)
                        break
                    except queue.Full:
                        continue
                if self._stop.is_set():
                    return
        except BaseException as exc:  # re-raised in the consumer
            self._error = exc
        while not self._stop.is_set():
            try:
                self._queue.put(_SENTINEL, timeout=0.1)
                return
            except queue.Full:
                continue

    def __iter__(self):
        return self

    def __next__(self):
        if self._done or self._stop.is_set():
            # Exhausted/closed iterators stay exhausted (repeat the
            # stored error rather than blocking on an empty queue).
            if self._error is not None:
                raise self._error
            raise StopIteration
        item = self._queue.get()
        if item is _SENTINEL:
            self._done = True
            if self._error is not None:
                raise self._error
            raise StopIteration
        return item

    def close(self):
        self._stop.set()
        # Tear down the chain upstream-first: our producer may be
        # blocked in the upstream's __next__, and the upstream's close
        # releases it (sentinel below).
        if self._upstream is not None:
            self._upstream.close()
        # Unblock a producer waiting on a full queue, then wait for it to
        # exit: a producer mid-read outliving its task would race the
        # next task's producer on the shared (non-thread-safe) reader.
        try:
            while True:
                self._queue.get_nowait()
        except queue.Empty:
            pass
        self._thread.join(timeout=30.0)
        # Release a consumer blocked in __next__ on the (now drained)
        # queue — when this iterator feeds a later pipeline stage, that
        # consumer is the downstream producer thread, which would
        # otherwise sit in ``get()`` forever. One sentinel suffices:
        # __next__ marks done on the first one.
        try:
            self._queue.put_nowait(_SENTINEL)
        except queue.Full:
            pass

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


def prefetch(source: Iterator, depth: int = 2) -> PrefetchIterator:
    return PrefetchIterator(source, depth)


def staged(upstream: PrefetchIterator, fn: Callable,
           depth: int = 1) -> PrefetchIterator:
    """A further pipeline stage: apply ``fn`` to each item of
    ``upstream`` on a dedicated thread, ``depth`` items ahead of the
    consumer. Closing the returned iterator closes ``upstream`` too.
    Items are processed in order; an ``fn`` failure re-raises in the
    consumer like any producer error."""
    return PrefetchIterator(
        (fn(item) for item in upstream), depth=depth, upstream=upstream
    )
