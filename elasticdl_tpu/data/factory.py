"""Reader factory (reference data/reader/data_reader_factory.py:10-56).

Picks a reader implementation from the data origin's extension, an explicit
``reader_type`` in data_reader_params, or a user ``custom_data_reader``.
"""

import os

from elasticdl_tpu.common.constants import ReaderType
from elasticdl_tpu.data.reader import CSVDataReader, RecordFileDataReader


def parse_data_reader_params(params: str) -> dict:
    """Parse 'k1=v1;k2=v2' data_reader_params strings."""
    out = {}
    if not params:
        return out
    for kv in params.replace(",", ";").split(";"):
        kv = kv.strip()
        if not kv:
            continue
        key, _, value = kv.partition("=")
        out[key.strip()] = value.strip()
    return out


def create_data_reader(data_origin: str, custom_reader=None, **kwargs):
    if custom_reader is not None:
        return custom_reader(data_origin=data_origin, **kwargs)
    reader_type = kwargs.pop("reader_type", None)
    # Table origins (sqlite/csv-table/ODPS) route by URL scheme
    # (reference data_reader_factory.py: ODPS selected by env+path).
    # Stream origins (data/stream.py): tail of append-only partitions,
    # selected by scheme or explicit reader_type.
    if reader_type == ReaderType.STREAM or data_origin.startswith(
        "stream://"
    ):
        from elasticdl_tpu.data.stream import StreamDataReader

        stream_dir = data_origin
        if stream_dir.startswith("stream://"):
            stream_dir = stream_dir[len("stream://"):]
        return StreamDataReader(stream_dir=stream_dir, **kwargs)
    if reader_type == ReaderType.TABLE or data_origin.startswith(
        ("table+sqlite://", "table+csv://", "table+rpc://", "odps://")
    ):
        from elasticdl_tpu.data.table_reader import TableDataReader

        return TableDataReader(data_origin=data_origin, **kwargs)
    if reader_type == ReaderType.CSV:
        return CSVDataReader(data_origin=data_origin, **kwargs)
    if reader_type == ReaderType.RECORD_FILE:
        return RecordFileDataReader(data_origin=data_origin, **kwargs)
    if reader_type is None:
        ext = os.path.splitext(data_origin.rstrip("/*"))[1].lower()
        if ext == ".csv":
            return CSVDataReader(data_origin=data_origin, **kwargs)
        return RecordFileDataReader(data_origin=data_origin, **kwargs)
    raise ValueError(f"Unknown reader_type {reader_type!r}")
