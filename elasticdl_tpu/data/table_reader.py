"""Table data reader — the ODPS/MaxCompute plane, TPU-build edition.

Reference parity targets: ``data/reader/odps_reader.py:12-60`` (shard =
table row-range), ``data/odps_io.py`` (retrying range reads) and
``data/parallel_odps_table_reader.py`` (thread-pool prefetch of ranges).

Design: the reader is generic over a ``TableSource`` (count + range read
of rows); concrete sources:

- ``SqliteTableSource`` — stdlib sqlite3, rowid-range addressable; the
  in-repo stand-in for a cloud table service, fully testable.
- ``CsvTableSource`` — header CSV as a table.
- ``OdpsTableSource`` — real MaxCompute via pyodps, import-gated: this
  image has no pyodps (and no egress), so constructing it without the
  package raises with instructions, mirroring how the reference gates
  ODPS tests behind env vars.

Rows are serialized to msgpack dicts (column name → value) so the user
``dataset_fn`` sees the same payloads as any other reader.
"""

import threading
import time
from typing import Dict, Iterator, List, Optional, Tuple
from urllib.parse import parse_qs, urlparse

from elasticdl_tpu.common import tensor_utils
from elasticdl_tpu.common.log_utils import get_logger
from elasticdl_tpu.data.reader import AbstractDataReader, Metadata

logger = get_logger("table_reader")


class TableSource:
    """count() + read(start, end) over ordered rows."""

    def count(self) -> int:
        raise NotImplementedError

    def column_names(self) -> List[str]:
        raise NotImplementedError

    def read(self, start: int, end: int) -> Iterator[dict]:
        """Yield rows [start, end) as column dicts."""
        raise NotImplementedError

    def is_transient_error(self, exc: BaseException) -> bool:
        """Whether a read/count failure is worth retrying. Sources with
        richer error models (RPC status codes) override this."""
        return is_transient_error(exc)

    def close(self):
        pass


def is_transient_error(exc: BaseException) -> bool:
    """Default transient/permanent classification for table IO.

    Transient (retry): network/file-system hiccups (OSError family incl.
    ConnectionError/TimeoutError) and sqlite busy/locked
    (sqlite3.OperationalError). Permanent (surface immediately): schema
    and programming errors — ValueError/KeyError/TypeError, missing
    tables — where a retry would just repeat the failure. The reference
    retried *every* exception (odps_io.py:243-265 catches Exception);
    classifying keeps genuine bugs loud, which its own tests relied on.
    """
    import sqlite3

    if isinstance(exc, sqlite3.OperationalError):
        # sqlite uses OperationalError for BOTH contention (locked/busy
        # — transient) and misconfiguration (no such table/column, SQL
        # syntax — permanent). Classify by message; unknown operational
        # errors default to transient (IO-flavored in practice).
        msg = str(exc).lower()
        permanent = ("no such table", "no such column", "syntax error",
                     "unable to open database")
        return not any(p in msg for p in permanent)
    if isinstance(exc, sqlite3.Error):
        return False
    if isinstance(exc, FileNotFoundError):
        return False  # a missing file won't appear by retrying
    return isinstance(exc, OSError)


class RetryingSource(TableSource):
    """Fault envelope around any TableSource (reference ``odps_io.py``
    ``record_generator_with_retry`` / ``read_batch`` retry loops).

    Improvements over the reference envelope:

    - **Resume, don't restart**: the reference re-runs the generator
      from ``start`` after a mid-stream failure, re-yielding rows the
      consumer already saw (duplicated training records). Here the
      retry resumes at ``start + rows_already_yielded``.
    - **Error classification**: only transient errors retry
      (``is_transient_error`` — the wrapped source can override);
      permanent ones surface immediately.
    - Exponential backoff with a cap, vs the reference's fixed 5 s.
    """

    def __init__(self, source: TableSource, max_retries: int = 5,
                 backoff_secs: float = 0.5, backoff_cap: float = 30.0):
        self._source = source
        self._max_retries = int(max_retries)
        self._backoff = float(backoff_secs)
        self._cap = float(backoff_cap)

    def _retry_loop(self, what: str, fn):
        delay = self._backoff
        for attempt in range(self._max_retries + 1):
            try:
                return fn()
            except Exception as exc:
                if (
                    not self._source.is_transient_error(exc)
                    or attempt == self._max_retries
                ):
                    raise
                logger.warning(
                    "table %s failed (%s: %s); retry %d/%d in %.1fs",
                    what, type(exc).__name__, exc, attempt + 1,
                    self._max_retries, delay,
                )
                time.sleep(delay)
                delay = min(delay * 2, self._cap)

    def count(self) -> int:
        return self._retry_loop("count", self._source.count)

    def column_names(self) -> List[str]:
        return self._retry_loop("column_names", self._source.column_names)

    def read(self, start: int, end: int) -> Iterator[dict]:
        yielded = 0
        delay = self._backoff
        attempt = 0
        progressed = False
        while True:
            try:
                for row in self._source.read(start + yielded, end):
                    yield row
                    yielded += 1
                    progressed = True
                return
            except Exception as exc:
                if progressed:
                    # A recovered-and-resumed stretch means the service
                    # is healthy between failures: fresh budget per
                    # failure, not cumulative over a minutes-long shard
                    # (6 individually-recovered restarts must not kill
                    # the task on the 6th).
                    attempt = 0
                    delay = self._backoff
                    progressed = False
                if (
                    not self._source.is_transient_error(exc)
                    or attempt >= self._max_retries
                ):
                    raise
                attempt += 1
                logger.warning(
                    "table read [%d, %d) failed at +%d rows (%s: %s); "
                    "retry %d/%d in %.1fs", start, end, yielded,
                    type(exc).__name__, exc, attempt, self._max_retries,
                    delay,
                )
                time.sleep(delay)
                delay = min(delay * 2, self._cap)

    def is_transient_error(self, exc: BaseException) -> bool:
        return self._source.is_transient_error(exc)

    def close(self):
        self._source.close()


class SqliteTableSource(TableSource):
    def __init__(self, path: str, table: str):
        import sqlite3

        self._path = path
        self._table = table
        # One connection per thread (sqlite objects are thread-bound and
        # the parallel reader fans ranges out over a pool).
        self._local = threading.local()
        cols = self._conn().execute(
            f"PRAGMA table_info({self._quoted})"
        ).fetchall()
        if not cols:
            raise ValueError(f"No such table {table!r} in {path}")
        self._columns = [c[1] for c in cols]

    @property
    def _quoted(self) -> str:
        return '"' + self._table.replace('"', '""') + '"'

    def _conn(self):
        conn = getattr(self._local, "conn", None)
        if conn is None:
            import sqlite3

            conn = sqlite3.connect(self._path)
            self._local.conn = conn
        return conn

    def count(self) -> int:
        row = self._conn().execute(
            f"SELECT COUNT(*) FROM {self._quoted}"
        ).fetchone()
        return int(row[0])

    def column_names(self) -> List[str]:
        return list(self._columns)

    def read(self, start: int, end: int) -> Iterator[dict]:
        # Index the range via rowid (the PK btree) instead of
        # LIMIT/OFFSET, which walks all `start` rows per call — O(n^2)
        # over a chunked shard scan. Rowids are 1-based and contiguous
        # for append-only tables (our ingest pattern; a table with
        # deletions should be compacted/VACUUMed first).
        cursor = self._conn().execute(
            f"SELECT * FROM {self._quoted} "
            f"WHERE rowid > ? AND rowid <= ? ORDER BY rowid",
            (start, end),
        )
        for row in cursor:
            yield dict(zip(self._columns, row))


class CsvTableSource(TableSource):
    def __init__(self, path: str):
        import csv

        self._path = path
        with open(path, newline="") as f:
            reader = csv.reader(f)
            self._columns = next(reader)
            self._num_rows = sum(1 for _ in reader)

    def count(self) -> int:
        return self._num_rows

    def column_names(self) -> List[str]:
        return list(self._columns)

    def read(self, start: int, end: int) -> Iterator[dict]:
        import csv

        with open(self._path, newline="") as f:
            reader = csv.reader(f)
            next(reader)  # header
            for i, row in enumerate(reader):
                if i >= end:
                    return
                if i >= start:
                    yield dict(zip(self._columns, row))


class OdpsTableSource(TableSource):
    """MaxCompute table via pyodps (import-gated; reference
    ``odps_io.py:61-142`` ODPSReader: project/endpoint/table[,partition]
    range reads over ``open_reader``).

    The class body is exercised against a faked pyodps API in
    tests/test_table_reader_and_tools.py (this image has no pyodps and
    no egress); only the import itself is environment-gated.
    """

    # pyodps exception class names worth retrying (reference
    # odps_io.py:243-265 retried everything; we classify — server-side
    # and connection flakes retry, schema/auth errors surface).
    _TRANSIENT_ERROR_NAMES = frozenset({
        "ConnectTimeout", "ReadTimeout", "Timeout",
        "InternalServerError", "ServiceUnavailable",
        "RequestTimeTooSkewed", "StreamError",
    })

    def __init__(self, project: str, table: str, access_id: str = "",
                 access_key: str = "", endpoint: str = "",
                 partition: str = ""):
        try:
            import odps  # noqa: F401
        except ImportError as e:
            raise ImportError(
                "OdpsTableSource requires the 'pyodps' package, which is "
                "not available in this environment; use a sqlite:// or "
                "csv table origin, or install pyodps where egress exists."
            ) from e
        from odps import ODPS

        self._odps = ODPS(access_id, access_key, project,
                          endpoint=endpoint)
        self._table = self._odps.get_table(table)
        self._partition = partition or None
        self._columns = [c.name for c in self._table.schema.columns]

    def _open_reader(self):
        if self._partition:
            return self._table.open_reader(partition=self._partition)
        return self._table.open_reader()

    def count(self) -> int:
        with self._open_reader() as reader:
            return reader.count

    def column_names(self) -> List[str]:
        return list(self._columns)

    def read(self, start: int, end: int) -> Iterator[dict]:
        with self._open_reader() as reader:
            for record in reader.read(start=start, count=end - start):
                yield dict(zip(self._columns, record.values))

    def is_transient_error(self, exc: BaseException) -> bool:
        for klass in type(exc).__mro__:
            if klass.__name__ in self._TRANSIENT_ERROR_NAMES:
                return True
        return is_transient_error(exc)


def open_table_source(data_origin: str) -> TableSource:
    """Parse a table origin URL:

    - ``table+sqlite:///path/to.db?table=name``
    - ``table+csv:///path/to.csv``
    - ``table+rpc://host:port`` (a running data.table_service)
    - ``odps://project/tables/name``
    """
    parsed = urlparse(data_origin)
    scheme = parsed.scheme
    if scheme == "table+sqlite":
        q = parse_qs(parsed.query)
        table = q.get("table", ["data"])[0]
        return SqliteTableSource(parsed.path, table)
    if scheme == "table+csv":
        return CsvTableSource(parsed.path)
    if scheme == "table+rpc":
        from elasticdl_tpu.data.table_service import RemoteTableSource

        return RemoteTableSource(parsed.netloc)
    if scheme == "odps":
        import os

        parts = parsed.path.strip("/").split("/")
        table = parts[-1] if parts else ""
        q = parse_qs(parsed.query)
        # Credentials come from the reference's MaxCompute env contract
        # (common/constants.py:15-18: MAXCOMPUTE_AK/SK/ENDPOINT), never
        # from the URL.
        return OdpsTableSource(
            project=parsed.netloc, table=table,
            access_id=os.environ.get("MAXCOMPUTE_AK", ""),
            access_key=os.environ.get("MAXCOMPUTE_SK", ""),
            endpoint=os.environ.get("MAXCOMPUTE_ENDPOINT", ""),
            partition=q.get("partition", [""])[0],
        )
    raise ValueError(f"Unrecognized table origin {data_origin!r}")


class TableDataReader(AbstractDataReader):
    """Row-range sharded reader over a TableSource (reference
    odps_reader.py: one shard table, shards = row ranges; the dispatcher
    splits the range into tasks)."""

    def __init__(self, data_origin: str, source: Optional[TableSource] =
                 None, num_prefetch_threads: int = 0,
                 prefetch_chunk: int = 256, max_retries: int = 5,
                 backoff_secs: float = 0.5, **kwargs):
        super().__init__(**kwargs)
        self._data_origin = data_origin
        source = source or open_table_source(data_origin)
        # Every source rides the fault envelope (reference readers
        # retried inside odps_io; a transient error must not kill the
        # task — the dispatcher's 3-retry budget is for real failures).
        if not isinstance(source, RetryingSource):
            source = RetryingSource(
                source, max_retries=max_retries, backoff_secs=backoff_secs
            )
        self._source = source
        self._num_prefetch_threads = int(num_prefetch_threads)
        self._prefetch_chunk = int(prefetch_chunk)

    def create_shards(self) -> Dict[str, Tuple[int, int]]:
        return {self._data_origin: (0, self._source.count())}

    def read_records(self, task) -> Iterator[bytes]:
        rows = (
            self._parallel_rows(task.start, task.end)
            if self._num_prefetch_threads > 1
            else self._source.read(task.start, task.end)
        )
        for row in rows:
            yield tensor_utils.dumps(row)

    def _parallel_rows(self, start: int, end: int) -> Iterator[dict]:
        """Thread-pool range prefetch preserving row order (reference
        parallel_odps_table_reader.py). ``executor.map`` keeps order and
        re-raises worker exceptions in the consumer, so a failing range
        read fails the task instead of hanging it."""
        from concurrent.futures import ThreadPoolExecutor

        chunk = self._prefetch_chunk
        ranges = [
            (s, min(s + chunk, end)) for s in range(start, end, chunk)
        ]
        with ThreadPoolExecutor(self._num_prefetch_threads) as pool:
            for rows in pool.map(
                lambda r: list(self._source.read(*r)), ranges
            ):
                yield from rows

    @property
    def metadata(self) -> Metadata:
        return Metadata(column_names=self._source.column_names())
