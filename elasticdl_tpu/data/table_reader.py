"""Table data reader — the ODPS/MaxCompute plane, TPU-build edition.

Reference parity targets: ``data/reader/odps_reader.py:12-60`` (shard =
table row-range), ``data/odps_io.py`` (retrying range reads) and
``data/parallel_odps_table_reader.py`` (thread-pool prefetch of ranges).

Design: the reader is generic over a ``TableSource`` (count + range read
of rows); concrete sources:

- ``SqliteTableSource`` — stdlib sqlite3, rowid-range addressable; the
  in-repo stand-in for a cloud table service, fully testable.
- ``CsvTableSource`` — header CSV as a table.
- ``OdpsTableSource`` — real MaxCompute via pyodps, import-gated: this
  image has no pyodps (and no egress), so constructing it without the
  package raises with instructions, mirroring how the reference gates
  ODPS tests behind env vars.

Rows are serialized to msgpack dicts (column name → value) so the user
``dataset_fn`` sees the same payloads as any other reader.
"""

import threading
from typing import Dict, Iterator, List, Optional, Tuple
from urllib.parse import parse_qs, urlparse

from elasticdl_tpu.common import tensor_utils
from elasticdl_tpu.data.reader import AbstractDataReader, Metadata


class TableSource:
    """count() + read(start, end) over ordered rows."""

    def count(self) -> int:
        raise NotImplementedError

    def column_names(self) -> List[str]:
        raise NotImplementedError

    def read(self, start: int, end: int) -> Iterator[dict]:
        """Yield rows [start, end) as column dicts."""
        raise NotImplementedError

    def close(self):
        pass


class SqliteTableSource(TableSource):
    def __init__(self, path: str, table: str):
        import sqlite3

        self._path = path
        self._table = table
        # One connection per thread (sqlite objects are thread-bound and
        # the parallel reader fans ranges out over a pool).
        self._local = threading.local()
        cols = self._conn().execute(
            f"PRAGMA table_info({self._quoted})"
        ).fetchall()
        if not cols:
            raise ValueError(f"No such table {table!r} in {path}")
        self._columns = [c[1] for c in cols]

    @property
    def _quoted(self) -> str:
        return '"' + self._table.replace('"', '""') + '"'

    def _conn(self):
        conn = getattr(self._local, "conn", None)
        if conn is None:
            import sqlite3

            conn = sqlite3.connect(self._path)
            self._local.conn = conn
        return conn

    def count(self) -> int:
        row = self._conn().execute(
            f"SELECT COUNT(*) FROM {self._quoted}"
        ).fetchone()
        return int(row[0])

    def column_names(self) -> List[str]:
        return list(self._columns)

    def read(self, start: int, end: int) -> Iterator[dict]:
        # Index the range via rowid (the PK btree) instead of
        # LIMIT/OFFSET, which walks all `start` rows per call — O(n^2)
        # over a chunked shard scan. Rowids are 1-based and contiguous
        # for append-only tables (our ingest pattern; a table with
        # deletions should be compacted/VACUUMed first).
        cursor = self._conn().execute(
            f"SELECT * FROM {self._quoted} "
            f"WHERE rowid > ? AND rowid <= ? ORDER BY rowid",
            (start, end),
        )
        for row in cursor:
            yield dict(zip(self._columns, row))


class CsvTableSource(TableSource):
    def __init__(self, path: str):
        import csv

        self._path = path
        with open(path, newline="") as f:
            reader = csv.reader(f)
            self._columns = next(reader)
            self._num_rows = sum(1 for _ in reader)

    def count(self) -> int:
        return self._num_rows

    def column_names(self) -> List[str]:
        return list(self._columns)

    def read(self, start: int, end: int) -> Iterator[dict]:
        import csv

        with open(self._path, newline="") as f:
            reader = csv.reader(f)
            next(reader)  # header
            for i, row in enumerate(reader):
                if i >= end:
                    return
                if i >= start:
                    yield dict(zip(self._columns, row))


class OdpsTableSource(TableSource):
    """MaxCompute table via pyodps (import-gated; reference odps_io.py)."""

    def __init__(self, project: str, table: str, access_id: str = "",
                 access_key: str = "", endpoint: str = ""):
        try:
            import odps  # noqa: F401
        except ImportError as e:
            raise ImportError(
                "OdpsTableSource requires the 'pyodps' package, which is "
                "not available in this environment; use a sqlite:// or "
                "csv table origin, or install pyodps where egress exists."
            ) from e
        from odps import ODPS

        self._odps = ODPS(access_id, access_key, project,
                          endpoint=endpoint)
        self._table = self._odps.get_table(table)
        self._columns = [c.name for c in self._table.schema.columns]

    def count(self) -> int:
        with self._table.open_reader() as reader:
            return reader.count

    def column_names(self) -> List[str]:
        return list(self._columns)

    def read(self, start: int, end: int) -> Iterator[dict]:
        with self._table.open_reader() as reader:
            for record in reader.read(start=start, count=end - start):
                yield dict(zip(self._columns, record.values))


def open_table_source(data_origin: str) -> TableSource:
    """Parse a table origin URL:

    - ``table+sqlite:///path/to.db?table=name``
    - ``table+csv:///path/to.csv``
    - ``odps://project/tables/name``
    """
    parsed = urlparse(data_origin)
    scheme = parsed.scheme
    if scheme == "table+sqlite":
        q = parse_qs(parsed.query)
        table = q.get("table", ["data"])[0]
        return SqliteTableSource(parsed.path, table)
    if scheme == "table+csv":
        return CsvTableSource(parsed.path)
    if scheme == "odps":
        parts = parsed.path.strip("/").split("/")
        table = parts[-1] if parts else ""
        return OdpsTableSource(project=parsed.netloc, table=table)
    raise ValueError(f"Unrecognized table origin {data_origin!r}")


class TableDataReader(AbstractDataReader):
    """Row-range sharded reader over a TableSource (reference
    odps_reader.py: one shard table, shards = row ranges; the dispatcher
    splits the range into tasks)."""

    def __init__(self, data_origin: str, source: Optional[TableSource] =
                 None, num_prefetch_threads: int = 0,
                 prefetch_chunk: int = 256, **kwargs):
        super().__init__(**kwargs)
        self._data_origin = data_origin
        self._source = source or open_table_source(data_origin)
        self._num_prefetch_threads = int(num_prefetch_threads)
        self._prefetch_chunk = int(prefetch_chunk)

    def create_shards(self) -> Dict[str, Tuple[int, int]]:
        return {self._data_origin: (0, self._source.count())}

    def read_records(self, task) -> Iterator[bytes]:
        rows = (
            self._parallel_rows(task.start, task.end)
            if self._num_prefetch_threads > 1
            else self._source.read(task.start, task.end)
        )
        for row in rows:
            yield tensor_utils.dumps(row)

    def _parallel_rows(self, start: int, end: int) -> Iterator[dict]:
        """Thread-pool range prefetch preserving row order (reference
        parallel_odps_table_reader.py). ``executor.map`` keeps order and
        re-raises worker exceptions in the consumer, so a failing range
        read fails the task instead of hanging it."""
        from concurrent.futures import ThreadPoolExecutor

        chunk = self._prefetch_chunk
        ranges = [
            (s, min(s + chunk, end)) for s in range(start, end, chunk)
        ]
        with ThreadPoolExecutor(self._num_prefetch_threads) as pool:
            for rows in pool.map(
                lambda r: list(self._source.read(*r)), ranges
            ):
                yield from rows

    @property
    def metadata(self) -> Metadata:
        return Metadata(column_names=self._source.column_names())
