"""Pod lifecycle + elasticity core (reference master/k8s_instance_manager.py).

The reference's ``InstanceManager`` starts worker/PS pods, watches pod
events, detects preemption (DELETED, or Failed with exit code 137 =
SIGKILL/OOM, reference k8s_instance_manager.py:250-271), re-queues the dead
worker's tasks and relaunches it under a **new** worker id
(reference :297-302). There is no PS here — state lives on the mesh and in
sharded checkpoints — so only the worker plane is managed; a relaunched
worker re-enters training by restoring the latest checkpoint and pulling
tasks (SURVEY.md §7.5).

Events are normalized through ``classify_pod_event`` so tests drive the
manager with plain dicts and no cluster (SURVEY.md §4 lesson).
"""

import itertools
import threading
from typing import Callable, Dict, List, Optional

from elasticdl_tpu.common.log_utils import get_logger
from elasticdl_tpu.platform.k8s_client import (
    ELASTICDL_REPLICA_INDEX_KEY,
    ELASTICDL_REPLICA_TYPE_KEY,
    build_pod_manifest,
    build_row_service_service_manifest,
    get_row_service_pod_name,
    get_row_service_service_name,
    get_worker_pod_name,
)

logger = get_logger("instance_manager")

# Exit code meaning "killed" (preemption / OOM), reference :250-271.
_EXIT_KILLED = 137

# ---- chaos seam (chaos/interceptors.py installs) -----------------------
# _chaos_observer(event, **info) with events "kill_worker" (a straggler
# kill was issued), "worker_dead" (recovery started: tasks re-queued)
# and "worker_relaunched" (replacement started) — the chaos plane times
# kill→relaunch recovery latency off these without the manager knowing
# chaos exists.
_chaos_observer: Optional[Callable] = None


def set_chaos_observer(fn: Optional[Callable]):
    global _chaos_observer
    _chaos_observer = fn


def _observe(event: str, **info):
    if _chaos_observer is not None:
        _chaos_observer(event, **info)


def classify_pod_event(event) -> Optional[dict]:
    """Normalize a k8s watch event (V1Pod or dict) to
    ``{type, name, replica_type, replica_index, phase, exit_code}``."""
    etype = event.get("type") if isinstance(event, dict) else event["type"]
    obj = event.get("object") if isinstance(event, dict) else None
    if obj is None:
        return None
    if isinstance(obj, dict):  # test path / raw dict from watch
        meta = obj.get("metadata", {})
        labels = meta.get("labels", {})
        name = meta.get("name", "")
        phase = obj.get("status", {}).get("phase", "")
        exit_code = obj.get("status", {}).get("exit_code")
    else:  # kubernetes V1Pod
        labels = obj.metadata.labels or {}
        name = obj.metadata.name
        phase = obj.status.phase if obj.status else ""
        exit_code = None
        statuses = (obj.status.container_statuses or []) if obj.status else []
        for cs in statuses:
            term = cs.state.terminated if cs.state else None
            if term is not None:
                exit_code = term.exit_code
    index = labels.get(ELASTICDL_REPLICA_INDEX_KEY)
    return {
        "type": etype,
        "name": name,
        "replica_type": labels.get(ELASTICDL_REPLICA_TYPE_KEY, ""),
        "replica_index": int(index) if index is not None else -1,
        "phase": phase,
        "exit_code": exit_code,
    }


class InstanceManager:
    def __init__(
        self,
        task_dispatcher,
        k8s_client,
        job_name: str,
        image_name: str,
        worker_command: Callable[[int], List[str]],
        num_workers: int = 1,
        namespace: str = "default",
        worker_resource_request: str = "cpu=1,memory=4096Mi",
        worker_resource_limit: str = "",
        volume: str = "",
        envs: Optional[Dict[str, str]] = None,
        restart_policy: str = "Never",
        owner: Optional[dict] = None,
        max_relaunches: int = 0,  # 0 = unlimited (reference relaunches
        # for the life of the job; task retries are capped instead)
        on_worker_relaunch: Optional[Callable[[int, int], None]] = None,
        multihost: bool = False,
        row_service_command: Optional[Callable[[int], List[str]]] = None,
        row_service_resource_request: str = "cpu=1,memory=4096Mi",
        row_service_resource_limit: str = "",
        num_row_service_shards: int = 1,
        journal=None,
    ):
        self._task_d = task_dispatcher
        self._client = k8s_client
        self._job_name = job_name
        self._image = image_name
        self._worker_command = worker_command
        self._num_workers = num_workers
        self._namespace = namespace
        self._resource_request = worker_resource_request
        self._resource_limit = worker_resource_limit
        self._volume = volume
        self._envs = envs or {}
        self._restart_policy = restart_policy
        self._owner = owner
        self._max_relaunches = max_relaunches
        self._on_worker_relaunch = on_worker_relaunch
        self._lock = threading.Lock()
        # live worker ids -> pod name; next id is monotonically fresh
        # (relaunched workers get NEW ids, reference :297-302).
        self._worker_pods: Dict[int, str] = {}
        self._next_worker_id = itertools.count(num_workers)
        self._relaunch_count = 0
        self._stopped = False
        # Multi-host jobs (jax.distributed) restart as a GANG: one death
        # invalidates every process's mesh, so all workers are deleted
        # and relaunched with their ORIGINAL ids (stable process ids;
        # docs/designs/multihost.md). Each gang generation gets a pod-
        # name suffix: k8s deletion is async, so recreating the same
        # name would 409, and the suffix also lets stale events for old
        # pods be recognized (name mismatch) instead of cascading.
        self._multihost = multihost
        self._generation = 0
        # Master write-ahead journal (master/journal.py): gang and
        # row-service relaunch generations append as ``relaunch``
        # records, so a recovered master adopts pods under their TRUE
        # (generation-suffixed) names instead of discarding their
        # death events as stale — the former "known limitation" in
        # docs/fault_tolerance.md.
        self._journal = journal
        # Host-tier row service (reference PS pod lifecycle: fixed
        # per-shard service names, relaunch on death —
        # k8s_instance_manager.py:303-308). One pod per shard (rows by
        # id % N client-side, row_service._ShardedTable); each shard's
        # state survives via its own checkpoint (row_service.py), which
        # the reference PS also relied on when re-init from workers
        # wasn't possible. ``row_service_command(shard)`` builds the
        # per-shard process command.
        self._row_service_command = row_service_command
        self._num_rs_shards = max(1, int(num_row_service_shards))
        # Dedicated sizing: the CPU-only row pods must not inherit the
        # workers' accelerator-sized resources (reference had its own
        # --ps_resource_* knobs).
        self._rs_resource_request = row_service_resource_request
        self._rs_resource_limit = row_service_resource_limit
        self._row_service_pods: Dict[int, str] = {}  # shard -> pod name
        self._rs_generation: Dict[int, int] = {}
        self._rs_relaunch_count = 0

    def _journal_relaunch(self, kind: str, generation: int,
                          shard: int = -1):
        """Persist a relaunch-generation bump BEFORE the replacement
        pod is created: a master crash between the bump and the
        create leaves the journal naming a pod that may not exist —
        harmless (its absence surfaces as watch events / straggler
        timeouts) — while the reverse order would leave a live pod
        the recovered master cannot recognize."""
        if self._journal is None:
            return
        try:
            self._journal.append(
                "relaunch", kind=str(kind),
                generation=int(generation), shard=int(shard),
            )
        except Exception as exc:
            # A fenced/failed append must not abort the relaunch path
            # (the pod plane is still this incarnation's to clean up);
            # the fencing rejection surfaces on the RPC plane.
            logger.warning("journal relaunch append failed: %s", exc)

    # ---- pod creation ---------------------------------------------------

    def _start_worker(self, worker_id: int):
        name = get_worker_pod_name(self._job_name, worker_id)
        if self._multihost and self._generation:
            name = f"{name}-g{self._generation}"
        manifest = build_pod_manifest(
            name=name,
            job_name=self._job_name,
            replica_type="worker",
            replica_index=worker_id,
            image=self._image,
            command=self._worker_command(worker_id),
            namespace=self._namespace,
            resource_request=self._resource_request,
            resource_limit=self._resource_limit,
            volume=self._volume,
            envs=self._envs,
            restart_policy=self._restart_policy,
            owner=self._owner,
        )
        self._client.create_pod(manifest)
        with self._lock:
            self._worker_pods[worker_id] = name
        logger.info("Started worker %d (%s)", worker_id, name)

    def start_workers(self):
        for worker_id in range(self._num_workers):
            self._start_worker(worker_id)

    # ---- master-restart adoption (master/journal.py recovery) ----------

    def adopt_workers(self, worker_ids, gang_generation: int = 0):
        """Track already-running worker pods instead of creating them
        (a recovered master re-attaches to the job it crashed out of).
        Pod names are reconstructed from the deterministic naming
        scheme; ids that died during the outage produce watch events /
        straggler timeouts against these names and recover through the
        normal dead-worker path. The fresh-id counter advances past
        every adopted id so relaunches never reuse one.

        ``gang_generation`` is the journal's replayed multihost
        gang-restart generation (``relaunch`` records): pods live
        under ``-gN``-suffixed names after a gang restart, and
        adopting them suffix-less would discard their death events as
        stale (the pre-journal known limitation)."""
        with self._lock:
            self._generation = max(self._generation,
                                   int(gang_generation))
            top = self._num_workers
            for wid in worker_ids:
                name = get_worker_pod_name(self._job_name, wid)
                if self._multihost and self._generation:
                    name = f"{name}-g{self._generation}"
                self._worker_pods[int(wid)] = name
                top = max(top, int(wid) + 1)
            self._next_worker_id = itertools.count(top)
        logger.info(
            "adopted %d running worker pod(s) after master restart "
            "(gang generation %d)",
            len(self._worker_pods), self._generation,
        )

    def adopt_row_service(self, generations: Optional[Dict[int, int]]
                          = None):
        """Track the (still-running) per-shard row-service pods after
        a master restart; their stable Services already exist.
        ``generations`` is the journal's replayed per-shard relaunch
        map (``relaunch`` records): a shard that relaunched before
        the crash lives under its bumped pod-name generation, and its
        next death is only detected when we track that name."""
        if self._row_service_command is None:
            return
        with self._lock:
            for shard, generation in (generations or {}).items():
                self._rs_generation[int(shard)] = max(
                    self._rs_generation.get(int(shard), 0),
                    int(generation),
                )
                # A shard ADDED after startup (add_row_service_shard
                # journals generation 0) lives beyond the configured
                # count; adopt it too, or its next death goes
                # undetected.
                self._num_rs_shards = max(
                    self._num_rs_shards, int(shard) + 1
                )
            for shard in range(self._num_rs_shards):
                self._row_service_pods[shard] = (
                    get_row_service_pod_name(
                        self._job_name,
                        self._rs_generation.get(shard, 0),
                        shard=shard,
                    )
                )
        logger.info(
            "adopted %d row-service pod(s) after master restart "
            "(relaunch generations %s)",
            self._num_rs_shards, dict(self._rs_generation),
        )

    # ---- row service (PS-pod lifecycle) --------------------------------

    def start_row_service(self):
        """Create the per-shard stable Services + serving pods."""
        if self._row_service_command is None:
            return
        for shard in range(self._num_rs_shards):
            self._client.create_service(
                build_row_service_service_manifest(
                    self._job_name, namespace=self._namespace,
                    shard=shard,
                )
            )
            self._start_row_service_pod(shard)

    def _start_row_service_pod(self, shard: int):
        with self._lock:
            if self._stopped:
                # A death event racing stop() must not recreate a pod
                # nothing will ever delete (same re-check the worker
                # relaunch path does).
                return
            name = get_row_service_pod_name(
                self._job_name, self._rs_generation.get(shard, 0),
                shard=shard,
            )
        manifest = build_pod_manifest(
            name=name,
            job_name=self._job_name,
            replica_type="rowservice",
            replica_index=shard,
            image=self._image,
            command=self._row_service_command(shard),
            namespace=self._namespace,
            resource_request=self._rs_resource_request,
            resource_limit=self._rs_resource_limit,
            volume=self._volume,
            envs=self._envs,
            restart_policy=self._restart_policy,
            owner=self._owner,
        )
        self._client.create_pod(manifest)
        with self._lock:
            self._row_service_pods[shard] = name
        logger.info("Started row service pod %s (shard %d)", name, shard)

    def _handle_dead_row_service(self, shard: int):
        """Same stable per-shard service name, fresh pod generation;
        workers ride the outage on their RPC retry/backoff (generous
        default budget, row_service.make_remote_engine) and the
        relaunched pod restores from its own checkpoint
        (row_service.py). Unlike workers, ANY failure relaunches: the
        service runs no user code, so the crash-loop concern behind the
        workers' exit-137-only policy does not apply; max_relaunches
        (when set) still bounds it (budget shared across shards)."""
        with self._lock:
            if self._stopped:
                return
            if self._max_relaunches and (
                self._rs_relaunch_count >= self._max_relaunches
            ):
                logger.error(
                    "Row service relaunch budget (%d) exhausted",
                    self._max_relaunches,
                )
                return
            self._rs_relaunch_count += 1
            self._rs_generation[shard] = (
                self._rs_generation.get(shard, 0) + 1
            )
            generation = self._rs_generation[shard]
        self._journal_relaunch("row_service", generation, shard=shard)
        logger.warning(
            "Row service shard %d pod died; relaunching "
            "(generation %d)", shard, generation,
        )
        self._start_row_service_pod(shard)

    def add_row_service_shard(self) -> Optional[int]:
        """Spawn one MORE row-service pod (stable Service + pod) under
        the next shard index — the autoscaler's pod-closing half of a
        live ``split`` (row_reshard.ShardMapController): the pod must
        exist and serve before the shard map routes ranges to it.
        Journaled as a generation-0 relaunch record BEFORE the create
        (the same order every relaunch uses), so a recovered master
        adopts the grown fleet instead of forgetting the extra pod.
        Returns the new shard index, or None when row service is off
        or the manager is stopped."""
        if self._row_service_command is None:
            return None
        with self._lock:
            if self._stopped:
                return None
            shard = self._num_rs_shards
            self._num_rs_shards += 1
            self._rs_generation.setdefault(shard, 0)
        self._journal_relaunch(
            "row_service", self._rs_generation.get(shard, 0),
            shard=shard,
        )
        self._client.create_service(
            build_row_service_service_manifest(
                self._job_name, namespace=self._namespace, shard=shard,
            )
        )
        self._start_row_service_pod(shard)
        logger.info("scaled up row service: added shard %d", shard)
        return shard

    def drain_row_service_shard(self, shard: int) -> bool:
        """Tear down one row-service pod + its Service WITHOUT
        relaunching — the pod-closing half of a completed ``merge``:
        call only AFTER the shard-map controller retired the shard
        (tick() returned ``retire:N``), i.e. the map no longer routes
        any range here and every row moved off. Untracked before
        deletion so the DELETED watch event matches nothing and the
        dead-row-service relaunch path never fires (the drain_worker
        pattern). Returns False when the shard is not tracked."""
        shard = int(shard)
        with self._lock:
            name = self._row_service_pods.pop(shard, None)
            if name is None:
                return False
            self._rs_generation.pop(shard, None)
            # Shrink the count only from the top — interior indices
            # stay burned (shard ids never recycle, like worker ids).
            while (self._num_rs_shards > 1
                   and (self._num_rs_shards - 1)
                   not in self._row_service_pods):
                self._num_rs_shards -= 1
        try:
            self._client.delete_pod(name)
        except Exception as exc:
            logger.warning("deleting drained row-service pod %s "
                           "failed: %s", name, exc)
        try:
            self._client.delete_service(
                get_row_service_service_name(self._job_name,
                                             shard=shard)
            )
        except Exception as exc:
            logger.warning("deleting drained row-service service "
                           "(shard %d) failed: %s", shard, exc)
        logger.info("drained row service shard %d (%s)", shard, name)
        return True

    def row_service_shards(self) -> Dict[int, str]:
        """shard -> tracked pod name (the pod scaler's view)."""
        with self._lock:
            return dict(self._row_service_pods)

    # ---- event handling -------------------------------------------------

    def _event_cb(self, event):
        """k8s watch callback (reference :219-308)."""
        info = classify_pod_event(event)
        if info is None:
            return
        if info["replica_type"] == "rowservice":
            dead = info["type"] == "DELETED" or info["phase"] == "Failed"
            with self._lock:
                # Map the event back to its shard by tracked pod name
                # (stale generations mismatch and are ignored, same as
                # the worker path).
                shard = next(
                    (
                        s for s, pod in self._row_service_pods.items()
                        if pod == info["name"]
                    ),
                    None,
                )
            if dead and shard is not None:
                self._handle_dead_row_service(shard)
            return
        if info["replica_type"] != "worker":
            return
        worker_id = info["replica_index"]
        # Relaunch only involuntary deaths: DELETED (preempted pod) or
        # Failed with exit 137 (SIGKILL/OOM). A worker that failed on its
        # own exit code crashed on user code — relaunching would loop
        # (reference :250-271).
        dead = info["type"] == "DELETED" or (
            info["phase"] == "Failed" and info["exit_code"] == _EXIT_KILLED
        )
        if not dead:
            return
        with self._lock:
            if self._stopped or worker_id not in self._worker_pods:
                return
            if self._worker_pods[worker_id] != info["name"]:
                # Stale event for a previous generation's pod (e.g. the
                # deletions a gang restart itself caused) — the tracked
                # pod is a newer one with a different name.
                return
            del self._worker_pods[worker_id]
        self._handle_dead_worker(worker_id)

    def _handle_dead_worker(self, worker_id: int):
        if self._multihost:
            self._handle_dead_worker_multihost(worker_id)
            return
        _observe("worker_dead", worker_id=worker_id)
        requeued = self._task_d.recover_tasks(worker_id)
        logger.info(
            "Worker %d died; re-queued %s task(s)", worker_id, requeued
        )
        with self._lock:
            # Re-check under the lock: a concurrent stop() may have run
            # since the event was classified — relaunching now would leak
            # a pod nothing will ever delete.
            if self._stopped:
                return
            if self._max_relaunches and (
                self._relaunch_count >= self._max_relaunches
            ):
                logger.warning(
                    "Relaunch budget (%d) exhausted; not replacing "
                    "worker %d", self._max_relaunches, worker_id,
                )
                return
            self._relaunch_count += 1
            new_id = next(self._next_worker_id)
        self._start_worker(new_id)
        _observe("worker_relaunched", worker_id=worker_id, new_id=new_id)
        if self._on_worker_relaunch is not None:
            self._on_worker_relaunch(worker_id, new_id)

    def _handle_dead_worker_multihost(self, worker_id: int):
        """Gang restart: one dead process invalidates every process's
        jax.distributed mesh, so delete ALL workers and relaunch the
        full set with their original ids (process ids must be stable)
        under a new pod-name generation. Workers resume from the rolling
        checkpoint (worker/main.py resolve_init_checkpoint)."""
        # The dead worker's tasks always re-queue, even when the budget
        # is spent — stuck `doing` tasks would hang the job forever.
        self._task_d.recover_tasks(worker_id)
        with self._lock:
            if self._stopped:
                return
            if self._max_relaunches and (
                self._relaunch_count >= self._max_relaunches
            ):
                logger.warning(
                    "Relaunch budget (%d) exhausted; not gang-"
                    "restarting after worker %d died",
                    self._max_relaunches, worker_id,
                )
                return
            self._relaunch_count += 1
            self._generation += 1
            generation = self._generation
            live = dict(self._worker_pods)
            live.pop(worker_id, None)
            self._worker_pods.clear()
        self._journal_relaunch("gang", generation)
        logger.info(
            "Multi-host gang restart (generation %d): worker %d died; "
            "deleting %d peer(s), relaunching all %d with original ids",
            self._generation, worker_id, len(live), self._num_workers,
        )
        for wid, pod_name in live.items():
            self._task_d.recover_tasks(wid)
            try:
                self._client.delete_pod(pod_name)
            except Exception as exc:
                logger.warning("deleting %s failed: %s", pod_name, exc)
        for wid in range(self._num_workers):
            self._start_worker(wid)
        if self._on_worker_relaunch is not None:
            self._on_worker_relaunch(worker_id, worker_id)

    # ---- elastic scaling (master/autoscaler.py) -------------------------

    def scale_up(self, count: int = 1) -> List[int]:
        """Add ``count`` workers under fresh ids (the same id scheme
        relaunches use — ids never recycle). Returns the new ids."""
        new_ids = []
        for _ in range(max(0, int(count))):
            with self._lock:
                if self._stopped:
                    break
                new_id = next(self._next_worker_id)
            self._start_worker(new_id)
            new_ids.append(new_id)
        if new_ids:
            logger.info("scaled up: started worker(s) %s", new_ids)
        return new_ids

    def drain_worker(self, worker_id: int) -> bool:
        """Scale-down: remove ``worker_id`` WITHOUT relaunching it.

        The pod is untracked before deletion, so its DELETED watch
        event matches nothing and the ``_handle_dead_worker`` relaunch
        path never fires — the one behavioral difference from a death.
        Its in-flight tasks re-queue exactly once here: if the worker's
        SIGTERM grace also hands a task back, the dispatcher's resolved
        ledger answers that late report with the original requeue
        outcome instead of double-queueing. Returns False when the id
        is not live."""
        with self._lock:
            name = self._worker_pods.pop(worker_id, None)
        if name is None:
            return False
        _observe("worker_drained", worker_id=worker_id)
        # Fence BEFORE the pod deletion: the dying worker keeps polling
        # through its SIGTERM grace, and a fresh lease taken after this
        # point would have no death event to recover it (the DELETED
        # event is deliberately ignored below).
        fence = getattr(self._task_d, "fence_worker", None)
        if fence is not None:
            fence(worker_id)
        try:
            self._client.delete_pod(name)
        except Exception as exc:
            logger.warning("deleting drained pod %s failed: %s",
                           name, exc)
        requeued = self._task_d.recover_tasks(worker_id)
        logger.info(
            "drained worker %d (%s); re-queued %s task(s)",
            worker_id, name, requeued,
        )
        return True

    # ---- straggler handling ---------------------------------------------

    def kill_worker(self, worker_id: int):
        """Delete a stuck worker's pod; the DELETED event then triggers
        recovery (reference master.py:487-509 timeout path). If the pod
        is already gone (delete returns None on 404 — e.g. it was
        preempted during a watch-stream reconnect gap, whose DELETED
        event was lost), run the dead-worker path directly: without this
        the task would sit in `doing` forever and the job would hang."""
        with self._lock:
            name = self._worker_pods.get(worker_id)
        if name is None:
            return
        _observe("kill_worker", worker_id=worker_id)
        result = self._client.delete_pod(name)
        if result is None:
            with self._lock:
                if worker_id not in self._worker_pods:
                    return
                del self._worker_pods[worker_id]
            self._handle_dead_worker(worker_id)

    # ---- lifecycle ------------------------------------------------------

    def start_watch(self):
        thread = threading.Thread(
            target=self._client.watch_job_pods,
            args=(self._job_name, self._event_cb),
            kwargs={"stop": lambda: self._stopped},
            daemon=True,
        )
        thread.start()
        return thread

    def stop(self):
        with self._lock:
            self._stopped = True
            pods = list(self._worker_pods.values())
            self._worker_pods.clear()
            pods.extend(self._row_service_pods.values())
            self._row_service_pods.clear()
        for name in pods:
            self._client.delete_pod(name)

    @property
    def live_workers(self) -> Dict[int, str]:
        with self._lock:
            return dict(self._worker_pods)
