"""Streaming ingestion: pump a live stream tail into the task
dispatcher (docs/online_learning.md).

The batch control plane walks a finite shard table per epoch; this
module replaces that walk for online / continual learning. A
``StreamIngestor`` bridges one ``data/stream.py`` source to one
streaming-mode ``TaskDispatcher``:

- **unbounded task generation**: each ``pump()`` tails every
  partition's high-water mark and queues offset-ranged TRAINING tasks
  for the new records (``dispatcher.create_stream_tasks`` — journaled,
  so replay rebuilds the identical todo queue);
- **backpressure**: task generation pauses while the todo queue holds
  ``max_todo`` or more tasks — a lagging worker fleet bounds master
  memory instead of growing it, and the stall is metered
  (``stream_ingest_backpressure_seconds``);
- **watermark accounting**: the committed watermark per partition
  (folded from REPORT records — see ``journal.advance_stream_watermark``)
  is compared against the tail to publish
  ``stream_ingest_watermark_lag_seconds`` and
  ``stream_ingest_offsets_committed_total``; the
  ``stream-watermark-stall`` SLO rule (observability/slo.py) burns on
  the lag gauge;
- **watermark-triggered eval**: every ``eval_every_records`` committed
  records the evaluation service opens a round
  (``EvaluationService.add_watermark_eval_if_needed``) — the streaming
  replacement for epoch-end eval.

Crash/preemption resume needs NO code here: the dispatcher's stream
state (committed watermarks + the ``next`` generation cursor) rides
its journal snapshots and REPORT/STREAM records, so a recovered
master's ingestor simply continues pumping from the restored cursors —
offsets below the committed watermark are never re-tasked and never
re-acked. ``chaos/stream_drill.py`` kills a worker AND a row shard in
one window to prove it.
"""

import threading
import time
from typing import Dict, Optional

from elasticdl_tpu.common.log_utils import get_logger
from elasticdl_tpu.data.stream import StreamSource

logger = get_logger("stream_ingest")


class StreamIngestor:
    """Pump loop from one ``StreamSource`` into one streaming
    ``TaskDispatcher`` (see module docstring)."""

    def __init__(
        self,
        source: StreamSource,
        dispatcher,
        max_todo: int = 64,
        eval_service=None,
        eval_every_records: int = 0,
        model_version_fn=None,
        metrics_registry=None,
    ):
        self._source = source
        self._dispatcher = dispatcher
        self._max_todo = max(1, int(max_todo))
        self._eval_service = eval_service
        self._eval_every_records = int(eval_every_records)
        self._model_version_fn = model_version_fn
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread = None
        self._last_pump = None  # monotonic time of the previous pump
        self._backpressured = False
        self._backpressure_total = 0.0
        self._lag_seconds: Dict[str, float] = {}
        self._committed_seen: Dict[str, int] = {}

        from elasticdl_tpu.observability import default_registry

        registry = metrics_registry or default_registry()
        self._m_lag = registry.gauge(
            "stream_ingest_watermark_lag_seconds",
            "Age of the oldest uncommitted stream record per partition",
            ["partition"],
        )
        self._m_committed = registry.counter(
            "stream_ingest_offsets_committed_total",
            "Stream offsets durably committed (watermark advances)",
            ["partition"],
        )
        self._m_backpressure = registry.counter(
            "stream_ingest_backpressure_seconds",
            "Cumulative seconds task generation was paused because "
            "the todo queue held max_todo tasks (worker fleet lagging)",
        )
        if eval_service is not None and self._eval_every_records > 0:
            # Seed the marker with the recovered committed total so a
            # master restart does not fire one round per historical
            # threshold crossing.
            eval_service.configure_watermark_eval(
                self._eval_every_records,
                start_at=self._committed_total(),
            )

    # ---- accounting ----------------------------------------------------

    def _committed_total(self) -> int:
        return sum(
            int(part["committed"])
            for part in self._dispatcher.stream_progress().values()
        )

    def _model_version(self) -> int:
        if self._model_version_fn is None:
            return -1
        return int(self._model_version_fn())

    # ---- the pump ------------------------------------------------------

    def pump(self) -> dict:
        """One ingestion pass; safe to call from a drill loop or the
        background thread. Returns a summary dict (tasks generated,
        backpressure verdict, per-partition lag)."""
        now = time.monotonic()
        with self._lock:
            elapsed = (
                now - self._last_pump
                if self._last_pump is not None else 0.0
            )
            self._last_pump = now
            if self._backpressured and elapsed > 0:
                # The PREVIOUS pass found the queue full: everything
                # since then was stall time, whether or not this pass
                # unblocks.
                self._backpressure_total += elapsed
                self._m_backpressure.inc(elapsed)

            generated = 0
            blocked = False
            progress = self._dispatcher.stream_progress()
            for partition in self._source.partitions():
                self._dispatcher.register_stream_partition(partition)
                end = int(self._source.end_offset(partition))
                cursor = int(
                    progress.get(partition, {}).get("next", 0)
                )
                if end <= cursor:
                    continue
                todo, _doing = self._dispatcher.queue_depths()
                budget = self._max_todo - todo
                if budget <= 0:
                    blocked = True
                    continue
                per_task = self._dispatcher._records_per_task
                stop = min(end, cursor + budget * per_task)
                generated += self._dispatcher.create_stream_tasks(
                    partition, cursor, stop,
                    model_version=self._model_version(),
                )
                if stop < end:
                    blocked = True
            self._backpressured = blocked

            # Watermark telemetry from the post-generation state.
            progress = self._dispatcher.stream_progress()
            wall = time.time()
            for partition, part in progress.items():
                committed = int(part["committed"])
                end = int(self._source.end_offset(partition))
                if committed < end:
                    appended = self._source.append_time(
                        partition, committed
                    )
                    lag = max(0.0, wall - appended) if appended else 0.0
                else:
                    lag = 0.0
                self._lag_seconds[partition] = lag
                self._m_lag.labels(partition).set(lag)
                seen = self._committed_seen.get(partition, 0)
                if committed > seen:
                    self._m_committed.labels(partition).inc(
                        committed - seen
                    )
                    self._committed_seen[partition] = committed

        if self._eval_service is not None \
                and self._eval_every_records > 0:
            # Outside the ingestor lock: opening a round takes the
            # eval service's lock and appends to the journal.
            self._eval_service.add_watermark_eval_if_needed(
                self._committed_total(),
                model_version=self._model_version(),
            )
        return {
            "generated": generated,
            "backpressured": blocked,
            "lag_seconds": dict(self._lag_seconds),
        }

    # ---- lifecycle -----------------------------------------------------

    def start(self, interval_secs: float = 0.5):
        """Run ``pump`` on a daemon thread every ``interval_secs``."""
        if self._thread is not None:
            return
        self._stop.clear()

        def _loop():
            while not self._stop.wait(interval_secs):
                try:
                    self.pump()
                except Exception:
                    logger.exception("stream pump failed; continuing")

        self._thread = threading.Thread(
            target=_loop, name="stream-ingest", daemon=True
        )
        self._thread.start()

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None

    def close(self):
        """Retire the stream: stop pumping and let the dispatcher's
        ``finished`` fire once the queues drain."""
        self.stop()
        self._dispatcher.close_stream()

    # ---- introspection -------------------------------------------------

    @property
    def backpressure_seconds(self) -> float:
        return self._backpressure_total

    def render(self) -> dict:
        """The ``/stream`` endpoint body (master/main.py mounts it next
        to ``/sched``; ``tools/dump_metrics.py --stream`` renders it)."""
        progress = self._dispatcher.stream_progress()
        partitions = {}
        for partition, part in sorted(progress.items()):
            end = int(self._source.end_offset(partition))
            committed = int(part["committed"])
            partitions[partition] = {
                "end": end,
                "committed": committed,
                "next": int(part["next"]),
                "pending_ranges": len(part.get("pending") or {}),
                "lag_records": max(0, end - committed),
                "watermark_lag_seconds": float(
                    self._lag_seconds.get(partition, 0.0)
                ),
            }
        return {
            "partitions": partitions,
            "backpressure_seconds": float(self._backpressure_total),
            "backpressured": bool(self._backpressured),
            "max_todo": int(self._max_todo),
            "eval_every_records": int(self._eval_every_records),
        }
