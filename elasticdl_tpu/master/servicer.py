"""Master service handlers.

Counterpart of the reference's ``master/servicer.py`` (MasterServicer): the
four control RPCs — get_task, report_task_result, report_evaluation_metrics,
report_version — plus worker-liveness and mean-task-time tracking used for
timeout-based straggler detection (reference servicer.py:107-124).

Handlers take/return plain dicts (see comm/rpc.py); ``InProcessMaster`` in
testing/ calls them directly, the RpcServer serves them over gRPC.

Tracing: over RPC each handler already runs under a ``serve/<method>``
server span (comm/rpc.py); the dispatcher adds its own ``dispatch``
span inside get_task, and the eval-metrics fold — the one handler
doing real compute — gets an ``eval_report`` span here. Piggybacked
worker spans ride the ``metrics`` snapshots and are popped into the
plane's TraceCollector by ``MetricsPlane.ingest``.
"""

import threading
import time
from typing import Dict

from elasticdl_tpu.common.constants import TaskType
from elasticdl_tpu.common.log_utils import get_logger
from elasticdl_tpu.common.task import Task
from elasticdl_tpu.observability import tracing

logger = get_logger("master_servicer")

SERVICE_NAME = "elasticdl_tpu.Master"


class MasterServicer:
    def __init__(self, task_dispatcher, evaluation_service=None,
                 task_timeout_secs: float = 300.0, metrics_plane=None,
                 journal=None, generation: int = 0):
        from elasticdl_tpu.observability import MetricsPlane

        self._task_d = task_dispatcher
        self._eval_service = evaluation_service
        # Master incarnation fence (master/journal.py): stamped on every
        # get_task response so workers detect a restart and re-attach;
        # reports carry the generation their task was dispatched under,
        # and ones referencing a task the recovered master re-queued
        # are fenced (accepted=False) instead of double-applied.
        self._journal = journal
        self.generation = int(generation)
        # Cluster telemetry: workers piggyback registry snapshots on the
        # RPCs below; the plane merges them keyed by worker id and ages
        # out workers that stop reporting (elastic resize / preemption).
        self.metrics_plane = metrics_plane or MetricsPlane()
        self._m_straggler = self.metrics_plane.registry.counter(
            "master_straggler_timeouts_total",
            "Tasks that blew the straggler deadline (factor x mean)",
        )
        self._m_reattach = self.metrics_plane.registry.counter(
            "master_worker_reattach_total",
            "Workers that re-registered after a master restart "
            "(their last-seen generation predates ours)",
        )
        self._lock = threading.Lock()
        self._worker_liveness: Dict[int, float] = {}
        # Workers already counted as re-attached to this generation.
        self._reattached = set()
        # Task ids already counted as stragglers (pruned against the
        # doing set so re-queued ids can be counted again).
        self._straggler_counted = set()
        # Running mean of task duration, for straggler detection
        # (reference servicer.py:107-121: default 300s until enough data).
        self._default_task_secs = task_timeout_secs
        self._task_secs_sum = 0.0
        self._task_count = 0
        self._task_start_times: Dict[int, float] = {}
        self.model_version = 0

    # ---- handler table -------------------------------------------------

    def handlers(self):
        return {
            "get_task": self.get_task,
            "report_task_result": self.report_task_result,
            "report_evaluation_metrics": self.report_evaluation_metrics,
            "report_version": self.report_version,
            "ping": lambda req: {"ok": True},
        }

    # ---- RPC handlers --------------------------------------------------

    def _ingest_metrics(self, worker_id: int, request: dict):
        snapshot = request.get("metrics")
        if snapshot:
            self.metrics_plane.ingest(worker_id, snapshot)

    def _note_worker_generation(self, worker_id: int, request: dict):
        """Re-attach detection: a worker reporting a last-seen
        generation below ours rode out a master restart."""
        seen = request.get("generation")
        if (seen is None or worker_id < 0
                # seen < 0 = a fresh worker that never attached to any
                # incarnation — an arrival, not a re-attach.
                or int(seen) < 0 or int(seen) >= self.generation):
            return
        with self._lock:
            fresh = worker_id not in self._reattached
            self._reattached.add(worker_id)
        if fresh:
            self._m_reattach.inc()
            logger.info(
                "worker %d re-attached (knew generation %s, now %d)",
                worker_id, seen, self.generation,
            )

    def get_task(self, request: dict) -> dict:
        worker_id = int(request.get("worker_id", -1))
        self._record_liveness(worker_id)
        self._ingest_metrics(worker_id, request)
        self._note_worker_generation(worker_id, request)
        task = self._task_d.get(worker_id)
        if task is not None:
            with self._lock:
                self._task_start_times[task.task_id] = time.time()
            return {"task": task.to_dict(), "finished": False,
                    "generation": self.generation}
        if self._task_d.finished():
            return {"task": None, "finished": True,
                    "generation": self.generation}
        # Queue temporarily empty (doing tasks may re-queue on failure):
        # tell the worker to wait (reference servicer.py:60-68).
        wait = Task(task_id=-1, type=TaskType.WAIT)
        return {"task": wait.to_dict(), "finished": False,
                "generation": self.generation}

    def report_task_result(self, request: dict) -> dict:
        task_id = int(request["task_id"])
        err_reason = request.get("err_reason", "")
        success = not err_reason
        worker_id = int(request.get("worker_id", -1))
        self._ingest_metrics(worker_id, request)
        self._note_worker_generation(worker_id, request)
        with self._lock:
            start = self._task_start_times.pop(task_id, None)
        # The duplicate flag is decided atomically with the report
        # application (dispatcher lock): a ledger hit means the side
        # effects below already ran on the first application — only
        # the outcome is re-sent. A pre-check here would race a
        # concurrent retry of the same report.
        task, _worker, requeued, duplicate = self._task_d.apply_report(
            task_id, success, err_reason
        )
        if (task is not None and success and start is not None
                and not duplicate):
            # First applications only: a straggler's late report (its
            # task already requeued, outcome ledger-answered) would
            # otherwise fold its pathological hold time into the mean
            # the straggler deadline derives from.
            with self._lock:
                self._task_secs_sum += time.time() - start
                self._task_count += 1
        if task is None:
            # Unknown AND not in the ledger: a report fenced to a dead
            # generation whose task the recovered master re-queued (or
            # a genuinely bogus id) — reject so the re-dispatched copy
            # is the only one that counts.
            return {"accepted": False, "fenced": True,
                    "generation": self.generation}
        # An eval task counts toward its EvaluationJob when it succeeds OR
        # fails permanently (dropped after retry cap) — otherwise one bad
        # eval shard would wedge the evaluation service forever.
        if (
            not duplicate
            and not requeued
            and task.type == TaskType.EVALUATION
            and self._eval_service is not None
        ):
            self._eval_service.complete_task(task.model_version)
        return {"accepted": True, "generation": self.generation}

    def report_evaluation_metrics(self, request: dict) -> dict:
        if self._eval_service is None:
            return {"accepted": False}
        # The one handler that does real compute (metric fold over raw
        # output arrays) — span it so a slow eval fold is attributable
        # in the task timeline rather than reading as RPC time.
        outputs = request["model_outputs"]
        rows = getattr(outputs, "shape", None)
        with tracing.span(
            "eval_report", outputs=int(rows[0]) if rows else len(outputs),
        ):
            ok = self._eval_service.report_evaluation_metrics(
                outputs, request["labels"],
                # Dedup key: the fold is a plain accumulate, so a
                # retried send must not double-count its samples.
                task_id=int(request.get("task_id", -1)),
            )
        return {"accepted": ok, "generation": self.generation}

    def report_version(self, request: dict) -> dict:
        version = int(request["model_version"])
        worker_id = int(request.get("worker_id", -1))
        self._record_liveness(worker_id)
        self._ingest_metrics(worker_id, request)
        with self._lock:
            advanced = version > self.model_version
            self.model_version = max(self.model_version, version)
        if advanced and self._journal is not None:
            # Model-version high-water mark: recovery re-arms eval
            # triggering and TensorBoard publishing from it.
            self._journal.append("version", model_version=version)
        self._task_d.record_worker_version(worker_id, version)
        if self._eval_service is not None:
            self._eval_service.add_evaluation_task_if_needed(version)
        return {"ok": True, "generation": self.generation}

    # ---- liveness / straggler detection --------------------------------

    def _record_liveness(self, worker_id: int):
        if worker_id >= 0:
            with self._lock:
                self._worker_liveness[worker_id] = time.time()

    def worker_liveness(self) -> Dict[int, float]:
        with self._lock:
            return dict(self._worker_liveness)

    def average_task_secs(self) -> float:
        with self._lock:
            if self._task_count < 3:
                return self._default_task_secs
            return self._task_secs_sum / self._task_count

    def find_timeout_tasks(self, factor: float = 3.0):
        """(task_id, worker_id) pairs running > factor × mean task time
        (reference master.py:487-509 _check_timeout_tasks)."""
        threshold = factor * self.average_task_secs()
        now = time.time()
        out = []
        doing = self._task_d.doing_start_times()
        for task_id, (worker_id, start) in doing.items():
            if now - start > threshold:
                out.append((task_id, worker_id))
        with self._lock:
            # Count each straggling task once, not once per poll tick —
            # in k8s mode kill_worker recovery is async (the pod DELETED
            # watch event), so a timed-out task stays in the doing set
            # for several ticks before it is re-queued.
            self._straggler_counted &= set(doing)
            fresh = [t for t, _w in out if t not in self._straggler_counted]
            self._straggler_counted.update(fresh)
        if fresh:
            self._m_straggler.inc(len(fresh))
        return out

    def seed_task_start_times(self, task_ids):
        """Recovery: start the straggler clock now for every lease
        that survived the master crash (the pre-crash start times died
        with the old process; counting from recovery avoids instantly
        timing out every surviving worker)."""
        now = time.time()
        with self._lock:
            for tid in task_ids:
                self._task_start_times[int(tid)] = now

    def remove_worker_metrics(self, worker_id: int):
        """Drop a departed worker from the cluster view immediately
        (recovery / elastic scale-down path) instead of waiting for the
        report TTL."""
        self.metrics_plane.cluster.remove_worker(worker_id)
        with self._lock:
            self._worker_liveness.pop(worker_id, None)
