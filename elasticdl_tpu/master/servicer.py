"""Master service handlers.

Counterpart of the reference's ``master/servicer.py`` (MasterServicer): the
four control RPCs — get_task, report_task_result, report_evaluation_metrics,
report_version — plus worker-liveness and mean-task-time tracking used for
timeout-based straggler detection (reference servicer.py:107-124).

Handlers take/return plain dicts (see comm/rpc.py); ``InProcessMaster`` in
testing/ calls them directly, the RpcServer serves them over gRPC.

Tracing: over RPC each handler already runs under a ``serve/<method>``
server span (comm/rpc.py); the dispatcher adds its own ``dispatch``
span inside get_task, and the eval-metrics fold — the one handler
doing real compute — gets an ``eval_report`` span here. Piggybacked
worker spans ride the ``metrics`` snapshots and are popped into the
plane's TraceCollector by ``MetricsPlane.ingest``.
"""

import threading
import time
from typing import Dict, Optional

from elasticdl_tpu.common.constants import TaskType
from elasticdl_tpu.common.log_utils import get_logger
from elasticdl_tpu.common.task import Task
from elasticdl_tpu.observability import tracing

logger = get_logger("master_servicer")

SERVICE_NAME = "elasticdl_tpu.Master"


class MasterServicer:
    def __init__(self, task_dispatcher, evaluation_service=None,
                 task_timeout_secs: float = 300.0, metrics_plane=None,
                 journal=None, generation: int = 0, scheduler=None):
        from elasticdl_tpu.observability import MetricsPlane

        self._task_d = task_dispatcher
        self._eval_service = evaluation_service
        # Multi-job mode (master/scheduler.py): when a GangScheduler is
        # attached, get_task routes through its worker->job binding and
        # every lease/report is job-scoped; without one, behavior is
        # the single-job plane unchanged.
        self._scheduler = scheduler
        # Master incarnation fence (master/journal.py): stamped on every
        # get_task response so workers detect a restart and re-attach;
        # reports carry the generation their task was dispatched under,
        # and ones referencing a task the recovered master re-queued
        # are fenced (accepted=False) instead of double-applied.
        self._journal = journal
        self.generation = int(generation)
        # Cluster telemetry: workers piggyback registry snapshots on the
        # RPCs below; the plane merges them keyed by worker id and ages
        # out workers that stop reporting (elastic resize / preemption).
        self.metrics_plane = metrics_plane or MetricsPlane()
        self._m_straggler = self.metrics_plane.registry.counter(
            "master_straggler_timeouts_total",
            "Tasks that blew the straggler deadline (factor x mean)",
        )
        self._m_reattach = self.metrics_plane.registry.counter(
            "master_worker_reattach_total",
            "Workers that re-registered after a master restart "
            "(their last-seen generation predates ours)",
        )
        self._m_fenced = self.metrics_plane.registry.counter(
            "master_fenced_requests_total",
            "RPCs rejected because this incarnation was fenced by a "
            "hot-standby takeover (split-brain guard)", ["method"],
        )
        self._lock = threading.Lock()
        self._worker_liveness: Dict[int, float] = {}
        # Workers already counted as re-attached to this generation.
        self._reattached = set()
        # Task ids already counted as stragglers (pruned against the
        # doing set so re-queued ids can be counted again).
        self._straggler_counted = set()
        # Running mean of task duration, for straggler detection
        # (reference servicer.py:107-121: default 300s until enough data).
        self._default_task_secs = task_timeout_secs
        self._task_secs_sum = 0.0
        self._task_count = 0
        # Keyed (job, task_id): per-job dispatchers number task ids
        # independently, so a bare int key would collide across jobs
        # in scheduler mode. The single-job plane uses job "".
        self._task_start_times: Dict[tuple, float] = {}
        self.model_version = 0
        # ---- live-resize barrier (docs/elasticity.md) ----------------
        # At most one pending resize: {resize_id, spec, direction,
        # expected (worker-id set), acks {worker_id: status}, t0}.
        # Directives piggyback on get_task responses (like the
        # generation fence); workers apply at a task boundary and ack
        # via report_resize; the barrier completes when every expected
        # worker acked — membership shrinks via maybe_complete_resize
        # when a worker dies mid-barrier (its replacement sees the
        # still-pending directive on its first get_task). Journaled
        # like dispatch: begin/done records survive a master crash, and
        # a recovered master re-offers the pending directive (acks are
        # volatile; the worker-side apply is idempotent by resize_id).
        self._resize: Optional[dict] = None
        self._next_resize_id = 0
        self._m_resize = self.metrics_plane.registry.counter(
            "master_resize_total",
            "Live mesh-resize barriers begun", ["direction"],
        )
        self._m_resize_pending = self.metrics_plane.registry.gauge(
            "master_resize_pending",
            "1 while a resize barrier is awaiting worker acks",
        )
        self._m_resize_barrier = self.metrics_plane.registry.histogram(
            "master_resize_barrier_seconds",
            "Resize barrier latency: begin_resize to last worker ack",
        )

    # ---- handler table -------------------------------------------------

    def handlers(self):
        return {
            "get_task": self.get_task,
            "report_task_result": self.report_task_result,
            "report_evaluation_metrics": self.report_evaluation_metrics,
            "report_version": self.report_version,
            "report_resize": self.report_resize,
            "report_metrics": self.report_metrics,
            "submit_job": self.submit_job,
            "sched_status": self.sched_status,
            "ping": lambda req: {"ok": True},
        }

    # ---- RPC handlers --------------------------------------------------

    def _ingest_metrics(self, worker_id: int, request: dict):
        snapshot = request.get("metrics")
        if snapshot:
            self.metrics_plane.ingest(worker_id, snapshot)

    def _stale_master_reject(self, method: str) -> Optional[dict]:
        """Fencing pre-check (master/journal.py hot-standby takeover):
        once a newer incarnation fenced this one, every state-mutating
        handler must reject — a zombie primary that kept dispatching
        or acking would fork the job's truth. The journal append
        itself is the authoritative guard (it re-checks under the
        flock and raises); this pre-check turns that hard error into a
        clean ``stale_master`` response workers re-resolve on."""
        if self._journal is None or not self._journal.is_fenced():
            return None
        self._m_fenced.labels(method).inc()
        logger.error(
            "FENCED: %s rejected — this master (generation %d) was "
            "superseded by a hot-standby takeover (fence generation "
            "%d); refusing to serve", method, self.generation,
            self._journal.fence_generation(),
        )
        return {"accepted": False, "fenced": True, "stale_master": True,
                "task": None, "finished": False,
                "generation": self.generation}

    def _note_worker_generation(self, worker_id: int, request: dict):
        """Re-attach detection: a worker reporting a last-seen
        generation below ours rode out a master restart."""
        seen = request.get("generation")
        if (seen is None or worker_id < 0
                # seen < 0 = a fresh worker that never attached to any
                # incarnation — an arrival, not a re-attach.
                or int(seen) < 0 or int(seen) >= self.generation):
            return
        with self._lock:
            fresh = worker_id not in self._reattached
            self._reattached.add(worker_id)
        if fresh:
            self._m_reattach.inc()
            logger.info(
                "worker %d re-attached (knew generation %s, now %d)",
                worker_id, seen, self.generation,
            )

    def get_task(self, request: dict) -> dict:
        fenced = self._stale_master_reject("get_task")
        if fenced is not None:
            return fenced
        worker_id = int(request.get("worker_id", -1))
        self._record_liveness(worker_id)
        self._ingest_metrics(worker_id, request)
        self._note_worker_generation(worker_id, request)
        extra = {}
        offer = self._resize_offer(worker_id)
        if offer is not None:
            # Piggybacked like the generation fence: WAIT responses
            # carry it too, so an idle worker still joins the barrier.
            extra["resize"] = offer
        if self._scheduler is not None:
            # Multi-job: the gang scheduler decides which job this
            # worker slot serves right now; the lease carries the job
            # id so the worker's report routes back to the same
            # dispatcher (and so a post-preemption rebinding cannot
            # mis-apply a stale report to the new job).
            job_id, disp = self._scheduler.lease_for(worker_id)
            if disp is not None:
                task = disp.get(worker_id)
                if task is not None:
                    with self._lock:
                        self._task_start_times[
                            (job_id, task.task_id)
                        ] = time.time()
                    return {"task": task.to_dict(), "finished": False,
                            "job": job_id,
                            "generation": self.generation, **extra}
            if self._scheduler.idle() and self._task_d.finished():
                return {"task": None, "finished": True,
                        "generation": self.generation, **extra}
            wait = Task(task_id=-1, type=TaskType.WAIT)
            return {"task": wait.to_dict(), "finished": False,
                    "generation": self.generation, **extra}
        task = self._task_d.get(worker_id)
        if task is not None:
            with self._lock:
                self._task_start_times[("", task.task_id)] = time.time()
            return {"task": task.to_dict(), "finished": False,
                    "generation": self.generation, **extra}
        if self._task_d.finished():
            return {"task": None, "finished": True,
                    "generation": self.generation, **extra}
        # Queue temporarily empty (doing tasks may re-queue on failure):
        # tell the worker to wait (reference servicer.py:60-68).
        wait = Task(task_id=-1, type=TaskType.WAIT)
        return {"task": wait.to_dict(), "finished": False,
                "generation": self.generation, **extra}

    def report_task_result(self, request: dict) -> dict:
        fenced = self._stale_master_reject("report_task_result")
        if fenced is not None:
            # Rejected unresolved: the worker re-resolves to the live
            # master, whose dispatcher (journal-recovered, leases
            # intact) applies it — or answers it from the resolved
            # ledger if an earlier attempt already landed there.
            return fenced
        task_id = int(request["task_id"])
        err_reason = request.get("err_reason", "")
        success = not err_reason
        worker_id = int(request.get("worker_id", -1))
        job_id = str(request.get("job", "") or "")
        self._ingest_metrics(worker_id, request)
        self._note_worker_generation(worker_id, request)
        with self._lock:
            start = self._task_start_times.pop((job_id, task_id), None)
        # Job-scoped routing: the lease carried a job id (scheduler
        # mode) and the report echoes it, so it applies to the
        # dispatcher that issued the lease even if this worker has
        # since been rebound to another gang. A done/cancelled job's
        # dispatcher still answers from its resolved ledger.
        dispatcher = self._task_d
        if job_id and self._scheduler is not None:
            routed = self._scheduler.dispatcher_of(job_id)
            if routed is None:
                return {"accepted": False, "fenced": True,
                        "generation": self.generation}
            dispatcher = routed
        # The duplicate flag is decided atomically with the report
        # application (dispatcher lock): a ledger hit means the side
        # effects below already ran on the first application — only
        # the outcome is re-sent. A pre-check here would race a
        # concurrent retry of the same report.
        task, _worker, requeued, duplicate = dispatcher.apply_report(
            task_id, success, err_reason
        )
        if (task is not None and success and start is not None
                and not duplicate):
            # First applications only: a straggler's late report (its
            # task already requeued, outcome ledger-answered) would
            # otherwise fold its pathological hold time into the mean
            # the straggler deadline derives from.
            with self._lock:
                self._task_secs_sum += time.time() - start
                self._task_count += 1
        if task is None:
            # Unknown AND not in the ledger: a report fenced to a dead
            # generation whose task the recovered master re-queued (or
            # a genuinely bogus id) — reject so the re-dispatched copy
            # is the only one that counts.
            return {"accepted": False, "fenced": True,
                    "generation": self.generation}
        # An eval task counts toward its EvaluationJob when it succeeds OR
        # fails permanently (dropped after retry cap) — otherwise one bad
        # eval shard would wedge the evaluation service forever.
        if (
            not duplicate
            and not requeued
            and task.type == TaskType.EVALUATION
            and self._eval_service is not None
        ):
            self._eval_service.complete_task(task.model_version)
        return {"accepted": True, "generation": self.generation}

    def report_evaluation_metrics(self, request: dict) -> dict:
        fenced = self._stale_master_reject("report_evaluation_metrics")
        if fenced is not None:
            return fenced
        if self._eval_service is None:
            return {"accepted": False}
        # The one handler that does real compute (metric fold over raw
        # output arrays) — span it so a slow eval fold is attributable
        # in the task timeline rather than reading as RPC time.
        outputs = request["model_outputs"]
        rows = getattr(outputs, "shape", None)
        with tracing.span(
            "eval_report", outputs=int(rows[0]) if rows else len(outputs),
        ):
            ok = self._eval_service.report_evaluation_metrics(
                outputs, request["labels"],
                # Dedup key: the fold is a plain accumulate, so a
                # retried send must not double-count its samples.
                task_id=int(request.get("task_id", -1)),
            )
        return {"accepted": ok, "generation": self.generation}

    def report_metrics(self, request: dict) -> dict:
        """Standalone-component telemetry fold-in: processes that are
        not workers (the serving router today) push their registry
        snapshots here so ``ClusterMetrics`` — and the time-series
        store sampling it — see the whole fleet, not just the training
        tier. Keyed ``<component>-<id>`` (e.g. ``router-0``) in the
        cluster view; the same TTL aging applies, so a router that
        stops reporting leaves ``/metrics`` and its series go stale."""
        component = str(request.get("component", "") or "")
        if not component or any(
            c in component for c in ("/", "\\", "\n", '"')
        ):
            return {"accepted": False,
                    "generation": self.generation}
        component_id = int(request.get("component_id", 0))
        snapshot = request.get("metrics")
        if snapshot:
            # Shape gate: a version-skewed reporter's malformed
            # snapshot must be rejected here, not stored to crash the
            # sampler on the next master tick.
            if not self._valid_snapshot(snapshot):
                return {"accepted": False,
                        "generation": self.generation}
            self.metrics_plane.ingest(
                f"{component}-{component_id}", snapshot
            )
        return {"accepted": True, "generation": self.generation}

    # ---- multi-job control (master/scheduler.py) -----------------------

    def submit_job(self, request: dict) -> dict:
        """Admit a job into the gang scheduler's table. Fenced like
        every state mutator: a zombie primary must not grow the job
        table (the submit journals BEFORE the table mutates, so even
        a fence that lands mid-handler aborts cleanly)."""
        fenced = self._stale_master_reject("submit_job")
        if fenced is not None:
            return fenced
        if self._scheduler is None:
            return {"accepted": False, "error": "scheduler disabled",
                    "generation": self.generation}
        job_id = str(request.get("job", "") or "")
        try:
            entry = self._scheduler.submit(
                job_id,
                spec=request.get("spec") or {},
                priority=int(request.get("priority", 0)),
                gang_size=int(request.get("gang_size", 1)),
            )
        except ValueError as exc:
            return {"accepted": False, "error": str(exc),
                    "generation": self.generation}
        return {"accepted": True, "job": job_id,
                "state": entry["state"],
                "generation": self.generation}

    def sched_status(self, request: dict) -> dict:
        """Job-table read for clients (``dump_metrics --sched`` talks
        to the HTTP ``/sched`` route; this is the RPC twin). Reads are
        not fenced — a stale table is labeled, not hidden."""
        if self._scheduler is None:
            return {"enabled": False, "generation": self.generation}
        out = self._scheduler.render()
        out["enabled"] = True
        out["generation"] = self.generation
        out["fenced"] = bool(
            self._journal is not None and self._journal.is_fenced()
        )
        return out

    @staticmethod
    def _valid_snapshot(snapshot) -> bool:
        if not isinstance(snapshot, dict):
            return False
        families = snapshot.get("families", [])
        if not isinstance(families, list):
            return False
        for family in families:
            if not isinstance(family, dict):
                return False
            if not isinstance(family.get("series", []), list):
                return False
            if not all(isinstance(s, dict)
                       for s in family.get("series", [])):
                return False
        return True

    def report_version(self, request: dict) -> dict:
        fenced = self._stale_master_reject("report_version")
        if fenced is not None:
            return fenced
        version = int(request["model_version"])
        worker_id = int(request.get("worker_id", -1))
        self._record_liveness(worker_id)
        self._ingest_metrics(worker_id, request)
        with self._lock:
            advanced = version > self.model_version
            self.model_version = max(self.model_version, version)
        if advanced and self._journal is not None:
            # Model-version high-water mark: recovery re-arms eval
            # triggering and TensorBoard publishing from it. The
            # worker id rides along so replay also restores the
            # dispatcher's per-worker version map (SSP bookkeeping).
            self._journal.append(
                "version", model_version=version,
                worker_id=int(worker_id),
            )
        self._task_d.record_worker_version(worker_id, version)
        if self._eval_service is not None:
            self._eval_service.add_evaluation_task_if_needed(version)
        return {"ok": True, "generation": self.generation}

    # ---- live-resize barrier (docs/elasticity.md) ----------------------

    def begin_resize(self, spec: dict, direction: str = "resize",
                     expected_workers=None) -> int:
        """Open a resize barrier: offer ``spec`` (parallel/reshard.py
        ``mesh_spec`` dict) to every worker on its next get_task.
        ``expected_workers`` seeds the barrier membership (defaults to
        every worker the servicer has seen alive); the autoscaler tick
        refreshes membership via ``maybe_complete_resize`` so a worker
        killed mid-barrier cannot wedge it. Raises if a barrier is
        already pending — resizes are serialized by design (two
        in-flight target meshes would race on the workers)."""
        with self._lock:
            if self._resize is not None:
                raise RuntimeError(
                    f"resize {self._resize['resize_id']} is still "
                    "pending; one barrier at a time"
                )
            self._next_resize_id += 1
            resize_id = self._next_resize_id
            if expected_workers is None:
                expected_workers = list(self._worker_liveness)
            expected = {int(w) for w in expected_workers}
            self._resize = {
                "resize_id": resize_id,
                "spec": dict(spec),
                "direction": str(direction),
                "expected": expected,
                "acks": {},
                "t0": time.monotonic(),
            }
            if self._journal is not None:
                # Inside the lock: a fast ack's done record must not
                # land before this begin record.
                self._journal.append(
                    "resize", resize_id=int(resize_id), spec=dict(spec),
                    direction=str(direction), done=False,
                )
            # Pending gauge set under the lock too: a worker ack on a
            # server thread can complete the barrier the instant the
            # lock drops, and its set(0) must not be overwritten by a
            # late set(1) here.
            self._m_resize_pending.set(1.0)
        self._m_resize.labels(str(direction)).inc()
        logger.info(
            "resize %d (%s) begun: %s, awaiting %s",
            resize_id, direction, spec, sorted(expected),
        )
        return resize_id

    def rearm_resize(self, record: dict):
        """Master-restart recovery: re-open the journaled pending
        barrier. Acks are volatile (they died with the old master), so
        the directive is re-offered to everyone; workers that already
        applied it re-ack idempotently by resize_id. Membership is
        UNKNOWN (``expected=None``) until the run-loop tick supplies
        the live worker set — ack-driven completion is disabled so the
        first re-ack cannot complete a fleet-wide barrier while peers
        still await the re-offer."""
        with self._lock:
            resize_id = int(record["resize_id"])
            self._next_resize_id = max(self._next_resize_id, resize_id)
            self._resize = {
                "resize_id": resize_id,
                "spec": dict(record["spec"]),
                "direction": str(record.get("direction", "resize")),
                "expected": None,  # unknown until the tick refreshes
                "acks": {},
                "t0": time.monotonic(),
            }
            self._m_resize_pending.set(1.0)
        logger.info("re-armed pending resize %d after master restart",
                    resize_id)

    def _resize_offer(self, worker_id: int) -> Optional[dict]:
        with self._lock:
            pending = self._resize
            if pending is None or worker_id in pending["acks"]:
                return None
            return {"resize_id": pending["resize_id"],
                    "spec": dict(pending["spec"])}

    def report_resize(self, request: dict) -> dict:
        """A worker finished applying (or noop-acked) a resize
        directive. Fenced by resize_id: an ack for anything but the
        pending barrier is rejected, so a late ack from before a master
        restart or a superseded resize cannot complete the wrong one."""
        fenced = self._stale_master_reject("report_resize")
        if fenced is not None:
            return fenced
        worker_id = int(request.get("worker_id", -1))
        resize_id = int(request.get("resize_id", -1))
        self._record_liveness(worker_id)
        self._ingest_metrics(worker_id, request)
        self._note_worker_generation(worker_id, request)
        with self._lock:
            pending = self._resize
            if pending is None or pending["resize_id"] != resize_id:
                return {"accepted": False, "fenced": True,
                        "generation": self.generation}
            pending["acks"][worker_id] = str(
                request.get("status", "applied")
            )
            # A worker that arrived after begin (elastic relaunch)
            # joins the membership by acking; a re-armed barrier's
            # membership stays unknown until the tick supplies it.
            if pending["expected"] is not None:
                pending["expected"].add(worker_id)
        self.maybe_complete_resize()
        return {"accepted": True, "generation": self.generation}

    def maybe_complete_resize(self, live_workers=None) -> Optional[dict]:
        """Complete the barrier iff every expected worker has acked.
        Pass the CURRENT live worker set to shrink membership after a
        mid-barrier death (the autoscaler tick / drill does); with no
        argument the membership recorded at begin (plus late joiners)
        decides, and a re-armed barrier (membership unknown) never
        completes. Returns the completed barrier dict or None."""
        with self._lock:
            pending = self._resize
            if pending is None:
                return None
            if live_workers is not None:
                # Membership from the live fleet. An EMPTY live set
                # completes the barrier: everyone who could apply is
                # gone (job drained mid-barrier) — leaving it pending
                # would wedge resize_status()/begin_resize forever.
                expected = {int(w) for w in live_workers}
            else:
                expected = pending["expected"]
                if not expected:
                    # Begin-time membership unknown (re-armed barrier)
                    # or empty: only the tick's live set may decide.
                    return None
            if expected - set(pending["acks"]):
                return None
            self._resize = None
            elapsed = time.monotonic() - pending["t0"]
            if self._journal is not None:
                # Inside the lock, like begin: the done record must not
                # be reorderable against a concurrent begin's record.
                self._journal.append(
                    "resize", resize_id=int(pending["resize_id"]),
                    spec=dict(pending["spec"]),
                    direction=str(pending["direction"]), done=True,
                )
            self._m_resize_pending.set(0.0)
        pending["barrier_seconds"] = elapsed
        self._m_resize_barrier.observe(elapsed)
        logger.info(
            "resize %d (%s) complete: %d ack(s) in %.3fs",
            pending["resize_id"], pending["direction"],
            len(pending["acks"]), elapsed,
        )
        return pending

    def resize_status(self) -> Optional[dict]:
        """Pending barrier (copy) or None — for the autoscaler tick
        and tests."""
        with self._lock:
            if self._resize is None:
                return None
            out = dict(self._resize)
            out["acks"] = dict(out["acks"])
            if out["expected"] is not None:
                out["expected"] = set(out["expected"])
            return out

    # ---- liveness / straggler detection --------------------------------

    def _record_liveness(self, worker_id: int):
        if worker_id >= 0:
            with self._lock:
                self._worker_liveness[worker_id] = time.time()

    def worker_liveness(self) -> Dict[int, float]:
        with self._lock:
            return dict(self._worker_liveness)

    def average_task_secs(self) -> float:
        with self._lock:
            if self._task_count < 3:
                return self._default_task_secs
            return self._task_secs_sum / self._task_count

    def find_timeout_tasks(self, factor: float = 3.0):
        """(task_id, worker_id) pairs running > factor × mean task time
        (reference master.py:487-509 _check_timeout_tasks)."""
        threshold = factor * self.average_task_secs()
        now = time.time()
        out = []
        # Composite (job, task_id) keys: in scheduler mode the scan
        # covers every gang currently holding slots, and per-job task
        # ids collide across dispatchers.
        doing = {
            ("", tid): v
            for tid, v in self._task_d.doing_start_times().items()
        }
        if self._scheduler is not None:
            for job_id, disp in (
                self._scheduler.active_dispatchers().items()
            ):
                if disp is self._task_d:
                    continue
                for tid, v in disp.doing_start_times().items():
                    doing[(job_id, tid)] = v
        for key, (worker_id, start) in doing.items():
            if now - start > threshold:
                out.append((key, worker_id))
        with self._lock:
            # Count each straggling task once, not once per poll tick —
            # in k8s mode kill_worker recovery is async (the pod DELETED
            # watch event), so a timed-out task stays in the doing set
            # for several ticks before it is re-queued.
            self._straggler_counted &= set(doing)
            fresh = [k for k, _w in out if k not in self._straggler_counted]
            self._straggler_counted.update(fresh)
        if fresh:
            self._m_straggler.inc(len(fresh))
        # Callers act on (task_id, worker_id) — kill_worker only needs
        # the worker; the job scoping above is for dedup correctness.
        return [(key[1], worker_id) for key, worker_id in out]

    def seed_task_start_times(self, task_ids):
        """Recovery: start the straggler clock now for every lease
        that survived the master crash (the pre-crash start times died
        with the old process; counting from recovery avoids instantly
        timing out every surviving worker). Bare ints seed the
        single-job plane (job ""); (job, task_id) pairs seed a
        scheduler job's leases."""
        now = time.time()
        with self._lock:
            for tid in task_ids:
                if isinstance(tid, (tuple, list)):
                    job_id, raw = tid
                    self._task_start_times[
                        (str(job_id), int(raw))
                    ] = now
                else:
                    self._task_start_times[("", int(tid))] = now

    def remove_worker_metrics(self, worker_id: int):
        """Drop a departed worker from the cluster view immediately
        (recovery / elastic scale-down path) instead of waiting for the
        report TTL — and from the time-series store, so a deliberate
        removal never reads as an absence-rule breach."""
        self.metrics_plane.remove_worker(worker_id)
        with self._lock:
            self._worker_liveness.pop(worker_id, None)
