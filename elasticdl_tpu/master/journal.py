"""Write-ahead job-state journal: the master's crash-survival plane.

The paper's fault-tolerance story (dynamic data sharding + task
re-queue + pod relaunch) covers every role except the one that
implements it: ``TaskDispatcher`` keeps ``_todo``/``_doing``, retry
counts, epoch state, and the max-steps budget purely in memory, so a
master crash used to mean a fresh job. This module closes that gap the
same way the checkpoint plane covers worker state — durable,
validated, replayable records:

- **Format**: one append-only file of length-prefixed, CRC32-checksummed
  msgpack records (``[u32 len][u32 crc][payload]``, little-endian).
  A torn tail (crash mid-write) is *truncated, not fatal* — the same
  philosophy as ``checkpoint/state_io.validate_shard_payload``: decode
  success alone is not integrity, so every frame is checksummed and
  every decoded record is shape-validated before replay trusts it.
- **Records**: ``dispatch`` / ``report`` / ``create_tasks`` /
  ``version`` events written through by ``TaskDispatcher`` and
  ``MasterServicer``, plus periodic full-state ``snapshot`` records;
  ``generation`` records fence master incarnations (strictly
  increasing; every task dispatch and RPC response is stamped with the
  current one so workers and late reports can be resolved against the
  incarnation that produced them). ``eval_round``/``eval_fold``
  event-source the evaluation service's round state (open job,
  accumulated raw outputs, ``_last_eval_version``), and ``relaunch``
  records persist the instance manager's gang / row-service relaunch
  generations — the two planes that used to die with the master
  (docs/fault_tolerance.md used to list them as known limitations).
  ``sched`` records event-source the multi-job gang scheduler's job
  table (master/scheduler.py, docs/scheduler.md): submit / schedule /
  run / preempt / resume / done / cancel transitions, so the job table
  survives failover and warm-replays into the standby.
- **Snapshots + compaction**: every ``snapshot_every`` state-mutating
  records the journal captures the dispatcher's full exported state
  and rewrites the file to ``[snapshot, tail…]`` — replay cost is
  bounded by the snapshot cadence, not job length.
- **Replay**: recovery re-runs the recorded operation sequence through
  the *real* dispatcher state machine (``get``/``report``/
  ``create_tasks`` with journaling detached), so the recovered
  dispatcher is equivalent by construction — same todo order, same
  task-id counter, same retry budgets, same counters — rather than a
  parallel reimplementation that could drift. The replay core
  (``apply_replay``) is incremental: a hot standby
  (``master/standby.py``) keeps a warm dispatcher continuously
  replayed by applying only the records appended since its last poll,
  so takeover pays the *tail*, not the journal.

Exactly-once across the crash: tasks leased at crash time replay back
into ``_doing`` and stay leased — the workers holding them ride out
the outage on their RPC retry budget (``--master_reattach_grace``) and
re-report against the recovered master. A report the pre-crash master
had already applied is answered from the dispatcher's bounded
recently-resolved ledger (the same idempotence path that absorbs
at-least-once RPC retries); a report for a task the recovered master
re-queued in the meantime is fenced (``accepted=False``) so the
re-dispatched copy is the only one that counts.

Split-brain fencing (the hot-standby plane): the journal directory
carries a ``fence`` file naming the lowest generation still allowed to
append. A standby taking over publishes ``fence = old_generation + 1``
and only then opens its own generation; every append re-checks the
fence **under an flock on the journal's lock file**, so a zombie
primary's late append is rejected *before any byte lands* — two
incarnations can never interleave records, structurally, not
probabilistically. A fenced append raises ``JournalFencedError``; the
servicer surfaces it as a ``stale_master`` rejection so workers
re-resolve to the new incarnation.
"""

import json
import os
import struct
import threading
import zlib
from typing import Callable, Dict, List, Optional

from elasticdl_tpu.common import tensor_utils
from elasticdl_tpu.common.log_utils import get_logger

try:
    import fcntl
except ImportError:  # non-POSIX: fall back to check-without-lock
    fcntl = None

logger = get_logger("master_journal")

JOURNAL_FILE = "journal.log"
FENCE_FILE = "fence"
LOCK_FILE = "journal.lock"

# Record types (the "t" field). KNOWN_TYPES gates replay: an unknown
# type from a newer writer fails loudly instead of silently skewing
# the reconstructed state.
DISPATCH = "dispatch"
REPORT = "report"
CREATE_TASKS = "create_tasks"
VERSION = "version"
SNAPSHOT = "snapshot"
GENERATION = "generation"
RESIZE = "resize"
# Row-plane shard-map epochs (master/row_reshard.py): audit + recovery
# aid riding the same journal. The controller's state file is the
# authoritative copy — compaction may drop old epoch records.
SHARD_MAP = "shard_map"
# Evaluation-round event sourcing (master/evaluation_service.py):
# open / task_done / close round events plus the per-task raw-output
# folds, so an open round survives a master death intact.
EVAL_ROUND = "eval_round"
EVAL_FOLD = "eval_fold"
# Instance-manager relaunch generations (master/instance_manager.py):
# multihost gang restarts and row-service pod relaunches — a recovered
# master must adopt pods under their true (suffixed) names or their
# next death events are discarded as stale.
RELAUNCH = "relaunch"
# Fencing of a prior incarnation at standby takeover: generations must
# be strictly increasing across fence records (fsck enforces).
FENCE = "fence"
# Multi-job gang-scheduler events (master/scheduler.py): the job table
# (spec, priority, gang size, lifecycle state, preemption counts) is
# event-sourced here so it survives failover and replays into the
# standby exactly like the dispatcher/eval/relaunch planes.
SCHED = "sched"
# Streaming-ingestion events (master/stream_ingest.py,
# docs/online_learning.md): partition registration and offset-ranged
# task generation. The committed watermark itself rides REPORT records
# (``stream_partition``/``stream_start``/``stream_end`` stamped by the
# dispatcher) so offset commit is atomic with task resolution — a
# crash cannot ack an offset whose task never resolved, and a
# relaunched pipeline resumes from the journaled watermark, never
# re-acking.
STREAM = "stream"

KNOWN_TYPES = (DISPATCH, REPORT, CREATE_TASKS, VERSION, SNAPSHOT,
               GENERATION, RESIZE, SHARD_MAP, EVAL_ROUND, EVAL_FOLD,
               RELAUNCH, FENCE, SCHED, STREAM)

EVAL_EVENTS = ("open", "close")
RELAUNCH_KINDS = ("gang", "row_service")
# Job lifecycle events (ISSUE 17): submitted -> scheduled -> running
# -> (preempted -> scheduled -> running)* -> done, plus cancel from
# any non-terminal state.
SCHED_EVENTS = ("submit", "schedule", "run", "preempt", "resume",
                "done", "cancel")
# Stream-plane events (ISSUE 18): "register" introduces a partition,
# "tasks" records one offset-ranged task generation (replay re-enqueues
# it — stream tasks come from the live tail, not CREATE_TASKS' epoch
# walk, so the journal is their only deterministic source).
STREAM_EVENTS = ("register", "tasks")

_HEADER = struct.Struct("<II")  # payload length, crc32(payload)


class JournalFormatError(RuntimeError):
    """A record *before* the tail failed validation — unlike a torn
    tail (expected after a crash, silently truncated), mid-file
    corruption means the journal cannot be trusted."""


class JournalFencedError(RuntimeError):
    """This incarnation has been fenced by a newer one (hot-standby
    takeover): its appends are rejected before any byte lands. The
    process must stop serving — its in-memory state is no longer the
    job's truth."""


def _pending_resize_from(record: dict) -> Optional[dict]:
    """Pending-barrier state a RESIZE record (or append fields) leaves
    behind: the begin fields while open, None once done. One helper so
    open/append/replay cannot drift on the record shape."""
    if record.get("done"):
        return None
    return {
        k: record[k] for k in ("resize_id", "spec", "direction")
        if k in record
    }


# ---- eval-round / relaunch state folding --------------------------------
#
# The journal mirrors the evaluation service's round state and the
# instance manager's relaunch generations the same way it mirrors the
# model-version high-water mark: tracked at append time (so snapshots
# can carry them through compaction), re-derived at open_generation
# scan, and rebuilt by replay — all through ONE fold function per
# plane, so the three paths cannot drift on the record shape.


def new_eval_state() -> dict:
    return {"open": None, "last_eval_version": -1, "results": {}}


def _implicit_open() -> dict:
    # Eval-only jobs open their round at construction (the
    # deterministic base state, never journaled) — progress tracks
    # against an implicit open round.
    return {"model_version": -1, "total_tasks": -1,
            "completed": 0, "folds": []}


def apply_eval_record(state: dict, record: dict):
    rtype = record["t"]
    if rtype == EVAL_ROUND:
        event = record.get("event")
        if event == "open":
            state["open"] = {
                "model_version": int(record.get("model_version", -1)),
                "total_tasks": int(record.get("total_tasks", -1)),
                "completed": 0,
                "folds": [],
            }
            state["last_eval_version"] = int(
                record.get("last_eval_version",
                           record.get("model_version", -1))
            )
        elif event == "close":
            state["results"][int(record.get("model_version", -1))] = (
                record.get("results") or {}
            )
            state["open"] = None
    elif rtype == EVAL_FOLD:
        if state["open"] is None:
            state["open"] = _implicit_open()
        state["open"]["folds"].append([
            int(record.get("task_id", -1)),
            record.get("outputs"),
            record.get("labels"),
        ])


def apply_eval_report_record(state: dict, record: dict):
    """Fold one REPORT record's eval-completion side effect into the
    eval state. Completion rides the REPORT record itself
    (``task_type``/``model_version``/``requeued`` fields stamped by
    the dispatcher) rather than a second journal append, so a crash
    between "task resolved" and "round progressed" is impossible —
    they are one fsynced record. Mirrors the servicer's
    ``complete_task`` call: a resolution counts unless the task was
    re-queued, and a completion from a different round's version must
    not count toward this one."""
    if record.get("task_type") != "evaluation" or record.get("requeued"):
        return
    model_version = int(record.get("model_version", -1))
    if state["open"] is None:
        if model_version >= 0:
            # A versioned eval task resolving with no open round is a
            # straggler from an already-closed round — the live path
            # (complete_task with no job) ignores it too.
            return
        state["open"] = _implicit_open()
    open_round = state["open"]
    if (model_version >= 0 and open_round["model_version"] >= 0
            and model_version != open_round["model_version"]):
        return
    open_round["completed"] += 1


def new_sched_state() -> dict:
    return {"jobs": {}, "preemptions": 0}


def apply_sched_record(state: dict, record: dict):
    """Fold one SCHED event into the job table — the ONE fold function
    shared by live appends (journal-side mirror), the open-generation
    scan, and replay, so the three paths cannot drift on the record
    shape (same discipline as the eval/relaunch planes)."""
    event = record.get("event")
    job = str(record.get("job", ""))
    jobs = state["jobs"]
    if event == "submit":
        jobs[job] = {
            "spec": dict(record.get("spec") or {}),
            "priority": int(record.get("priority", 0)),
            "gang_size": int(record.get("gang_size", 1)),
            "state": "submitted",
            "preemptions": 0,
        }
        return
    entry = jobs.get(job)
    if entry is None:
        # An event for a job the (compacted) prefix no longer names —
        # replay tolerates it (the snapshot's table supersedes), the
        # live scheduler never produces it.
        return
    if event == "schedule" or event == "resume":
        entry["state"] = "scheduled"
    elif event == "run":
        entry["state"] = "running"
    elif event == "preempt":
        entry["state"] = "preempted"
        entry["preemptions"] = int(entry.get("preemptions", 0)) + 1
        state["preemptions"] = int(state.get("preemptions", 0)) + 1
    elif event == "done":
        entry["state"] = "done"
    elif event == "cancel":
        entry["state"] = "cancelled"


def new_relaunch_state() -> dict:
    return {"gang": 0, "row_service": {}}


def apply_relaunch_record(state: dict, record: dict):
    generation = int(record.get("generation", 0))
    if record.get("kind") == "gang":
        state["gang"] = max(state["gang"], generation)
    else:
        shard = int(record.get("shard", 0))
        state["row_service"][shard] = max(
            state["row_service"].get(shard, 0), generation
        )


def new_stream_state() -> dict:
    """Per-partition ingestion progress: ``next`` (first offset no
    task has been generated for), ``committed`` (exclusive watermark —
    every offset below it resolved successfully and durably), and
    ``pending`` (resolved ranges still ahead of the contiguous
    committed prefix, {start: end} — tasks complete out of order)."""
    return {"partitions": {}}


def _stream_partition(state: dict, partition: str) -> dict:
    part = state["partitions"].get(partition)
    if part is None:
        part = {"committed": 0, "next": 0, "pending": {}}
        state["partitions"][partition] = part
    return part


def advance_stream_watermark(part: dict, start: int, end: int):
    """Fold one successfully-resolved offset range [start, end) into a
    partition's watermark: record it as pending, then advance
    ``committed`` across the contiguous resolved prefix. Shared by the
    dispatcher's live accounting and every journal fold path so the
    watermark algebra cannot drift. Idempotent for replayed ranges at
    or below the watermark (recovery re-folds are no-ops)."""
    start, end = int(start), int(end)
    if end <= start or end <= int(part["committed"]):
        return
    pending = part["pending"]
    prev = pending.get(start)
    if prev is None or prev < end:
        pending[start] = end
    committed = int(part["committed"])
    while committed in pending:
        committed = pending.pop(committed)
    part["committed"] = committed


def apply_stream_record(state: dict, record: dict):
    """Fold one STREAM event — the ONE fold function shared by live
    appends (journal-side mirror), the open-generation scan, and
    replay (same discipline as the eval/relaunch/sched planes)."""
    partition = str(record.get("partition", ""))
    part = _stream_partition(state, partition)
    if record.get("event") == "tasks":
        part["next"] = max(int(part["next"]), int(record.get("end", 0)))


def apply_stream_report_record(state: dict, record: dict):
    """Fold one REPORT record's offset-commit side effect. The commit
    rides the REPORT record itself (``stream_*`` fields stamped by the
    dispatcher) rather than a second append, so a crash between "task
    resolved" and "watermark advanced" is impossible — they are one
    fsynced record. A failed or re-queued task commits nothing: its
    range stays uncommitted until the retry resolves."""
    partition = record.get("stream_partition")
    if not partition or not record.get("success") \
            or record.get("requeued"):
        return
    advance_stream_watermark(
        _stream_partition(state, str(partition)),
        record.get("stream_start", 0), record.get("stream_end", 0),
    )


def normalize_stream_state(state) -> dict:
    """Snapshot/json round-trip normalization (pending keys may come
    back as strings from json-sourced snapshots)."""
    out = new_stream_state()
    for partition, part in (state or {}).get("partitions", {}).items():
        out["partitions"][str(partition)] = {
            "committed": int(part.get("committed", 0)),
            "next": int(part.get("next", 0)),
            "pending": {
                int(k): int(v)
                for k, v in (part.get("pending") or {}).items()
            },
        }
    return out


def _frame(payload: bytes) -> bytes:
    return _HEADER.pack(len(payload), zlib.crc32(payload)) + payload


def read_records(path: str, start: int = 0):
    """Yield ``(offset, end, record)`` for every intact frame from
    byte ``start``; stop at the first torn/corrupt frame (crash
    tail). The caller decides whether to truncate (recovery) or
    report (fsck) — this reader never raises on a bad tail, only on
    unreadable files. ``start`` must be a frame boundary a previous
    read returned (the standby's incremental tail read); the CRC +
    shape gates make a stale boundary read as an empty tail, never as
    garbage records."""
    with open(path, "rb") as fh:
        blob = fh.read()
    offset = int(start)
    while offset + _HEADER.size <= len(blob):
        length, crc = _HEADER.unpack_from(blob, offset)
        start = offset + _HEADER.size
        payload = blob[start:start + length]
        if len(payload) < length or zlib.crc32(payload) != crc:
            return  # torn tail: partial frame or checksum mismatch
        try:
            record = tensor_utils.loads(payload)
        except Exception:
            return  # undecodable despite matching crc: treat as tail
        if not isinstance(record, dict) or "t" not in record:
            return
        yield offset, start + length, record
        offset = start + length


def validate_record(record: dict) -> Optional[str]:
    """Structural check on one decoded record (the journal's analogue
    of ``state_io.validate_shard_payload``). Returns an error string
    or None."""
    rtype = record.get("t")
    if rtype not in KNOWN_TYPES:
        return f"unknown record type {rtype!r}"
    if not isinstance(record.get("seq"), int):
        return f"{rtype}: non-int seq"
    if rtype == DISPATCH:
        if not isinstance(record.get("task"), dict):
            return "dispatch: task is not a dict"
        for key in ("task_id", "worker_id", "generation"):
            if not isinstance(record.get(key), int):
                return f"dispatch: non-int {key}"
    elif rtype == REPORT:
        if not isinstance(record.get("task_id"), int):
            return "report: non-int task_id"
        if not isinstance(record.get("success"), bool):
            return "report: non-bool success"
        if "stream_partition" in record:
            if not isinstance(record["stream_partition"], str):
                return "report: non-str stream_partition"
            for key in ("stream_start", "stream_end"):
                if not isinstance(record.get(key), int):
                    return f"report: non-int {key}"
    elif rtype == CREATE_TASKS:
        if not isinstance(record.get("task_type"), str):
            return "create_tasks: non-str task_type"
    elif rtype == VERSION:
        if not isinstance(record.get("model_version"), int):
            return "version: non-int model_version"
    elif rtype in (GENERATION, FENCE):
        if not isinstance(record.get("generation"), int):
            return f"{rtype}: non-int generation"
    elif rtype == RESIZE:
        if not isinstance(record.get("resize_id"), int):
            return "resize: non-int resize_id"
        if not isinstance(record.get("spec"), dict):
            return "resize: spec is not a dict"
        if not isinstance(record.get("done"), bool):
            return "resize: non-bool done"
    elif rtype == SHARD_MAP:
        if not isinstance(record.get("version"), int):
            return "shard_map: non-int version"
        if not isinstance(record.get("map"), dict):
            return "shard_map: map is not a dict"
    elif rtype == EVAL_ROUND:
        if record.get("event") not in EVAL_EVENTS:
            return f"eval_round: unknown event {record.get('event')!r}"
        if not isinstance(record.get("model_version"), int):
            return "eval_round: non-int model_version"
        if (record.get("event") == "open"
                and not isinstance(record.get("total_tasks"), int)):
            return "eval_round: open without int total_tasks"
    elif rtype == EVAL_FOLD:
        if not isinstance(record.get("task_id"), int):
            return "eval_fold: non-int task_id"
    elif rtype == RELAUNCH:
        if record.get("kind") not in RELAUNCH_KINDS:
            return f"relaunch: unknown kind {record.get('kind')!r}"
        if not isinstance(record.get("generation"), int):
            return "relaunch: non-int generation"
        if (record.get("kind") == "row_service"
                and not isinstance(record.get("shard"), int)):
            return "relaunch: row_service without int shard"
    elif rtype == SCHED:
        if record.get("event") not in SCHED_EVENTS:
            return f"sched: unknown event {record.get('event')!r}"
        if not isinstance(record.get("job"), str) or not record["job"]:
            return "sched: missing job id"
        if (record.get("event") == "submit"
                and not isinstance(record.get("spec"), dict)):
            return "sched: submit without spec dict"
    elif rtype == STREAM:
        if record.get("event") not in STREAM_EVENTS:
            return f"stream: unknown event {record.get('event')!r}"
        if not isinstance(record.get("partition"), str) \
                or not record["partition"]:
            return "stream: missing partition"
        if record.get("event") == "tasks":
            for key in ("start", "end"):
                if not isinstance(record.get(key), int):
                    return f"stream: tasks without int {key}"
            if record["end"] <= record["start"]:
                return "stream: empty tasks range"
    elif rtype == SNAPSHOT:
        state = record.get("state")
        if not isinstance(state, dict):
            return "snapshot: state is not a dict"
        for key in ("todo", "doing"):
            if not isinstance(state.get(key), list):
                return f"snapshot: state.{key} is not a list"
        for key in ("task_id", "epochs_todo"):
            if not isinstance(state.get(key), int):
                return f"snapshot: state.{key} is not an int"
    return None


def new_replay_carry() -> dict:
    """Accumulator ``apply_replay`` folds records into — everything a
    recovered (or continuously-replaying standby) master needs beyond
    the dispatcher itself."""
    return {
        "replayed": 0,
        "snapshot": False,
        "model_version": 0,
        "generation": 0,
        "known_workers": set(),
        "resize": None,
        "shard_map": None,
        "eval": new_eval_state(),
        "relaunch": new_relaunch_state(),
        "sched": new_sched_state(),
        "stream": new_stream_state(),
        "seq": 0,
    }


def apply_replay(dispatcher, records: List[dict],
                 carry: Optional[dict] = None) -> dict:
    """Fold ``records`` into ``dispatcher`` + ``carry`` — the replay
    core shared by cold recovery (``recover_into``: all records into a
    fresh dispatcher) and the hot standby (only the records appended
    since its last poll, into its warm dispatcher).

    Records with ``seq <= carry["seq"]`` are skipped (already
    applied); a SNAPSHOT with a newer seq supersedes the dispatcher's
    current state wholesale (that is what a snapshot means), so the
    incremental path survives compaction rewrites. The dispatcher must
    NOT have a journal attached — replay drives its real ``get``/
    ``report``/``create_tasks`` methods and must not re-append what it
    reads.
    """
    if getattr(dispatcher, "_journal", None) is not None:
        raise RuntimeError("detach the journal before replay")
    carry = carry if carry is not None else new_replay_carry()
    for record in records:
        seq = int(record.get("seq", 0))
        if seq <= carry["seq"]:
            continue
        carry["seq"] = seq
        rtype = record["t"]
        if rtype == GENERATION or rtype == FENCE:
            carry["generation"] = max(carry["generation"],
                                      record["generation"])
            continue
        if rtype == SHARD_MAP:
            # Newest epoch wins (versions are monotonic by
            # construction — the authority is the only writer).
            carry["shard_map"] = record["map"]
            carry["replayed"] += 1
            continue
        if rtype == VERSION:
            carry["model_version"] = max(carry["model_version"],
                                         record["model_version"])
            worker_id = int(record.get("worker_id", -1))
            if worker_id >= 0:
                dispatcher.record_worker_version(
                    worker_id, record["model_version"]
                )
                carry["known_workers"].add(worker_id)
            carry["replayed"] += 1
            continue
        if rtype == RESIZE:
            # Barrier state, not dispatcher state: an open begin
            # survives so the recovered servicer re-offers the
            # directive; done closes it.
            carry["resize"] = _pending_resize_from(record)
            carry["replayed"] += 1
            continue
        if rtype in (EVAL_ROUND, EVAL_FOLD):
            apply_eval_record(carry["eval"], record)
            carry["replayed"] += 1
            continue
        if rtype == RELAUNCH:
            apply_relaunch_record(carry["relaunch"], record)
            carry["replayed"] += 1
            continue
        if rtype == SCHED:
            apply_sched_record(carry["sched"], record)
            carry["replayed"] += 1
            continue
        if rtype == STREAM:
            apply_stream_record(carry["stream"], record)
            # Stream tasks are generated from the live tail, not the
            # epoch walk — replay re-enqueues them from the journal so
            # the subsequent DISPATCH records find the same todo queue
            # the dead master had.
            if record.get("event") == "tasks":
                dispatcher.create_stream_tasks(
                    record["partition"], record["start"], record["end"],
                    model_version=record.get("model_version", -1),
                )
            else:
                dispatcher.register_stream_partition(
                    record["partition"]
                )
            carry["replayed"] += 1
            continue
        if rtype == SNAPSHOT:
            state = record["state"]
            dispatcher.restore_state(state)
            carry["snapshot"] = True
            carry["generation"] = max(carry["generation"],
                                      int(record.get("generation", 0)))
            carry["model_version"] = max(
                carry["model_version"],
                int(record.get("model_version", 0)),
            )
            carry["resize"] = record.get("resize")
            if record.get("eval") is not None:
                carry["eval"] = record["eval"]
            if record.get("relaunch") is not None:
                # msgpack round-trips the shard keys as ints already,
                # but normalize defensively (json-sourced snapshots).
                relaunch = record["relaunch"]
                carry["relaunch"] = {
                    "gang": int(relaunch.get("gang", 0)),
                    "row_service": {
                        int(k): int(v) for k, v in
                        (relaunch.get("row_service") or {}).items()
                    },
                }
            if record.get("sched") is not None:
                sched = record["sched"]
                carry["sched"] = {
                    "jobs": {
                        str(k): dict(v) for k, v in
                        (sched.get("jobs") or {}).items()
                    },
                    "preemptions": int(sched.get("preemptions", 0)),
                }
            if record.get("stream") is not None:
                carry["stream"] = normalize_stream_state(
                    record["stream"]
                )
            # Compaction dropped the pre-snapshot dispatch records;
            # the snapshot's leases and version reports still name the
            # workers this job had.
            carry["known_workers"].update(
                int(wid) for _tid, _task, wid in state.get("doing", [])
            )
            carry["known_workers"].update(
                int(k) for k in state.get("worker_version", {})
            )
            carry["replayed"] += 1
            continue
        if rtype == CREATE_TASKS:
            dispatcher.create_tasks(
                record["task_type"],
                model_version=record.get("model_version", -1),
            )
            carry["replayed"] += 1
            continue
        if rtype == DISPATCH:
            wid = record["worker_id"]
            carry["known_workers"].add(wid)
            task = dispatcher.get(wid)
            want = record["task"]
            if task is None or task.task_id != record["task_id"] or (
                (task.shard_name, task.start, task.end, task.type)
                != (want.get("shard_name"), want.get("start"),
                    want.get("end"), want.get("type"))
            ):
                # The state machine disagreed with the journal —
                # a bug or a journal from different job config.
                # Fail loudly; recovering wrong state silently
                # would double- or under-train.
                raise JournalFormatError(
                    f"replay diverged at seq {record['seq']}: "
                    f"journal dispatched task {record['task_id']} "
                    f"({want.get('shard_name')}:{want.get('start')}-"
                    f"{want.get('end')}), state machine produced "
                    f"{task.task_id if task else None}"
                )
            carry["replayed"] += 1
            continue
        if rtype == REPORT:
            dispatcher.report(
                record["task_id"], record["success"],
                err_reason=record.get("err_reason", ""),
            )
            # The eval-completion and stream-commit side effects ride
            # the same record (atomic with the resolution — a crash
            # cannot separate them).
            apply_eval_report_record(carry["eval"], record)
            apply_stream_report_record(carry["stream"], record)
            carry["replayed"] += 1
    return carry


class MasterJournal:
    """One job's journal: append with periodic snapshot/compaction,
    replay with torn-tail truncation. Thread-safe (appends come from
    dispatcher and servicer threads)."""

    def __init__(self, journal_dir: str, snapshot_every: int = 64):
        if not journal_dir:
            raise ValueError("journal_dir must be non-empty")
        self.journal_dir = journal_dir
        self.snapshot_every = max(1, int(snapshot_every))
        os.makedirs(journal_dir, exist_ok=True)
        self.path = os.path.join(journal_dir, JOURNAL_FILE)
        self.fence_path = os.path.join(journal_dir, FENCE_FILE)
        self.lock_path = os.path.join(journal_dir, LOCK_FILE)
        self._lock = threading.RLock()
        self._fh = None
        self._seq = 0
        self._since_snapshot = 0
        # Provider returning the dispatcher's exported state; called
        # with the dispatcher lock already held (appends happen inside
        # the dispatcher's critical sections), so it must be the
        # lock-free variant (TaskDispatcher._export_state_locked).
        self._snapshot_provider: Optional[Callable[[], dict]] = None
        self.generation = 0
        # Model-version high-water mark, tracked journal-side so
        # compaction (which discards the raw VERSION records) can
        # carry it inside the snapshot record.
        self._model_version = 0
        # Pending resize barrier (master/servicer.py), tracked the
        # same way: the open begin record must survive compaction so
        # a recovered master can re-offer the directive.
        self._pending_resize = None
        # Evaluation-round and relaunch-generation mirrors, tracked
        # journal-side for the same reason (compaction must not drop
        # an open round or a live pod generation). Folded through the
        # SAME functions replay uses, so they cannot drift.
        self._eval = new_eval_state()
        self._relaunch = new_relaunch_state()
        self._sched = new_sched_state()
        self._stream = new_stream_state()
        # (last-checked monotonic time, verdict) for is_fenced().
        self._fence_cache = (0.0, False)

    # ---- lifecycle -----------------------------------------------------

    def has_state(self) -> bool:
        """True when the journal holds at least one intact record —
        i.e. a restarted master has something to recover."""
        if not os.path.exists(self.path):
            return False
        for _offset, _end, _record in read_records(self.path):
            return True
        return False

    def set_snapshot_provider(self, provider: Callable[[], dict]):
        self._snapshot_provider = provider

    def open_generation(self) -> int:
        """Start (or resume) this master incarnation: scan for the
        highest generation on disk, truncate any torn tail, open with
        ``max(generation + 1, fence file)``, and PUBLISH that fence —
        opening a generation always fences every prior incarnation, so
        a restarted old primary coming back next to a promoted standby
        produces a single-writer handover (last opener wins; the other
        side's next append is rejected), never two live masters
        interleaving records. The whole scan→fence→first-append runs
        under the journal flock, so two racing openers serialize: the
        second sees the first's generation record and lands above it.
        Returns the new generation. Raises if the fence file exists
        but is unreadable — opening under an unknown fence could
        resurrect a fenced incarnation."""
        with self._lock:
            fd = self._flock()
            try:
                return self._open_generation_flocked()
            finally:
                self._funlock(fd)

    def _open_generation_flocked(self) -> int:
            last_good_end = 0
            max_gen = -1
            if os.path.exists(self.path):
                for _offset, end, record in read_records(self.path):
                    last_good_end = end
                    self._seq = max(self._seq, int(record.get("seq", 0)))
                    if record["t"] in (GENERATION, FENCE):
                        max_gen = max(
                            max_gen, int(record.get("generation", -1))
                        )
                    elif record["t"] == VERSION:
                        self._model_version = max(
                            self._model_version,
                            int(record.get("model_version", 0)),
                        )
                    elif record["t"] == SNAPSHOT:
                        self._model_version = max(
                            self._model_version,
                            int(record.get("model_version", 0)),
                        )
                        self._pending_resize = record.get("resize")
                        if record.get("eval") is not None:
                            self._eval = record["eval"]
                        if record.get("relaunch") is not None:
                            self._relaunch = record["relaunch"]
                        if record.get("sched") is not None:
                            self._sched = record["sched"]
                        if record.get("stream") is not None:
                            self._stream = normalize_stream_state(
                                record["stream"]
                            )
                    elif record["t"] == RESIZE:
                        self._pending_resize = _pending_resize_from(
                            record
                        )
                    elif record["t"] in (EVAL_ROUND, EVAL_FOLD):
                        apply_eval_record(self._eval, record)
                    elif record["t"] == REPORT:
                        # Round progress and stream commits ride
                        # report records — the scan must fold them
                        # like append/replay do, or this incarnation's
                        # next snapshot regresses the mirrored state.
                        apply_eval_report_record(self._eval, record)
                        apply_stream_report_record(self._stream, record)
                    elif record["t"] == RELAUNCH:
                        apply_relaunch_record(self._relaunch, record)
                    elif record["t"] == SCHED:
                        apply_sched_record(self._sched, record)
                    elif record["t"] == STREAM:
                        apply_stream_record(self._stream, record)
                size = os.path.getsize(self.path)
                if size > last_good_end:
                    logger.warning(
                        "journal %s: truncating torn tail "
                        "(%d byte(s) past the last intact record)",
                        self.path, size - last_good_end,
                    )
                    with open(self.path, "r+b") as fh:
                        fh.truncate(last_good_end)
            # An existing fence wins over the on-disk generation scan:
            # a takeover published fence = old + 1 BEFORE opening, and
            # the opener must land exactly on it (never below — that
            # incarnation would be stillborn, its own appends fenced).
            # strict=True: an unreadable fence must abort the open,
            # not be adopted as a generation.
            self.generation = max(max_gen + 1,
                                  self._read_fence(strict=True))
            self._write_fence_file(self.generation)
            self._fence_cache = (0.0, False)  # verdict was per old gen
            self._fh = open(self.path, "ab")
            self._append_frame(GENERATION, generation=self.generation)
            return self.generation

    def close(self):
        with self._lock:
            if self._fh is not None:
                self._fh.close()
                self._fh = None

    # ---- fencing (hot-standby takeover) --------------------------------

    def _read_fence(self, strict: bool = False) -> int:
        try:
            with open(self.fence_path) as fh:
                return int(json.load(fh).get("generation", 0))
        except FileNotFoundError:
            return 0
        except Exception:
            logger.exception("unreadable fence file %s", self.fence_path)
            if strict:
                # open_generation must never adopt the fail-closed
                # sentinel as its own generation (that would un-fence
                # exactly the case the sentinel blocks).
                raise RuntimeError(
                    f"fence file {self.fence_path} exists but is "
                    "unreadable; refusing to open a generation under "
                    "an unknown fence"
                )
            # An unreadable fence fails CLOSED: nobody can prove they
            # are the live incarnation, so nobody may append.
            return 1 << 62

    def fence_generation(self) -> int:
        """Lowest generation still allowed to append (0 = unfenced)."""
        return self._read_fence()

    def _write_fence_file(self, generation: int) -> int:
        """Durably publish ``max(current fence, generation)`` (caller
        holds the flock). Returns the published value."""
        generation = max(int(generation), self._read_fence())
        tmp = self.fence_path + ".tmp"
        with open(tmp, "w") as fh:
            json.dump({"generation": generation}, fh)
            fh.flush()
            os.fsync(fh.fileno())
        os.replace(tmp, self.fence_path)
        return generation

    def is_fenced(self) -> bool:
        """Cheap pre-check for RPC handlers (the authoritative reject
        happens inside ``append`` under the flock). Cached briefly —
        one fence-file stat per ~100ms, not per WAIT poll — and
        sticky: once fenced, always fenced (fences never regress)."""
        import time

        now = time.monotonic()
        t, fenced = self._fence_cache
        if fenced:
            return True
        if now - t < 0.1:
            return False
        fenced = self.fence_generation() > self.generation
        self._fence_cache = (now, fenced)
        return fenced

    def _flock(self):
        """Exclusive lock on the journal's lock file (cross-process
        AND cross-instance-in-process: flock contends per open file
        description). Returns the fd, or None when flock is
        unavailable (fence checks still run, just not atomically)."""
        if fcntl is None:
            return None
        fd = os.open(self.lock_path, os.O_CREAT | os.O_RDWR, 0o644)
        try:
            fcntl.flock(fd, fcntl.LOCK_EX)
        except Exception:
            os.close(fd)
            return None
        return fd

    @staticmethod
    def _funlock(fd):
        if fd is not None:
            try:
                fcntl.flock(fd, fcntl.LOCK_UN)
            finally:
                os.close(fd)

    def publish_fence(self, generation: int) -> int:
        """Fence every incarnation below ``generation`` (standby
        takeover step 1 — BEFORE opening our own generation). Under
        the flock, so it serializes against in-flight appends: once
        this returns, no fenced incarnation can land another byte.
        Monotonic: an older fence is never regressed. Returns the
        published fence generation."""
        fd = self._flock()
        try:
            return self._write_fence_file(generation)
        finally:
            self._funlock(fd)

    # ---- append --------------------------------------------------------

    def _check_fence_flocked(self, action: str):
        """Caller holds the flock: reject if a newer incarnation owns
        the journal."""
        fence = self.fence_generation()
        if fence > self.generation:
            raise JournalFencedError(
                f"incarnation (generation {self.generation}) is "
                f"fenced by generation {fence}: {action} rejected — "
                "a newer master owns this journal"
            )

    def _append_frame(self, rtype: str, **fields):
        """Write + fsync one frame. Caller holds the flock (or is the
        opener inside open_generation's flock)."""
        if self._fh is None:
            raise RuntimeError(
                "journal not open for append (call open_generation)"
            )
        self._seq += 1
        record = {"t": rtype, "seq": self._seq, **fields}
        self._fh.write(_frame(tensor_utils.dumps(record)))
        self._fh.flush()
        # fsync per record: exactly-once across NODE failure requires
        # the record durable before the RPC response leaves (a
        # flushed-but-unsynced report acked to the worker would
        # re-train after power loss). Affordable here — the control
        # plane appends at task granularity (seconds), not step
        # granularity.
        os.fsync(self._fh.fileno())

    def _append_locked(self, rtype: str, **fields):
        fd = self._flock()
        try:
            self._check_fence_flocked(f"append of {rtype!r}")
            self._append_frame(rtype, **fields)
        finally:
            self._funlock(fd)

    def append(self, rtype: str, **fields):
        """Append one event record; dispatcher-originated state
        mutations (dispatch/report) also advance the snapshot cadence
        — those are the only appends guaranteed to run under the
        dispatcher lock, which the snapshot provider requires."""
        with self._lock:
            if rtype == VERSION:
                self._model_version = max(
                    self._model_version,
                    int(fields.get("model_version", 0)),
                )
            elif rtype == RESIZE:
                self._pending_resize = _pending_resize_from(fields)
            elif rtype in (EVAL_ROUND, EVAL_FOLD):
                apply_eval_record(self._eval, {"t": rtype, **fields})
            elif rtype == REPORT:
                # Eval-round completion and stream-offset commits ride
                # the report record (see apply_eval_report_record /
                # apply_stream_report_record) — mirror them here so
                # the snapshot carries the progress.
                apply_eval_report_record(self._eval, fields)
                apply_stream_report_record(self._stream, fields)
            elif rtype == RELAUNCH:
                apply_relaunch_record(self._relaunch, fields)
            elif rtype == SCHED:
                apply_sched_record(self._sched, fields)
            elif rtype == STREAM:
                apply_stream_record(self._stream, {"t": rtype, **fields})
            self._append_locked(rtype, **fields)
            if rtype in (DISPATCH, REPORT):
                self._since_snapshot += 1
                if (self._snapshot_provider is not None
                        and self._since_snapshot >= self.snapshot_every):
                    self._snapshot_locked()

    def _snapshot_locked(self):
        state = self._snapshot_provider()
        self._seq += 1
        record = {
            "t": SNAPSHOT, "seq": self._seq,
            "generation": self.generation, "state": state,
            # Compaction discards the raw VERSION records; the
            # high-water mark must survive inside the snapshot.
            "model_version": int(self._model_version),
            # Same for an open resize barrier, an open eval round, and
            # the relaunch generations (their raw records are
            # compacted away with the rest of the prefix).
            "resize": self._pending_resize,
            "eval": self._eval,
            "relaunch": self._relaunch,
            "sched": self._sched,
            "stream": self._stream,
        }
        # Compaction: the snapshot supersedes everything before it, so
        # rewrite the file as [generation fence, snapshot] and keep
        # appending — replay cost stays bounded by the cadence. The
        # tmp+rename publish mirrors the checkpoint saver: a crash
        # mid-compaction leaves either the old journal or the new one,
        # never a half-written file. The whole rewrite runs under the
        # flock WITH a fence re-check: os.replace would otherwise let
        # a freshly-fenced zombie clobber records the new incarnation
        # appended after this zombie's last fence check — the one
        # remaining way around the append-path fence.
        fd = self._flock()
        try:
            self._check_fence_flocked("snapshot compaction")
            tmp = self.path + ".tmp"
            with open(tmp, "wb") as fh:
                fence = {
                    "t": GENERATION, "seq": self._seq - 1,
                    "generation": self.generation,
                }
                fh.write(_frame(tensor_utils.dumps(fence)))
                fh.write(_frame(tensor_utils.dumps(record)))
                fh.flush()
                os.fsync(fh.fileno())
            if self._fh is not None:
                self._fh.close()
            os.replace(tmp, self.path)
            self._fh = open(self.path, "ab")
            self._since_snapshot = 0
        finally:
            self._funlock(fd)

    # ---- replay --------------------------------------------------------

    def head_signature(self) -> Optional[tuple]:
        """(seq, type) of the FIRST intact record, or None. One-frame
        decode: the standby's incremental reader uses it to detect a
        compaction rewrite (the head changes) without re-decoding the
        file."""
        if not os.path.exists(self.path):
            return None
        for _offset, _end, record in read_records(self.path):
            return (int(record.get("seq", 0)), record.get("t"))
        return None

    def last_seq(self) -> int:
        """Highest intact seq, decoding ONLY the final frame: frames
        are hopped by their length headers (CRC-checked, no msgpack
        work), so a lag probe on a snapshot-heavy journal costs I/O,
        not an ndarray decode per beat."""
        if not os.path.exists(self.path):
            return 0
        with open(self.path, "rb") as fh:
            blob = fh.read()
        offset = 0
        last_payload = None
        while offset + _HEADER.size <= len(blob):
            length, crc = _HEADER.unpack_from(blob, offset)
            start = offset + _HEADER.size
            payload = blob[start:start + length]
            if len(payload) < length or zlib.crc32(payload) != crc:
                break
            last_payload = payload
            offset = start + length
        if last_payload is None:
            return 0
        try:
            record = tensor_utils.loads(last_payload)
            return int(record.get("seq", 0))
        except Exception:
            return 0

    def replay_records(self) -> List[dict]:
        """All intact records, torn tail dropped; raises
        ``JournalFormatError`` only on structurally invalid records
        *before* the tail (a bad frame is the tail by definition —
        framing cannot resync past it)."""
        if not os.path.exists(self.path):
            return []
        out = []
        for _offset, _end, record in read_records(self.path):
            err = validate_record(record)
            if err:
                raise JournalFormatError(f"{self.path}: {err}")
            out.append(record)
        return out

    def tail(self, n: int = 50) -> List[dict]:
        """Last ``n`` intact records — the incident bundle's
        ``journal_tail.json`` (observability/slo.IncidentRecorder):
        what the control plane was doing right before a breach.
        Read-only and crash-tolerant (torn tails drop, bad records
        return what precedes them rather than raising — an incident
        capture must never fail on a journal quirk)."""
        if not os.path.exists(self.path):
            return []
        out: List[dict] = []
        try:
            for _offset, _end, record in read_records(self.path):
                out.append(record)
        except Exception:
            logger.exception("journal tail read stopped early")
        return out[-int(n):]

    def recover_into(self, dispatcher) -> dict:
        """Replay the full journal into ``dispatcher`` (freshly
        constructed with the same shard/epoch/seed config). Returns
        the replay carry (``replayed``, ``snapshot``,
        ``model_version``, ``generation``, sorted ``known_workers``,
        ``resize``, ``shard_map``, ``eval``, ``relaunch``)."""
        carry = apply_replay(dispatcher, self.replay_records())
        # Leases survive the crash: tasks in doing stay leased to the
        # workers riding out the outage; their start clocks reset to
        # replay time (dispatcher.get stamped time.time()), so the
        # straggler deadline counts from recovery, and a worker that
        # died DURING the outage is caught by the normal timeout path.
        carry["known_workers"] = sorted(carry["known_workers"])
        return carry


def rearm_recovered_master(journal: "MasterJournal", dispatcher,
                           stats: dict, servicer=None,
                           eval_service=None) -> None:
    """Re-arm the control plane around a replayed dispatcher after the
    new generation is open: journal write-through re-attached, eval
    round restored, servicer high-water marks / straggler clocks /
    pending resize re-offered. One function so cold recovery
    (``recover_master_state``) and the hot standby's warm takeover
    (``master/standby.py``) cannot drift on the sequence."""
    dispatcher.attach_journal(journal)
    if eval_service is not None:
        eval_service.restore_recovered(stats["eval"])
        eval_service.attach_journal(journal)
    if servicer is not None:
        servicer.model_version = max(
            servicer.model_version, stats["model_version"]
        )
        servicer.generation = journal.generation
        servicer.seed_task_start_times(
            list(dispatcher.doing_start_times())
        )
        if stats.get("resize"):
            # A master crash mid-resize: re-offer the journaled
            # pending directive (acks are volatile; workers that
            # applied it already re-ack idempotently).
            servicer.rearm_resize(stats["resize"])


def recover_master_state(journal: "MasterJournal", dispatcher,
                         servicer=None,
                         metrics_registry=None,
                         eval_service=None,
                         fence: bool = False) -> Dict:
    """The full master-side recovery sequence: replay the journal into
    the dispatcher, re-arm the servicer (model version high-water mark
    + fresh straggler clocks for surviving leases) and the evaluation
    service (open round restored, raw outputs re-folded), bump the
    generation fence, re-attach the journal for write-through, and
    publish recovery telemetry. Returns the replay stats dict with
    ``recovery_seconds`` added.

    ``fence=True`` (standby takeover) publishes the fence file BEFORE
    opening the new generation, so a still-running prior incarnation
    is locked out of the journal from this point on — the split-brain
    guarantee. A plain restart (the old process is dead) skips it.

    Shared by ``master/main.py`` (process restart), the hot standby
    (``master/standby.py``), and the chaos restart seam
    (``testing/cluster.MiniCluster.restart_master``) so drills
    exercise the same code path production uses.
    """
    import time

    from elasticdl_tpu.observability import default_registry, tracing

    registry = metrics_registry or default_registry()
    t0 = time.monotonic()
    with tracing.Tracer("master").span("recover") as sp:
        carry = apply_replay(dispatcher, journal.replay_records())
        if fence:
            journal.publish_fence(carry["generation"] + 1)
            # Drain records that raced in between the read above and
            # the fence landing (a live zombie may have appended) —
            # durable records the promoted state must not omit. After
            # the fence nothing more can land (same drain the
            # StandbyMaster takeover does).
            apply_replay(dispatcher, journal.replay_records(), carry)
        stats = carry
        stats["known_workers"] = sorted(stats["known_workers"])
        generation = journal.open_generation()
        if fence:
            journal.append("fence", generation=generation)
        rearm_recovered_master(journal, dispatcher, stats,
                               servicer=servicer,
                               eval_service=eval_service)
        sp.set(replayed=int(stats["replayed"]),
               generation=int(generation))
    elapsed = time.monotonic() - t0
    stats["generation"] = generation
    stats["recovery_seconds"] = elapsed
    registry.histogram(
        "master_recovery_seconds",
        "Journal replay + re-arm latency on master restart",
    ).observe(elapsed)
    registry.counter(
        "master_journal_replayed_records_total",
        "Journal records replayed into recovered dispatchers",
    ).inc(stats["replayed"])
    logger.info(
        "master recovered from %s: %d record(s) replayed "
        "(snapshot=%s), generation %d, %d leased task(s) surviving",
        journal.path, stats["replayed"], stats["snapshot"],
        generation, len(dispatcher.doing_start_times()),
    )
    return stats
