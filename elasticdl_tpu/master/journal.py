"""Write-ahead job-state journal: the master's crash-survival plane.

The paper's fault-tolerance story (dynamic data sharding + task
re-queue + pod relaunch) covers every role except the one that
implements it: ``TaskDispatcher`` keeps ``_todo``/``_doing``, retry
counts, epoch state, and the max-steps budget purely in memory, so a
master crash used to mean a fresh job. This module closes that gap the
same way the checkpoint plane covers worker state — durable,
validated, replayable records:

- **Format**: one append-only file of length-prefixed, CRC32-checksummed
  msgpack records (``[u32 len][u32 crc][payload]``, little-endian).
  A torn tail (crash mid-write) is *truncated, not fatal* — the same
  philosophy as ``checkpoint/state_io.validate_shard_payload``: decode
  success alone is not integrity, so every frame is checksummed and
  every decoded record is shape-validated before replay trusts it.
- **Records**: ``dispatch`` / ``report`` / ``create_tasks`` /
  ``version`` events written through by ``TaskDispatcher`` and
  ``MasterServicer``, plus periodic full-state ``snapshot`` records;
  ``generation`` records fence master incarnations (strictly
  increasing; every task dispatch and RPC response is stamped with the
  current one so workers and late reports can be resolved against the
  incarnation that produced them).
- **Snapshots + compaction**: every ``snapshot_every`` state-mutating
  records the journal captures the dispatcher's full exported state
  and rewrites the file to ``[snapshot, tail…]`` — replay cost is
  bounded by the snapshot cadence, not job length.
- **Replay**: recovery re-runs the recorded operation sequence through
  the *real* dispatcher state machine (``get``/``report``/
  ``create_tasks`` with journaling detached), so the recovered
  dispatcher is equivalent by construction — same todo order, same
  task-id counter, same retry budgets, same counters — rather than a
  parallel reimplementation that could drift.

Exactly-once across the crash: tasks leased at crash time replay back
into ``_doing`` and stay leased — the workers holding them ride out
the outage on their RPC retry budget (``--master_reattach_grace``) and
re-report against the recovered master. A report the pre-crash master
had already applied is answered from the dispatcher's bounded
recently-resolved ledger (the same idempotence path that absorbs
at-least-once RPC retries); a report for a task the recovered master
re-queued in the meantime is fenced (``accepted=False``) so the
re-dispatched copy is the only one that counts.
"""

import os
import struct
import threading
import zlib
from typing import Callable, Dict, List, Optional

from elasticdl_tpu.common import tensor_utils
from elasticdl_tpu.common.log_utils import get_logger

logger = get_logger("master_journal")

JOURNAL_FILE = "journal.log"

# Record types (the "t" field). KNOWN_TYPES gates replay: an unknown
# type from a newer writer fails loudly instead of silently skewing
# the reconstructed state.
DISPATCH = "dispatch"
REPORT = "report"
CREATE_TASKS = "create_tasks"
VERSION = "version"
SNAPSHOT = "snapshot"
GENERATION = "generation"
RESIZE = "resize"
# Row-plane shard-map epochs (master/row_reshard.py): audit + recovery
# aid riding the same journal. The controller's state file is the
# authoritative copy — compaction may drop old epoch records.
SHARD_MAP = "shard_map"

KNOWN_TYPES = (DISPATCH, REPORT, CREATE_TASKS, VERSION, SNAPSHOT,
               GENERATION, RESIZE, SHARD_MAP)

_HEADER = struct.Struct("<II")  # payload length, crc32(payload)


def _pending_resize_from(record: dict) -> Optional[dict]:
    """Pending-barrier state a RESIZE record (or append fields) leaves
    behind: the begin fields while open, None once done. One helper so
    open/append/replay cannot drift on the record shape."""
    if record.get("done"):
        return None
    return {
        k: record[k] for k in ("resize_id", "spec", "direction")
        if k in record
    }


class JournalFormatError(RuntimeError):
    """A record *before* the tail failed validation — unlike a torn
    tail (expected after a crash, silently truncated), mid-file
    corruption means the journal cannot be trusted."""


def _frame(payload: bytes) -> bytes:
    return _HEADER.pack(len(payload), zlib.crc32(payload)) + payload


def read_records(path: str):
    """Yield ``(offset, end, record)`` for every intact frame; stop at
    the first torn/corrupt frame (crash tail). The caller decides
    whether to truncate (recovery) or report (fsck) — this reader
    never raises on a bad tail, only on unreadable files."""
    with open(path, "rb") as fh:
        blob = fh.read()
    offset = 0
    while offset + _HEADER.size <= len(blob):
        length, crc = _HEADER.unpack_from(blob, offset)
        start = offset + _HEADER.size
        payload = blob[start:start + length]
        if len(payload) < length or zlib.crc32(payload) != crc:
            return  # torn tail: partial frame or checksum mismatch
        try:
            record = tensor_utils.loads(payload)
        except Exception:
            return  # undecodable despite matching crc: treat as tail
        if not isinstance(record, dict) or "t" not in record:
            return
        yield offset, start + length, record
        offset = start + length


def validate_record(record: dict) -> Optional[str]:
    """Structural check on one decoded record (the journal's analogue
    of ``state_io.validate_shard_payload``). Returns an error string
    or None."""
    rtype = record.get("t")
    if rtype not in KNOWN_TYPES:
        return f"unknown record type {rtype!r}"
    if not isinstance(record.get("seq"), int):
        return f"{rtype}: non-int seq"
    if rtype == DISPATCH:
        if not isinstance(record.get("task"), dict):
            return "dispatch: task is not a dict"
        for key in ("task_id", "worker_id", "generation"):
            if not isinstance(record.get(key), int):
                return f"dispatch: non-int {key}"
    elif rtype == REPORT:
        if not isinstance(record.get("task_id"), int):
            return "report: non-int task_id"
        if not isinstance(record.get("success"), bool):
            return "report: non-bool success"
    elif rtype == CREATE_TASKS:
        if not isinstance(record.get("task_type"), str):
            return "create_tasks: non-str task_type"
    elif rtype == VERSION:
        if not isinstance(record.get("model_version"), int):
            return "version: non-int model_version"
    elif rtype == GENERATION:
        if not isinstance(record.get("generation"), int):
            return "generation: non-int generation"
    elif rtype == RESIZE:
        if not isinstance(record.get("resize_id"), int):
            return "resize: non-int resize_id"
        if not isinstance(record.get("spec"), dict):
            return "resize: spec is not a dict"
        if not isinstance(record.get("done"), bool):
            return "resize: non-bool done"
    elif rtype == SHARD_MAP:
        if not isinstance(record.get("version"), int):
            return "shard_map: non-int version"
        if not isinstance(record.get("map"), dict):
            return "shard_map: map is not a dict"
    elif rtype == SNAPSHOT:
        state = record.get("state")
        if not isinstance(state, dict):
            return "snapshot: state is not a dict"
        for key in ("todo", "doing"):
            if not isinstance(state.get(key), list):
                return f"snapshot: state.{key} is not a list"
        for key in ("task_id", "epochs_todo"):
            if not isinstance(state.get(key), int):
                return f"snapshot: state.{key} is not an int"
    return None


class MasterJournal:
    """One job's journal: append with periodic snapshot/compaction,
    replay with torn-tail truncation. Thread-safe (appends come from
    dispatcher and servicer threads)."""

    def __init__(self, journal_dir: str, snapshot_every: int = 64):
        if not journal_dir:
            raise ValueError("journal_dir must be non-empty")
        self.journal_dir = journal_dir
        self.snapshot_every = max(1, int(snapshot_every))
        os.makedirs(journal_dir, exist_ok=True)
        self.path = os.path.join(journal_dir, JOURNAL_FILE)
        self._lock = threading.RLock()
        self._fh = None
        self._seq = 0
        self._since_snapshot = 0
        # Provider returning the dispatcher's exported state; called
        # with the dispatcher lock already held (appends happen inside
        # the dispatcher's critical sections), so it must be the
        # lock-free variant (TaskDispatcher._export_state_locked).
        self._snapshot_provider: Optional[Callable[[], dict]] = None
        self.generation = 0
        # Model-version high-water mark, tracked journal-side so
        # compaction (which discards the raw VERSION records) can
        # carry it inside the snapshot record.
        self._model_version = 0
        # Pending resize barrier (master/servicer.py), tracked the
        # same way: the open begin record must survive compaction so
        # a recovered master can re-offer the directive.
        self._pending_resize = None

    # ---- lifecycle -----------------------------------------------------

    def has_state(self) -> bool:
        """True when the journal holds at least one intact record —
        i.e. a restarted master has something to recover."""
        if not os.path.exists(self.path):
            return False
        for _offset, _end, _record in read_records(self.path):
            return True
        return False

    def set_snapshot_provider(self, provider: Callable[[], dict]):
        self._snapshot_provider = provider

    def open_generation(self) -> int:
        """Start (or resume) this master incarnation: scan for the
        highest generation on disk, truncate any torn tail, fence with
        generation+1, and open for append. Returns the new generation."""
        with self._lock:
            last_good_end = 0
            max_gen = -1
            if os.path.exists(self.path):
                for _offset, end, record in read_records(self.path):
                    last_good_end = end
                    self._seq = max(self._seq, int(record.get("seq", 0)))
                    if record["t"] == GENERATION:
                        max_gen = max(
                            max_gen, int(record.get("generation", -1))
                        )
                    elif record["t"] == VERSION:
                        self._model_version = max(
                            self._model_version,
                            int(record.get("model_version", 0)),
                        )
                    elif record["t"] == SNAPSHOT:
                        self._model_version = max(
                            self._model_version,
                            int(record.get("model_version", 0)),
                        )
                        self._pending_resize = record.get("resize")
                    elif record["t"] == RESIZE:
                        self._pending_resize = _pending_resize_from(
                            record
                        )
                size = os.path.getsize(self.path)
                if size > last_good_end:
                    logger.warning(
                        "journal %s: truncating torn tail "
                        "(%d byte(s) past the last intact record)",
                        self.path, size - last_good_end,
                    )
                    with open(self.path, "r+b") as fh:
                        fh.truncate(last_good_end)
            self.generation = max_gen + 1
            self._fh = open(self.path, "ab")
            self._append_locked(GENERATION, generation=self.generation)
            return self.generation

    def close(self):
        with self._lock:
            if self._fh is not None:
                self._fh.close()
                self._fh = None

    # ---- append --------------------------------------------------------

    def _append_locked(self, rtype: str, **fields):
        if self._fh is None:
            raise RuntimeError(
                "journal not open for append (call open_generation)"
            )
        self._seq += 1
        record = {"t": rtype, "seq": self._seq, **fields}
        self._fh.write(_frame(tensor_utils.dumps(record)))
        self._fh.flush()
        # fsync per record: exactly-once across NODE failure requires
        # the record durable before the RPC response leaves (a flushed-
        # but-unsynced report acked to the worker would re-train after
        # power loss). Affordable here — the control plane appends at
        # task granularity (seconds), not step granularity.
        os.fsync(self._fh.fileno())

    def append(self, rtype: str, **fields):
        """Append one event record; dispatcher-originated state
        mutations (dispatch/report) also advance the snapshot cadence
        — those are the only appends guaranteed to run under the
        dispatcher lock, which the snapshot provider requires."""
        with self._lock:
            if rtype == VERSION:
                self._model_version = max(
                    self._model_version,
                    int(fields.get("model_version", 0)),
                )
            elif rtype == RESIZE:
                self._pending_resize = _pending_resize_from(fields)
            self._append_locked(rtype, **fields)
            if rtype in (DISPATCH, REPORT):
                self._since_snapshot += 1
                if (self._snapshot_provider is not None
                        and self._since_snapshot >= self.snapshot_every):
                    self._snapshot_locked()

    def _snapshot_locked(self):
        state = self._snapshot_provider()
        self._seq += 1
        record = {
            "t": SNAPSHOT, "seq": self._seq,
            "generation": self.generation, "state": state,
            # Compaction discards the raw VERSION records; the
            # high-water mark must survive inside the snapshot.
            "model_version": int(self._model_version),
            # Same for an open resize barrier (raw RESIZE records are
            # compacted away with the rest of the prefix).
            "resize": self._pending_resize,
        }
        # Compaction: the snapshot supersedes everything before it, so
        # rewrite the file as [generation fence, snapshot] and keep
        # appending — replay cost stays bounded by the cadence. The
        # tmp+rename publish mirrors the checkpoint saver: a crash
        # mid-compaction leaves either the old journal or the new one,
        # never a half-written file.
        tmp = self.path + ".tmp"
        with open(tmp, "wb") as fh:
            fence = {
                "t": GENERATION, "seq": self._seq - 1,
                "generation": self.generation,
            }
            fh.write(_frame(tensor_utils.dumps(fence)))
            fh.write(_frame(tensor_utils.dumps(record)))
            fh.flush()
            os.fsync(fh.fileno())
        if self._fh is not None:
            self._fh.close()
        os.replace(tmp, self.path)
        self._fh = open(self.path, "ab")
        self._since_snapshot = 0

    # ---- replay --------------------------------------------------------

    def replay_records(self) -> List[dict]:
        """All intact records, torn tail dropped; raises
        ``JournalFormatError`` only on structurally invalid records
        *before* the tail (a bad frame is the tail by definition —
        framing cannot resync past it)."""
        if not os.path.exists(self.path):
            return []
        out = []
        for _offset, _end, record in read_records(self.path):
            err = validate_record(record)
            if err:
                raise JournalFormatError(f"{self.path}: {err}")
            out.append(record)
        return out

    def tail(self, n: int = 50) -> List[dict]:
        """Last ``n`` intact records — the incident bundle's
        ``journal_tail.json`` (observability/slo.IncidentRecorder):
        what the control plane was doing right before a breach.
        Read-only and crash-tolerant (torn tails drop, bad records
        return what precedes them rather than raising — an incident
        capture must never fail on a journal quirk)."""
        if not os.path.exists(self.path):
            return []
        out: List[dict] = []
        try:
            for _offset, _end, record in read_records(self.path):
                out.append(record)
        except Exception:
            logger.exception("journal tail read stopped early")
        return out[-int(n):]

    def recover_into(self, dispatcher) -> dict:
        """Replay snapshot + tail into ``dispatcher`` (freshly
        constructed with the same shard/epoch/seed config). Returns
        ``{"replayed": n, "snapshot": bool, "model_version": v,
        "generation": g, "known_workers": [...]}``.

        The dispatcher must NOT have a journal attached yet — replay
        drives its real ``get``/``report``/``create_tasks`` methods
        and must not re-append what it reads.
        """
        if getattr(dispatcher, "_journal", None) is not None:
            raise RuntimeError("detach the journal before replay")
        records = self.replay_records()
        # Only the latest snapshot matters; tail = records after it.
        snap_idx = None
        for i, record in enumerate(records):
            if record["t"] == SNAPSHOT:
                snap_idx = i
        model_version = 0
        generation = 0
        known_workers = set()
        replayed = 0
        start = 0
        pending_resize = None
        if snap_idx is not None:
            state = records[snap_idx]["state"]
            dispatcher.restore_state(state)
            generation = max(generation,
                             int(records[snap_idx].get("generation", 0)))
            model_version = max(
                model_version,
                int(records[snap_idx].get("model_version", 0)),
            )
            pending_resize = records[snap_idx].get("resize")
            # Compaction dropped the pre-snapshot dispatch records;
            # the snapshot's leases and version reports still name the
            # workers this job had.
            known_workers.update(
                int(wid) for _tid, _task, wid in state.get("doing", [])
            )
            known_workers.update(
                int(k) for k in state.get("worker_version", {})
            )
            replayed += 1
            start = snap_idx + 1
        shard_map = None
        for record in records[:start]:
            # Pre-snapshot records still carry fencing/worker facts the
            # snapshot state does not (generation high-water mark).
            if record["t"] == GENERATION:
                generation = max(generation, record["generation"])
            elif record["t"] == VERSION:
                model_version = max(model_version,
                                    record["model_version"])
            elif record["t"] == SHARD_MAP:
                shard_map = record["map"]
        for record in records[start:]:
            rtype = record["t"]
            if rtype == GENERATION:
                generation = max(generation, record["generation"])
                continue
            if rtype == SHARD_MAP:
                # Newest epoch wins (versions are monotonic by
                # construction — the authority is the only writer).
                shard_map = record["map"]
                replayed += 1
                continue
            if rtype == VERSION:
                model_version = max(model_version, record["model_version"])
                replayed += 1
                continue
            if rtype == RESIZE:
                # Barrier state, not dispatcher state: an open begin
                # survives so the recovered servicer re-offers the
                # directive; done closes it.
                pending_resize = _pending_resize_from(record)
                replayed += 1
                continue
            if rtype == SNAPSHOT:
                continue  # unreachable (snap_idx is the last one)
            if rtype == CREATE_TASKS:
                dispatcher.create_tasks(
                    record["task_type"],
                    model_version=record.get("model_version", -1),
                )
                replayed += 1
                continue
            if rtype == DISPATCH:
                wid = record["worker_id"]
                known_workers.add(wid)
                task = dispatcher.get(wid)
                want = record["task"]
                if task is None or task.task_id != record["task_id"] or (
                    (task.shard_name, task.start, task.end, task.type)
                    != (want.get("shard_name"), want.get("start"),
                        want.get("end"), want.get("type"))
                ):
                    # The state machine disagreed with the journal —
                    # a bug or a journal from different job config.
                    # Fail loudly; recovering wrong state silently
                    # would double- or under-train.
                    raise JournalFormatError(
                        f"replay diverged at seq {record['seq']}: "
                        f"journal dispatched task {record['task_id']} "
                        f"({want.get('shard_name')}:{want.get('start')}-"
                        f"{want.get('end')}), state machine produced "
                        f"{task.task_id if task else None}"
                    )
                replayed += 1
                continue
            if rtype == REPORT:
                dispatcher.report(
                    record["task_id"], record["success"],
                    err_reason=record.get("err_reason", ""),
                )
                replayed += 1
        # Leases survive the crash: tasks in doing stay leased to the
        # workers riding out the outage; their start clocks reset to
        # replay time (dispatcher.get stamped time.time()), so the
        # straggler deadline counts from recovery, and a worker that
        # died DURING the outage is caught by the normal timeout path.
        return {
            "replayed": replayed,
            "snapshot": snap_idx is not None,
            "model_version": model_version,
            "generation": generation,
            "known_workers": sorted(known_workers),
            "resize": pending_resize,
            "shard_map": shard_map,
        }


def recover_master_state(journal: "MasterJournal", dispatcher,
                         servicer=None,
                         metrics_registry=None) -> Dict:
    """The full master-side recovery sequence: replay the journal into
    the dispatcher, re-arm the servicer (model version high-water mark
    + fresh straggler clocks for surviving leases), bump the
    generation fence, re-attach the journal for write-through, and
    publish recovery telemetry. Returns the replay stats dict with
    ``recovery_seconds`` added.

    Shared by ``master/main.py`` (process restart) and the chaos
    restart seam (``testing/cluster.MiniCluster.restart_master``) so
    the drill exercises the same code path production uses.
    """
    import time

    from elasticdl_tpu.observability import default_registry, tracing

    registry = metrics_registry or default_registry()
    t0 = time.monotonic()
    with tracing.Tracer("master").span("recover") as sp:
        stats = journal.recover_into(dispatcher)
        generation = journal.open_generation()
        dispatcher.attach_journal(journal)
        if servicer is not None:
            servicer.model_version = max(
                servicer.model_version, stats["model_version"]
            )
            servicer.generation = generation
            servicer.seed_task_start_times(
                list(dispatcher.doing_start_times())
            )
            if stats.get("resize"):
                # A master crash mid-resize: re-offer the journaled
                # pending directive (acks are volatile; workers that
                # applied it already re-ack idempotently).
                servicer.rearm_resize(stats["resize"])
        sp.set(replayed=int(stats["replayed"]),
               generation=int(generation))
    elapsed = time.monotonic() - t0
    stats["generation"] = generation
    stats["recovery_seconds"] = elapsed
    registry.histogram(
        "master_recovery_seconds",
        "Journal replay + re-arm latency on master restart",
    ).observe(elapsed)
    registry.counter(
        "master_journal_replayed_records_total",
        "Journal records replayed into recovered dispatchers",
    ).inc(stats["replayed"])
    logger.info(
        "master recovered from %s: %d record(s) replayed "
        "(snapshot=%s), generation %d, %d leased task(s) surviving",
        journal.path, stats["replayed"], stats["snapshot"],
        generation, len(dispatcher.doing_start_times()),
    )
    return stats
