"""Dynamic data sharding: the master's task queues.

Counterpart of the reference's ``elasticdl/python/master/task_dispatcher.py``
(``_TaskDispatcher``): shards are split into tasks of
``records_per_task`` records; workers pull tasks from ``todo``, the master
tracks them in ``doing``; failed/dead-worker tasks are re-queued with a
retry cap; training tasks regenerate per epoch; when all training work is
done a deferred TRAIN_END_CALLBACK task is created (reference
task_dispatcher.py:206-241). This mechanism — not checkpoint-restart — is
what makes preemption cheap.
"""

import collections
import dataclasses
import random
import threading
import time
import weakref
from typing import Callable, Dict, List, Optional, Tuple

from elasticdl_tpu.common.constants import MAX_TASK_RETRIES, TaskType
from elasticdl_tpu.common.log_utils import get_logger
from elasticdl_tpu.common.task import Task
from elasticdl_tpu.master.journal import (
    _stream_partition,
    advance_stream_watermark,
    new_stream_state,
    normalize_stream_state,
)

logger = get_logger("task_dispatcher")

# Bounded ledger of recently resolved task ids → original outcome.
# Serves two callers: at-least-once RPC retries (RpcStub re-sends a
# report whose response was lost) and post-crash re-reports from
# workers that rode out a master restart — both must get the original
# outcome back instead of the "Unknown task id" path, or accounting
# drifts. Sized to cover many report round-trips of in-flight retry
# ambiguity without growing with job length.
RESOLVED_LEDGER_SIZE = 512


class JobCounters:
    """Per-task-type record counters (reference task_dispatcher.py:40-61)."""

    def __init__(self):
        self.total_records = {}
        self.failed_records = {}

    def add_completed(self, task_type: str, n: int):
        self.total_records[task_type] = (
            self.total_records.get(task_type, 0) + n
        )

    def add_failed(self, task_type: str, n: int):
        self.failed_records[task_type] = (
            self.failed_records.get(task_type, 0) + n
        )


class TaskDispatcher:
    def __init__(
        self,
        training_shards: Dict[str, Tuple[int, int]],
        evaluation_shards: Optional[Dict[str, Tuple[int, int]]] = None,
        prediction_shards: Optional[Dict[str, Tuple[int, int]]] = None,
        records_per_task: int = 64,
        num_epochs: int = 1,
        shuffle: bool = True,
        seed: int = 0,
        metrics_registry=None,
        streaming: bool = False,
    ):
        self._lock = threading.Lock()
        self._training_shards = dict(training_shards or {})
        self._evaluation_shards = dict(evaluation_shards or {})
        self._prediction_shards = dict(prediction_shards or {})
        self._records_per_task = records_per_task
        self._epochs_todo = num_epochs
        self._shuffle = shuffle
        self._rng = random.Random(seed)

        self._todo: List[Task] = []
        # task_id -> (task, worker_id, start_time)
        self._doing: Dict[int, Tuple[Task, int, float]] = {}
        self._task_id = 0
        # MaxStepsStopping support (reference callbacks.py:57-98): a cap on
        # dispatched TRAINING records; 0 = unlimited. Enforced at dispatch,
        # which is exact — the reference's worker-side version check is
        # best-effort across workers.
        self._max_train_records = 0
        self._train_records_dispatched = 0
        self._task_retry_count: Dict[str, int] = {}
        self._deferred_callbacks: List[Callable] = []
        self._worker_version: Dict[int, int] = {}
        # Workers being drained (elastic scale-down): fenced out of
        # dispatch so a dying pod cannot lease fresh work during its
        # SIGTERM grace — its DELETED event is deliberately ignored by
        # the instance manager, so a task leased post-drain would have
        # no death event to recover it. Volatile on purpose: not
        # journaled/exported (a fence only outlives its pod by the
        # grace window, and replay equivalence must not depend on it).
        self._fenced_workers = set()
        # Streaming-ingestion mode (master/stream_ingest.py,
        # docs/online_learning.md): tasks come from a live stream tail
        # instead of the epoch walk, so ``finished`` stays False while
        # the stream is open and per-partition watermark state rides
        # this dispatcher's snapshots. The watermark algebra is shared
        # with the journal's fold functions — one implementation for
        # live accounting, append-time mirroring, and replay.
        self._streaming = bool(streaming)
        self._stream_closed = False
        self._stream = new_stream_state()
        self.counters = JobCounters()
        # task_id -> (task, worker_id, requeued): the idempotent-report
        # ledger (see RESOLVED_LEDGER_SIZE above). OrderedDict as a
        # FIFO ring.
        self._resolved = collections.OrderedDict()
        # Write-ahead journal (master/journal.py); attached AFTER
        # construction (attach_journal) so the constructor's initial
        # create_tasks is part of the deterministic base state, not a
        # journaled event — replay rebuilds it from the same config.
        self._journal = None

        # Telemetry: queue health as pull-time gauges (evaluated per
        # scrape; reading a list length needs no lock) + dispatch
        # outcome counters. Families are idempotent on the shared
        # registry; set_function re-binds to the newest dispatcher.
        from elasticdl_tpu.observability import default_registry, tracing

        registry = metrics_registry or default_registry()
        # Dispatch spans join the pulling task's trace (the RPC server
        # span — or, in-process, the worker's own task span — is the
        # ambient parent); free with no recorder installed.
        self._trace = tracing.Tracer("master")
        # weakref: the registry is process-global and outlives
        # dispatchers; a strong closure would pin a drained job's task
        # lists and shard metadata for the process lifetime.
        self_ref = weakref.ref(self)
        registry.gauge(
            "master_task_queue_depth", "Tasks waiting in the todo queue"
        ).set_function(
            lambda: len(d._todo) if (d := self_ref()) is not None else 0.0
        )
        registry.gauge(
            "master_tasks_doing", "Tasks currently leased to workers"
        ).set_function(
            lambda: len(d._doing) if (d := self_ref()) is not None else 0.0
        )
        self._m_dispatched = registry.counter(
            "master_tasks_dispatched_total",
            "Tasks handed to workers", ["type"],
        )
        self._m_completed = registry.counter(
            "master_tasks_completed_total",
            "Tasks reported successful", ["type"],
        )
        self._m_failed = registry.counter(
            "master_tasks_failed_total",
            "Tasks failed permanently (retry cap exhausted)", ["type"],
        )
        self._m_requeued = registry.counter(
            "master_task_requeues_total",
            "Failed/preempted tasks re-queued for another worker",
        )

        if self._training_shards:
            self.create_tasks(TaskType.TRAINING)
            self._epochs_todo -= 1
        elif self._evaluation_shards and not self._streaming:
            # Streaming jobs hold their eval shards for
            # watermark-triggered rounds (master/stream_ingest.py) —
            # auto-queuing them here would run an eval round before
            # the stream committed anything.
            self.create_tasks(TaskType.EVALUATION)
        elif self._prediction_shards:
            self.create_tasks(TaskType.PREDICTION)

    # ---- task creation -------------------------------------------------

    def _shards_for(self, task_type: str) -> Dict[str, Tuple[int, int]]:
        return {
            TaskType.TRAINING: self._training_shards,
            TaskType.EVALUATION: self._evaluation_shards,
            TaskType.PREDICTION: self._prediction_shards,
        }[task_type]

    def _build_tasks(self, task_type: str,
                     model_version: int = -1) -> List[Task]:
        """Split shards into records_per_task-sized tasks (pure; shared by
        initial creation and per-epoch regeneration)."""
        tasks = []
        for shard_name, (start, count) in self._shards_for(
            task_type
        ).items():
            for begin in range(start, start + count,
                               self._records_per_task):
                end = min(begin + self._records_per_task, start + count)
                tasks.append(
                    Task(
                        shard_name=shard_name,
                        start=begin,
                        end=end,
                        type=task_type,
                        model_version=model_version,
                    )
                )
        if self._shuffle and task_type == TaskType.TRAINING:
            self._rng.shuffle(tasks)
        return tasks

    def create_tasks(self, task_type: str, model_version: int = -1):
        """Split shards into tasks and queue them
        (reference task_dispatcher.py:134-204)."""
        with self._lock:
            tasks = self._build_tasks(task_type, model_version)
            if task_type == TaskType.EVALUATION:
                # Eval tasks jump the queue so they run close to the version
                # that triggered them (reference prepends eval tasks).
                self._todo = tasks + self._todo
            else:
                self._todo.extend(tasks)
            if self._journal is not None:
                self._journal.append(
                    "create_tasks", task_type=str(task_type),
                    model_version=int(model_version),
                )
            logger.info("Created %d %s tasks", len(tasks), task_type)

    def add_deferred_callback(self, callback: Callable):
        with self._lock:
            self._deferred_callbacks.append(callback)

    def create_train_end_callback_task(self):
        """One final task so a worker can run callbacks_list.on_train_end
        (reference task_dispatcher.py:206-241)."""
        with self._lock:
            if not self._training_shards:
                return
            name = next(iter(self._training_shards))
            self._todo.append(
                Task(shard_name=name, start=0, end=0,
                     type=TaskType.TRAIN_END_CALLBACK)
            )

    # ---- streaming mode (master/stream_ingest.py) ----------------------

    @property
    def is_streaming(self) -> bool:
        return self._streaming

    def register_stream_partition(self, partition: str):
        """Introduce a stream partition (idempotent). Journaled so a
        recovered master knows the partition set even before its first
        task lands."""
        partition = str(partition)
        with self._lock:
            self._streaming = True
            if partition in self._stream["partitions"]:
                return
            _stream_partition(self._stream, partition)
            if self._journal is not None:
                self._journal.append(
                    "stream", event="register", partition=partition
                )

    def create_stream_tasks(self, partition: str, start: int, end: int,
                            model_version: int = -1) -> int:
        """Queue offset-ranged TRAINING tasks covering ``[start, end)``
        of ``partition``, split at ``records_per_task``. One STREAM
        journal event covers the whole range: stream tasks come from
        the live tail (not CREATE_TASKS' epoch walk), so replay
        re-enqueues them from this record and the subsequent DISPATCH
        records must find the identical todo queue — the split is
        deterministic in (start, end, records_per_task). Ranges at or
        below the partition's ``next`` cursor are clipped (idempotent
        for an ingestor retrying after a lost ack). Returns the number
        of tasks queued."""
        partition = str(partition)
        with self._lock:
            self._streaming = True
            part = _stream_partition(self._stream, partition)
            start = max(int(start), int(part["next"]))
            end = int(end)
            if end <= start:
                return 0
            tasks = []
            for begin in range(start, end, self._records_per_task):
                tasks.append(Task(
                    shard_name=partition,
                    start=begin,
                    end=min(begin + self._records_per_task, end),
                    type=TaskType.TRAINING,
                    model_version=int(model_version),
                    extended_config={"stream": True},
                ))
            self._todo.extend(tasks)
            part["next"] = end
            if self._journal is not None:
                self._journal.append(
                    "stream", event="tasks", partition=partition,
                    start=int(start), end=int(end),
                    model_version=int(model_version),
                )
            return len(tasks)

    def close_stream(self):
        """No more stream tasks will be generated: ``finished`` may
        fire once the queues drain (a drill's clean shutdown, or an
        operator retiring the streaming job — the gang scheduler's
        completion sweep then marks the job done)."""
        with self._lock:
            self._stream_closed = True

    def stream_progress(self) -> Dict[str, dict]:
        """Per-partition {committed, next, pending} — ``committed`` is
        the exclusive watermark: every offset below it resolved
        successfully AND its REPORT record is fsynced. The ingestor's
        resume point and the ``/stream`` endpoint's body."""
        with self._lock:
            return {
                p: {
                    "committed": int(s["committed"]),
                    "next": int(s["next"]),
                    "pending": dict(s["pending"]),
                }
                for p, s in self._stream["partitions"].items()
            }

    # ---- worker-facing -------------------------------------------------

    def set_max_steps(self, max_steps: int, minibatch_size: int):
        """Bound total dispatched training records to
        ``max_steps × minibatch_size``."""
        with self._lock:
            self._max_train_records = (
                max_steps * minibatch_size if max_steps > 0 else 0
            )

    def _train_cap_reached_locked(self) -> bool:
        return bool(self._max_train_records) and (
            self._train_records_dispatched >= self._max_train_records
        )

    def _epochs_pending_locked(self) -> bool:
        return (
            self._epochs_todo > 0
            and bool(self._training_shards)
            and not self._train_cap_reached_locked()
        )

    def get(self, worker_id: int) -> Optional[Task]:
        """Pop a task for a worker; None when nothing is available
        (the servicer converts None into a WAIT task while unfinished)."""
        with self._trace.span("dispatch", worker=int(worker_id)) as sp:
            task = self._get(worker_id)
            if task is not None:
                sp.set(task_id=int(task.task_id), type=str(task.type))
            else:
                # WAIT / drained polls would drown the dispatch stats.
                sp.discard()
            return task

    def fence_worker(self, worker_id: int):
        """Stop dispatching to ``worker_id`` (drain_worker calls this
        BEFORE deleting the pod). Its get_task polls see WAIT until the
        pod dies."""
        with self._lock:
            self._fenced_workers.add(int(worker_id))

    def _get(self, worker_id: int) -> Optional[Task]:
        callbacks = []
        task = None
        with self._lock:
            if worker_id in self._fenced_workers:
                return None
            while True:
                if not self._todo and self._epochs_pending_locked():
                    self._create_training_tasks_locked()
                    self._epochs_todo -= 1
                if not self._todo:
                    break
                candidate = self._todo.pop(0)
                if (
                    candidate.type == TaskType.TRAINING
                    and self._max_train_records
                ):
                    remaining = (
                        self._max_train_records
                        - self._train_records_dispatched
                    )
                    if remaining <= 0:
                        continue  # drop: max_steps reached
                    if candidate.num_records > remaining:
                        # Trim the final task so the bound is exact at
                        # record (= step) granularity, not task
                        # granularity.
                        candidate.end = candidate.start + remaining
                task = candidate
                break
            if task is not None:
                if task.type == TaskType.TRAINING:
                    self._train_records_dispatched += task.num_records
                self._task_id += 1
                task.task_id = self._task_id
                self._doing[task.task_id] = (task, worker_id, time.time())
                self._m_dispatched.labels(task.type).inc()
                if self._journal is not None:
                    # Inside the lock, so the journal's event order
                    # matches the state-mutation order exactly —
                    # replay re-runs these ops through this same state
                    # machine and must see the same interleaving.
                    self._journal.append(
                        "dispatch", task_id=int(task.task_id),
                        worker_id=int(worker_id),
                        generation=int(self._journal.generation),
                        task=task.to_dict(),
                    )
            elif (
                not self._doing
                and not self._epochs_pending_locked()
                and self._deferred_callbacks
            ):
                # Dropping capped tasks can drain the queue outside
                # report(); fire deferred callbacks here too so the
                # train-end task still gets created.
                callbacks, self._deferred_callbacks = (
                    self._deferred_callbacks, []
                )
        for cb in callbacks:
            cb()
        if callbacks:
            return self._get(worker_id)
        return task

    def _create_training_tasks_locked(self):
        tasks = self._build_tasks(TaskType.TRAINING)
        self._todo.extend(tasks)
        logger.info("Created %d training tasks (new epoch)", len(tasks))

    def report(self, task_id: int, success: bool,
               err_reason: str = "") -> Tuple[Optional[Task], int, bool]:
        """Worker reports task completion (reference :286-350). Failed tasks
        re-queue at the front, up to MAX_TASK_RETRIES per shard range.
        Returns (task, worker_id, requeued)."""
        task, worker_id, requeued, _duplicate = self.apply_report(
            task_id, success, err_reason
        )
        return task, worker_id, requeued

    def apply_report(
        self, task_id: int, success: bool, err_reason: str = ""
    ) -> Tuple[Optional[Task], int, bool, bool]:
        """``report`` plus a ``duplicate`` flag, decided atomically
        under the lock: True iff the outcome came from the resolved
        ledger rather than a first application. The servicer needs
        the distinction to run report side effects (eval
        complete_task) exactly once even when at-least-once RPC
        retries race each other."""
        callbacks = []
        requeued = False
        with self._lock:
            entry = self._doing.pop(task_id, None)
            if entry is None:
                resolved = self._resolved.get(task_id)
                if resolved is not None:
                    # At-least-once RPC (or a re-report across a master
                    # restart): the first application already counted
                    # this task; hand back the original outcome instead
                    # of re-applying or warning.
                    logger.info(
                        "Task %d already resolved; returning original "
                        "outcome (duplicate report)", task_id,
                    )
                    return (*resolved, True)
                logger.warning("Unknown task id %d reported", task_id)
                return None, -1, False, False
            task, worker_id, _start = entry
            if success:
                self.counters.add_completed(task.type, task.num_records)
                self._m_completed.labels(task.type).inc()
                # Clear the shard's burned retries: the map otherwise
                # grows without bound across epochs, and next epoch's
                # identical shard key would inherit this epoch's
                # failures against its retry budget.
                self._task_retry_count.pop(
                    f"{task.shard_name}:{task.start}:{task.end}", None
                )
            else:
                key = f"{task.shard_name}:{task.start}:{task.end}"
                # Graceful preemption hand-backs (SIGTERM before the
                # pod dies) are not task failures: no records were
                # consumed and no real error occurred, so they must not
                # burn the shard's retry budget — repeatedly-preempted
                # shards would otherwise be dropped from training.
                preempted = err_reason.startswith("preempted")
                retries = self._task_retry_count.get(key, 0) + (
                    0 if preempted else 1
                )
                self._task_retry_count[key] = retries
                if retries <= MAX_TASK_RETRIES:
                    logger.info(
                        "Task %d failed (%s), re-queueing (retry %d)",
                        task_id, err_reason, retries,
                    )
                    # Fresh copy: the popped object is still referenced by
                    # the reporting worker; re-dispatch must not mutate it.
                    self._todo.insert(0, dataclasses.replace(task))
                    requeued = True
                    self._m_requeued.inc()
                    if task.type == TaskType.TRAINING:
                        # Re-queued records will be re-dispatched; release
                        # them from the max-steps budget.
                        self._train_records_dispatched -= task.num_records
                else:
                    self.counters.add_failed(task.type, task.num_records)
                    self._m_failed.labels(task.type).inc()
                    logger.error(
                        "Task %d failed permanently after %d retries (%s)",
                        task_id, MAX_TASK_RETRIES, err_reason,
                    )
            self._resolved[task_id] = (task, worker_id, requeued)
            while len(self._resolved) > RESOLVED_LEDGER_SIZE:
                self._resolved.popitem(last=False)
            stream_fields = {}
            if (task.extended_config or {}).get("stream"):
                # Offset commit is atomic with the resolution: the
                # stream fields ride the same REPORT record (see
                # journal.apply_stream_report_record), and the live
                # watermark advances only on success — a requeued or
                # failed range stays uncommitted until its retry
                # resolves, so recovery never re-acks.
                if success:
                    advance_stream_watermark(
                        _stream_partition(
                            self._stream, task.shard_name
                        ),
                        task.start, task.end,
                    )
                stream_fields = {
                    "stream_partition": str(task.shard_name),
                    "stream_start": int(task.start),
                    "stream_end": int(task.end),
                }
            if self._journal is not None:
                # Appended after the mutation completes (still inside
                # the lock): a snapshot triggered by this append must
                # capture the post-report state, and replay re-derives
                # the requeue decision from the same inputs. The
                # task's type/version and the requeue verdict ride
                # along so the eval plane's round progress is ATOMIC
                # with the resolution (journal.apply_eval_report_record
                # — a separate append would leave a crash window that
                # wedges the round).
                self._journal.append(
                    "report", task_id=int(task_id),
                    success=bool(success), err_reason=str(err_reason),
                    task_type=str(task.type),
                    model_version=int(task.model_version),
                    requeued=bool(requeued),
                    **stream_fields,
                )
            todo_undroppable = [
                t for t in self._todo
                if not (
                    t.type == TaskType.TRAINING
                    and self._train_cap_reached_locked()
                )
            ]
            if (
                not todo_undroppable
                and not self._doing
                and not self._epochs_pending_locked()
                and self._deferred_callbacks
            ):
                callbacks, self._deferred_callbacks = (
                    self._deferred_callbacks, []
                )
        # Fired outside the lock: callbacks typically append new tasks
        # (e.g. create_train_end_callback_task re-acquires the lock).
        for cb in callbacks:
            cb()
        return task, worker_id, requeued, False

    def recover_tasks(self, worker_id: int):
        """Re-queue all doing tasks of a dead worker
        (reference task_dispatcher.py:352-364)."""
        with self._lock:
            ids = [
                tid for tid, (_t, wid, _s) in self._doing.items()
                if wid == worker_id
            ]
        for tid in ids:
            self.report(tid, False, err_reason="worker_dead")

    def preempt_leases(self, reason: str = "preempted: gang released"
                       ) -> int:
        """Hand every leased task back to the front of the queue —
        the gang scheduler evicting this job (master/scheduler.py).
        Rides the graceful-preemption path of ``apply_report`` (the
        ``preempted`` err_reason prefix), so retry budgets are NOT
        burned and the resolved ledger keeps late duplicate reports
        from the evicted workers idempotent. Returns the number of
        leases handed back."""
        if not reason.startswith("preempted"):
            raise ValueError(
                "preempt reason must start with 'preempted'"
            )
        with self._lock:
            ids = list(self._doing.keys())
        for tid in ids:
            self.report(tid, False, err_reason=reason)
        return len(ids)

    # ---- status --------------------------------------------------------

    def finished(self) -> bool:
        with self._lock:
            if self._streaming and not self._stream_closed:
                # An open stream is never done — the completion sweep
                # (gang scheduler) and the servicer's finished RPC must
                # keep the job live even when the tail is momentarily
                # drained (todo and doing both empty).
                return False
            remaining = [
                t for t in self._todo
                if not (
                    t.type == TaskType.TRAINING
                    and self._train_cap_reached_locked()
                )
            ]
            return (
                not remaining
                and not self._doing
                and not self._epochs_pending_locked()
            )

    def count_tasks(self, task_type: str) -> int:
        """Tasks of ``task_type`` currently queued or leased (the
        eval plane's recovery sanity check)."""
        with self._lock:
            n = sum(1 for t in self._todo if t.type == task_type)
            n += sum(
                1 for t, _wid, _s in self._doing.values()
                if t.type == task_type
            )
            return n

    def queue_depths(self) -> Tuple[int, int]:
        """(todo, doing) sizes for queue-health consumers (the
        autoscaler's signals) — lock-free ``len`` reads, same pattern
        as the ``master_task_queue_depth`` gauges above."""
        return len(self._todo), len(self._doing)

    def doing_tasks_of(self, worker_id: int) -> List[int]:
        with self._lock:
            return [
                tid for tid, (_t, wid, _s) in self._doing.items()
                if wid == worker_id
            ]

    def doing_start_times(self) -> Dict[int, Tuple[int, float]]:
        """task_id -> (worker_id, start_time) for timeout detection."""
        with self._lock:
            return {
                tid: (wid, start)
                for tid, (_t, wid, start) in self._doing.items()
            }

    def record_worker_version(self, worker_id: int, version: int):
        with self._lock:
            self._worker_version[worker_id] = version

    # ---- journal (master/journal.py) -----------------------------------

    def attach_journal(self, journal):
        """Write dispatch/report/create_tasks through ``journal`` from
        now on; wires the snapshot provider to the locked exporter
        (appends run inside this dispatcher's critical sections)."""
        with self._lock:
            self._journal = journal
        journal.set_snapshot_provider(self._export_state_locked)

    def detach_journal(self):
        with self._lock:
            self._journal = None

    def export_state(self) -> dict:
        """Full serializable dispatcher state (journal snapshots and
        the chaos master-restart equivalence audit)."""
        with self._lock:
            return self._export_state_locked()

    def _export_state_locked(self) -> dict:
        version, internal, gauss = self._rng.getstate()
        return {
            "todo": [t.to_dict() for t in self._todo],
            "doing": [
                [int(tid), t.to_dict(), int(wid)]
                for tid, (t, wid, _s) in self._doing.items()
            ],
            "task_id": int(self._task_id),
            "epochs_todo": int(self._epochs_todo),
            "max_train_records": int(self._max_train_records),
            "train_records_dispatched": int(
                self._train_records_dispatched
            ),
            "retry": dict(self._task_retry_count),
            "completed": dict(self.counters.total_records),
            "failed": dict(self.counters.failed_records),
            "worker_version": {
                str(k): int(v) for k, v in self._worker_version.items()
            },
            "resolved": [
                [int(tid), t.to_dict() if t is not None else None,
                 int(wid), bool(rq)]
                for tid, (t, wid, rq) in self._resolved.items()
            ],
            # Epoch-regeneration shuffle must continue the same
            # sequence after recovery, or the replayed run diverges
            # from a never-crashed one under shuffle=True.
            "rng": [int(version), [int(x) for x in internal], gauss],
            "deferred_pending": len(self._deferred_callbacks),
            # Stream-plane state rides the dispatcher snapshot so
            # compaction keeps the committed watermarks (the resume
            # point) without a separate journal mirror lifecycle.
            "streaming": bool(self._streaming),
            "stream_closed": bool(self._stream_closed),
            "stream": self._stream,
        }

    def restore_state(self, state: dict):
        """Install a journal snapshot. Leased (doing) tasks stay
        leased — the workers holding them survive the master crash and
        re-report; their start clocks reset to now so the straggler
        deadline counts from recovery."""
        now = time.time()
        with self._lock:
            self._todo = [Task.from_dict(d) for d in state["todo"]]
            self._doing = {
                int(tid): (Task.from_dict(d), int(wid), now)
                for tid, d, wid in state["doing"]
            }
            self._task_id = int(state["task_id"])
            self._epochs_todo = int(state["epochs_todo"])
            self._max_train_records = int(state["max_train_records"])
            self._train_records_dispatched = int(
                state["train_records_dispatched"]
            )
            self._task_retry_count = dict(state["retry"])
            self.counters.total_records = dict(state["completed"])
            self.counters.failed_records = dict(state["failed"])
            self._worker_version = {
                int(k): int(v)
                for k, v in state.get("worker_version", {}).items()
            }
            self._resolved = collections.OrderedDict(
                (int(tid),
                 (Task.from_dict(d) if d is not None else None,
                  int(wid), bool(rq)))
                for tid, d, wid, rq in state.get("resolved", [])
            )
            rng = state.get("rng")
            if rng:
                self._rng.setstate((rng[0], tuple(rng[1]), rng[2]))
            self._streaming = bool(
                state.get("streaming", self._streaming)
            )
            self._stream_closed = bool(state.get("stream_closed", False))
            self._stream = normalize_stream_state(state.get("stream"))
            if state.get("deferred_pending", 0) == 0:
                # The pre-crash dispatcher had already fired its
                # deferred callbacks (train-end task created); firing
                # the re-registered ones again would duplicate it.
                self._deferred_callbacks = []
