"""Hot-standby master: warm failover in under a second.

Before this module, a master death meant restart-and-replay: wait for
the pod to reschedule, pay a cold process start, replay the journal,
and only then serve again — seconds to minutes of control-plane
outage that docs/fault_tolerance.md could only size
``--master_reattach_grace`` around. ``StandbyMaster`` turns that into
a warm failover, the resource-orchestration shape of Podracer
(arxiv 2104.06272):

- **Continuous replay.** The standby tails the primary's journal
  (``MasterJournal`` read paths — the same file, on shared storage)
  and keeps a WARM dispatcher: each poll applies only the records
  appended since the last one (``journal.apply_replay`` with a carry;
  a compaction snapshot with a newer seq supersedes wholesale, so
  rewrites are transparent). Takeover pays the un-replayed *tail*,
  not the journal. ``master_standby_lag_records`` gauges how far
  behind the warm state runs.
- **Heartbeats.** A ``ping`` to the primary every
  ``heartbeat_secs``; ``miss_threshold`` consecutive failures
  (channel rebuilt between attempts — a refused gRPC channel can
  wedge) declare the primary dead. Successful beats observe
  ``master_primary_heartbeat_seconds``, which the default SLO ruleset
  watches with an absence rule: a standby that stops confirming
  heartbeats means the job's failover protection is gone.
- **Fencing, then takeover.** Promotion publishes the journal fence
  (``fence = last seen generation + 1``) *before* opening its own
  generation: from that instant a zombie primary — alive but
  partitioned — cannot land another journal byte (the append path
  re-checks the fence under an flock) and its RPC handlers answer
  ``stale_master``, so split-brain is structurally impossible. Then
  the warm dispatcher is re-armed through the same
  ``rearm_recovered_master`` sequence cold recovery uses (eval round
  restored, straggler clocks seeded, pending resize re-offered) and
  the RPC server binds the advertised address. Workers and
  row-services re-attach through their existing reconnect retry
  (``MasterClient`` rotates through its address list).
  ``master_failover_seconds`` observes detection→serving.

The drill that proves it: ``chaos/failover_drill.py`` (``make
failover-smoke``) SIGKILLs real primary processes mid-lease,
mid-eval-round, and mid-resize-barrier, and gates takeover downtime
at ≥5x better than restart-and-replay on the same kill schedule
(FAILOVER_DRILL.json).
"""

import threading
import time
from typing import Callable, Optional

from elasticdl_tpu.common.log_utils import get_logger
from elasticdl_tpu.master.journal import (
    JournalFormatError,
    MasterJournal,
    apply_replay,
    new_replay_carry,
    rearm_recovered_master,
)
from elasticdl_tpu.master.servicer import SERVICE_NAME

logger = get_logger("master_standby")


class StandbyMaster:
    """One warm standby for one journaled master.

    ``dispatcher_factory()`` must build a dispatcher from the
    IDENTICAL job config the primary used (shards, sizing, seed) —
    same contract as every journal-recovery path. ``assemble(
    dispatcher, journal)`` returns ``(evaluation_service, servicer)``
    wired around them (called at promotion, AFTER the new generation
    is open, so the servicer may stamp it; the journal must not be
    attached to the eval service — promotion attaches it after the
    restore). ``serve_addr`` is the address the promoted master binds
    (the advertised address workers re-resolve to).
    """

    def __init__(
        self,
        journal_dir: str,
        dispatcher_factory: Callable,
        assemble: Callable,
        primary_addr: str,
        serve_addr: str,
        heartbeat_secs: float = 1.0,
        miss_threshold: int = 3,
        poll_secs: float = 0.5,
        bind_retries: int = 40,
        bind_retry_secs: float = 0.25,
        metrics_registry=None,
        on_promoted: Optional[Callable] = None,
        handlers_factory: Optional[Callable] = None,
    ):
        from elasticdl_tpu.observability import default_registry

        self._journal = MasterJournal(journal_dir)
        self._dispatcher_factory = dispatcher_factory
        self._assemble = assemble
        self._primary_addr = primary_addr
        self._serve_addr = serve_addr
        self._heartbeat_secs = max(0.01, float(heartbeat_secs))
        self._miss_threshold = max(1, int(miss_threshold))
        self._poll_secs = max(0.01, float(poll_secs))
        self._bind_retries = int(bind_retries)
        self._bind_retry_secs = float(bind_retry_secs)
        self._on_promoted = on_promoted
        # fn(servicer) -> handler dict for the promoted server;
        # defaults to servicer.handlers(). Lets embedders (the
        # failover drill's control-plane stand-in) add aux methods.
        self._handlers_factory = handlers_factory
        self._stop = threading.Event()
        self._stub = None
        self._misses = 0
        # Warm state: a journal-replayed dispatcher plus the carry
        # that lets the next poll apply only fresh records.
        self._dispatcher = dispatcher_factory()
        self._carry = new_replay_carry()
        # (size, mtime_ns) of the journal at the last poll: an
        # unchanged file skips the read entirely, so idle polls cost
        # one stat — not a full decode of snapshot + eval folds.
        self._last_stat = None
        # Incremental read cursor: byte offset of the first unread
        # frame, plus the head frame's (seq, type) — a changed head
        # means compaction rewrote the file and the cursor resets.
        # Active-job polls therefore decode only the appended TAIL,
        # matching the "pays the tail, not the journal" design on the
        # read side too (the seq gate in apply_replay makes any
        # fallback full re-read double-apply-free).
        self._read_cursor = 0
        self._head_sig = None
        # Promoted artifacts (None until takeover).
        self.promoted = False
        self.server = None
        self.servicer = None
        self.eval_service = None
        self.dispatcher = None
        self.generation = -1
        self.takeover_stats: Optional[dict] = None

        registry = metrics_registry or default_registry()
        self._m_lag = registry.gauge(
            "master_standby_lag_records",
            "Journal records the standby's warm replay is behind "
            "(sampled at each poll, before catching up)",
        )
        self._m_replayed = registry.counter(
            "master_standby_replayed_records_total",
            "Journal records folded into the standby's warm state",
        )
        self._m_heartbeat = registry.histogram(
            "master_primary_heartbeat_seconds",
            "Primary heartbeat round-trip observed by the standby "
            "(the default SLO ruleset alerts on its ABSENCE: no "
            "beats = failover protection is gone)",
        )
        self._m_failover = registry.histogram(
            "master_failover_seconds",
            "Hot-standby takeover latency: primary declared dead -> "
            "new incarnation serving on the advertised address",
        )

    # ---- journal tailing (continuous replay) ---------------------------

    def poll_journal(self) -> int:
        """Fold any newly-appended records into the warm dispatcher;
        returns how many records were applied. Divergence or mid-file
        corruption rebuilds the warm state from scratch (the cold
        path) rather than serving wrong state later."""
        import os

        try:
            st = os.stat(self._journal.path)
            sig = (st.st_size, st.st_mtime_ns)
        except OSError:
            return 0  # journal not created yet
        if sig == self._last_stat:
            return 0  # nothing appended (and compaction moves mtime)
        try:
            head = self._journal.head_signature()
            if head != self._head_sig or st.st_size < self._read_cursor:
                # Compaction rewrote the file (or first poll): the
                # cursor's boundary is meaningless — read from the top.
                self._head_sig = head
                self._read_cursor = 0
            records = []
            cursor = self._read_cursor
            from elasticdl_tpu.master.journal import (
                read_records,
                validate_record,
            )

            for _offset, end, record in read_records(
                self._journal.path, start=self._read_cursor
            ):
                err = validate_record(record)
                if err:
                    raise JournalFormatError(err)
                records.append(record)
                cursor = end
        except JournalFormatError:
            logger.exception("journal unreadable; will re-poll")
            return 0
        # Committed only after a successful read: records appended
        # between the stat and the read re-read next poll (seq-gated,
        # so re-reads are free of double-apply).
        self._last_stat = sig
        self._read_cursor = cursor
        if not records:
            return 0
        behind = sum(
            1 for r in records
            if int(r.get("seq", 0)) > self._carry["seq"]
        )
        self._m_lag.set(float(behind))
        if not behind:
            return 0
        before = self._carry["replayed"]
        try:
            apply_replay(self._dispatcher, records, self._carry)
        except JournalFormatError:
            # The warm state machine disagreed with the tail (e.g. a
            # primary restart replayed differently than our increment
            # assumed). Cold rebuild from the FULL journal (the
            # incremental read above held only the tail) —
            # correctness over warmth.
            logger.exception(
                "incremental replay diverged; rebuilding warm state"
            )
            self._dispatcher = self._dispatcher_factory()
            self._carry = new_replay_carry()
            apply_replay(
                self._dispatcher, self._journal.replay_records(),
                self._carry,
            )
            before = 0
        applied = self._carry["replayed"] - before
        self._m_replayed.inc(max(0, applied))
        self._m_lag.set(0.0)
        return applied

    # ---- heartbeating ---------------------------------------------------

    def heartbeat(self) -> bool:
        """One ping to the primary; True = alive. Rebuilds the channel
        on failure (wedge avoidance, the PR 5/6 lesson)."""
        from elasticdl_tpu.comm.rpc import RpcStub

        if self._stub is None:
            self._stub = RpcStub(
                self._primary_addr, SERVICE_NAME, max_retries=0
            )
        t0 = time.monotonic()
        try:
            self._stub.call(
                "ping", timeout=max(0.5, self._heartbeat_secs)
            )
        except Exception:
            self._misses += 1
            logger.warning(
                "primary heartbeat missed (%d/%d)",
                self._misses, self._miss_threshold,
            )
            try:
                self._stub.reconnect()
            except Exception:
                self._stub = None
            return False
        self._m_heartbeat.observe(time.monotonic() - t0)
        self._misses = 0
        return True

    # ---- takeover --------------------------------------------------------

    def _fence_and_drain(self) -> int:
        """The shared front half of every takeover: catch the tail,
        publish the fence (zombie locked out), then drain records
        that won the race against the fence publish (seq-gated, so
        the drain cannot double-apply). ONE copy of this ordering —
        ``take_over`` (embedded: assembles + serves) and
        ``hand_over`` (CLI: feeds ``Master(warm_state=…)``) must not
        drift on it."""
        self.poll_journal()
        fence_gen = self._journal.publish_fence(
            self._carry["generation"] + 1
        )
        self.poll_journal()
        return fence_gen

    def hand_over(self) -> dict:
        """The NON-serving half of a takeover: fence + drain, then
        release the journal — returning ``{"dispatcher", "stats",
        "fence_generation"}`` for a caller that finishes promotion
        itself (``master/main.py run_standby`` feeds this straight
        into ``Master(warm_state=…)``, which opens the post-fence
        generation and re-arms the full production assembly)."""
        fence_gen = self._fence_and_drain()
        self._journal.close()
        return {
            "dispatcher": self._dispatcher,
            "stats": dict(self._carry),
            "fence_generation": fence_gen,
        }

    def take_over(self) -> dict:
        """Fence the old incarnation and start serving. Sequence:
        catch the tail → publish the fence (zombie locked out) → catch
        anything that raced in before the fence landed → open our
        generation (+ fence record) → re-arm the warm dispatcher →
        bind the advertised address."""
        from elasticdl_tpu.comm.rpc import RpcServer

        t_detect = time.monotonic()
        phases = {}

        def _mark(name, t0):
            now = time.monotonic()
            phases[name] = round(now - t0, 4)
            return now

        t = t_detect
        fence_gen = self._fence_and_drain()
        t = _mark("fence", t)
        self.generation = self._journal.open_generation()
        self._journal.append("fence", generation=self.generation)
        t = _mark("open_generation", t)
        stats = dict(self._carry)
        stats["known_workers"] = sorted(stats["known_workers"])
        self.dispatcher = self._dispatcher
        self.eval_service, self.servicer = self._assemble(
            self.dispatcher, self._journal
        )
        rearm_recovered_master(
            self._journal, self.dispatcher, stats,
            servicer=self.servicer, eval_service=self.eval_service,
        )
        t = _mark("assemble_rearm", t)
        # The old incarnation's socket may linger in TIME_WAIT /
        # teardown for a beat — retry the bind like the drill fleets
        # retry shard relaunch ports.
        handlers = (
            self._handlers_factory(self.servicer)
            if self._handlers_factory is not None
            else self.servicer.handlers()
        )
        last_exc = None
        for _ in range(max(1, self._bind_retries)):
            try:
                self.server = RpcServer(
                    self._serve_addr,
                    {SERVICE_NAME: handlers},
                ).start()
                break
            except Exception as exc:
                last_exc = exc
                time.sleep(self._bind_retry_secs)
        if self.server is None:
            raise RuntimeError(
                f"standby could not bind {self._serve_addr}: "
                f"{last_exc}"
            )
        _mark("bind", t)
        elapsed = time.monotonic() - t_detect
        self._m_failover.observe(elapsed)
        self.promoted = True
        stats["generation"] = self.generation
        stats["fence_generation"] = fence_gen
        stats["takeover_seconds"] = elapsed
        stats["takeover_phases"] = phases
        self.takeover_stats = stats
        logger.warning(
            "STANDBY PROMOTED: generation %d (fence %d) serving on "
            "%s after %.3fs (%s); %d record(s) warm-replayed, %d "
            "leased task(s) surviving",
            self.generation, fence_gen, self._serve_addr, elapsed,
            phases, stats["replayed"],
            len(self.dispatcher.doing_start_times()),
        )
        if self._on_promoted is not None:
            self._on_promoted(self)
        return stats

    # ---- the standby loop ------------------------------------------------

    def run(self) -> bool:
        """Tail + heartbeat until the primary dies (→ take_over,
        returns True) or ``stop()`` is called (returns False)."""
        next_poll = 0.0
        next_beat = 0.0
        while not self._stop.is_set():
            now = time.monotonic()
            if now >= next_poll:
                self.poll_journal()
                next_poll = now + self._poll_secs
            if now >= next_beat:
                self.heartbeat()
                next_beat = now + self._heartbeat_secs
                if self._misses >= self._miss_threshold:
                    self.take_over()
                    return True
            self._stop.wait(
                max(0.005, min(next_poll, next_beat) - time.monotonic())
            )
        return False

    def start(self) -> threading.Thread:
        thread = threading.Thread(
            target=self.run, daemon=True, name="master-standby"
        )
        thread.start()
        return thread

    def stop(self):
        self._stop.set()
        if self._stub is not None:
            try:
                self._stub.close()
            except Exception:
                pass

    def close(self):
        self.stop()
        if self.server is not None:
            self.server.stop(0)
        self._journal.close()
