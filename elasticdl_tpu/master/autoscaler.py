"""Closed-loop elastic autoscaling over the master's own telemetry.

The control loop the ROADMAP asked for: the master already *measures*
everything a scaling decision needs — queue depth
(``master_task_queue_depth``), per-worker device saturation
(``worker_step_utilization``, piggybacked in the cluster snapshots),
and per-phase p99 straggler attribution
(``observability/critical_path.py`` over the collected span trees).
This module closes the loop: a policy with hysteresis + cooldown +
min/max bounds reads those signals each master tick and issues scale
decisions through pluggable actions:

- **pod scaling** (k8s): ``InstanceManager.scale_up`` /
  ``InstanceManager.drain_worker`` — more or fewer worker pods pulling
  from the same task queue;
- **mesh scaling** (SPMD): ``MasterServicer.begin_resize`` — the
  checkpointless live-reshard barrier (parallel/reshard.py), where the
  same workers re-place their train state onto a bigger or smaller
  device mesh with no disk round trip.

The policy is deliberately boring and fully unit-testable: decisions
are pure functions of an ``AutoscaleSignals`` snapshot, and all
statefulness (hysteresis streaks, cooldown clock) lives in
``Autoscaler`` behind an injectable clock. What keeps it safe in
production is the plumbing around it, not the thresholds: decisions
are rate-limited (cooldown), damped (hysteresis), bounded (min/max),
and the resize barrier serializes — a new decision is suppressed while
a barrier is pending, and a worker killed mid-barrier cannot wedge it
(the tick refreshes barrier membership from the live worker set).
"""

import dataclasses
import time
from typing import Callable, Dict, List, Optional

from elasticdl_tpu.common.log_utils import get_logger

logger = get_logger("autoscaler")

UP = "up"
DOWN = "down"
HOLD = "hold"


@dataclasses.dataclass
class AutoscaleSignals:
    """One tick's telemetry snapshot (see ``master_signals``)."""

    queue_depth: int = 0          # tasks waiting in todo
    doing: int = 0                # tasks currently leased
    live_workers: int = 1         # current fleet size
    # Mean worker_step_utilization across reporting workers; None when
    # no worker has reported the gauge yet (don't guess — hold).
    step_utilization: Optional[float] = None
    # Critical-path reduction over collected spans (when tracing is
    # on): p99 task latency and its dominant phase. Informational for
    # the decision log; a fetch-dominated p99 also vetoes scale-up
    # (more workers cannot help a job starved on input).
    p99_task_secs: float = 0.0
    p99_dominant_phase: Optional[str] = None
    resize_pending: bool = False


@dataclasses.dataclass
class AutoscalePolicy:
    """Decision thresholds. Defaults are conservative: scale up only
    on real backlog with saturated workers, scale down only when the
    queue is empty and workers are measurably idle."""

    min_workers: int = 1
    max_workers: int = 4
    # Scale up when todo > backlog_factor × live_workers (each worker
    # already has more than a full task of lookahead) AND utilization
    # is high (a starved fleet with a deep queue means input, not
    # compute, is the bottleneck — more workers won't help).
    scale_up_backlog_factor: float = 2.0
    scale_up_utilization: float = 0.7
    # Scale down when nothing queues and utilization is low.
    scale_down_utilization: float = 0.3
    # Consecutive same-direction ticks required before acting.
    hysteresis_ticks: int = 3
    # Quiet period after any decision.
    cooldown_secs: float = 60.0

    def direction(self, s: AutoscaleSignals) -> str:
        """Pure per-tick desired direction, before hysteresis."""
        if s.resize_pending:
            return HOLD
        util = s.step_utilization
        if (
            s.queue_depth > self.scale_up_backlog_factor * max(
                1, s.live_workers
            )
            and s.live_workers < self.max_workers
            and (util is None or util >= self.scale_up_utilization)
            and s.p99_dominant_phase != "fetch"
        ):
            return UP
        if (
            s.queue_depth == 0
            and s.live_workers > self.min_workers
            and util is not None
            and util <= self.scale_down_utilization
        ):
            return DOWN
        return HOLD


class Autoscaler:
    """The loop: read signals, damp, bound, act.

    ``signals_fn``  → AutoscaleSignals for this tick;
    ``scale_up``    → add capacity (one worker / one mesh rung);
    ``scale_down``  → remove capacity;
    both actions receive the signals snapshot. ``clock`` is injectable
    for tests."""

    def __init__(
        self,
        policy: AutoscalePolicy,
        signals_fn: Callable[[], AutoscaleSignals],
        scale_up: Callable[[AutoscaleSignals], None],
        scale_down: Callable[[AutoscaleSignals], None],
        metrics_registry=None,
        clock: Callable[[], float] = time.monotonic,
    ):
        from elasticdl_tpu.observability import default_registry

        self.policy = policy
        self._signals_fn = signals_fn
        self._scale_up = scale_up
        self._scale_down = scale_down
        self._clock = clock
        self._streak_direction = HOLD
        self._streak = 0
        self._last_decision_at: Optional[float] = None
        self.decisions: List[dict] = []
        registry = metrics_registry or default_registry()
        self._m_decisions = registry.counter(
            "master_autoscale_decisions_total",
            "Autoscale decisions issued", ["direction"],
        )
        self._m_streak = registry.gauge(
            "master_autoscale_streak",
            "Consecutive ticks agreeing on the pending direction",
        )

    def _in_cooldown(self, now: float) -> bool:
        return (
            self._last_decision_at is not None
            and now - self._last_decision_at < self.policy.cooldown_secs
        )

    def tick(self) -> Optional[str]:
        """One control-loop iteration; returns the issued direction
        (``"up"``/``"down"``) or None."""
        now = self._clock()
        signals = self._signals_fn()
        direction = self.policy.direction(signals)
        if direction == HOLD:
            self._streak_direction, self._streak = HOLD, 0
            self._m_streak.set(0.0)
            return None
        if direction == self._streak_direction:
            self._streak += 1
        else:
            self._streak_direction, self._streak = direction, 1
        self._m_streak.set(float(self._streak))
        if self._streak < self.policy.hysteresis_ticks:
            return None
        if self._in_cooldown(now):
            return None
        # Act. The streak resets so another full hysteresis window is
        # required on top of the cooldown.
        self._streak_direction, self._streak = HOLD, 0
        self._m_streak.set(0.0)
        self._last_decision_at = now
        self._m_decisions.labels(direction).inc()
        self.decisions.append({
            "direction": direction,
            "signals": dataclasses.asdict(signals),
        })
        logger.info(
            "autoscale %s: queue=%d doing=%d workers=%d util=%s "
            "p99=%.3fs[%s]",
            direction, signals.queue_depth, signals.doing,
            signals.live_workers, signals.step_utilization,
            signals.p99_task_secs, signals.p99_dominant_phase,
        )
        if direction == UP:
            self._scale_up(signals)
        else:
            self._scale_down(signals)
        return direction


class RowServicePodScaler:
    """Closes the PR 12 loop: the shard-map controller could already
    ``split``/``merge`` ranges across row-service processes, but
    nothing ever SPAWNED or REMOVED a process — splits were confined
    to pods that existed at launch. This scaler owns the pod half:

    - ``grow()``  — ``InstanceManager.add_row_service_shard`` (stable
      Service + pod, journaled before the create), then ``split`` the
      hottest live shard onto the new pod's service address. Pod
      first, routes second: the map must never point at an address
      with nothing behind it.
    - ``shrink()`` — ``merge`` the coldest scaled pod's shard into the
      busiest survivor and remember its address as pending. The pod
      keeps serving: clients holding a pre-drain map still route ids
      at it until the controller's quiescence check proves otherwise.
    - ``tick()`` — called after the controller's own tick. When a
      pending address has left the map (the controller retired the
      drained slot), the pod has served its last request:
      ``drain_row_service_shard`` deletes pod + Service without
      triggering the dead-pod relaunch path. Routes first, pod
      second — the mirror of grow.

    Decision *policy* (when to grow/shrink) stays with the caller —
    the master tick, a drill, or an ``Autoscaler`` wired to row
    telemetry; this class only makes the actions safe."""

    def __init__(self, controller, instance_manager,
                 address_fn: Callable[[int], str],
                 metrics_registry=None):
        from elasticdl_tpu.observability import default_registry

        self._controller = controller
        self._im = instance_manager
        self._address_fn = address_fn
        # Service addresses merged away, awaiting the controller's
        # retirement proof. Keyed by ADDRESS, not shard index — map
        # indices shift when a slot is retired.
        self._pending_drain: set = set()
        registry = metrics_registry or default_registry()
        self._m_pods = registry.counter(
            "master_rowservice_pod_scale_total",
            "Row-service pods spawned/drained by the pod scaler",
            ["action"],
        )
        self.events: List[dict] = []

    def _addr_to_im_shard(self) -> Dict[str, int]:
        return {
            self._address_fn(shard): shard
            for shard in self._im.row_service_shards()
        }

    def _traffic_by_shard(self) -> Dict[int, int]:
        stats = self._controller.poll_stats()
        return {
            s: int(per.get("pulled_rows", 0))
            + int(per.get("pushed_rows", 0))
            for s, per in stats.items()
        }

    def grow(self) -> Optional[dict]:
        """Spawn a pod and split the hottest shard onto it. Returns
        ``{"im_shard", "addr", "source"}`` or None (row service off /
        manager stopped / no live map)."""
        shard_map = self._controller.map
        if shard_map is None or not shard_map.shards:
            return None
        im_shard = self._im.add_row_service_shard()
        if im_shard is None:
            return None
        addr = self._address_fn(im_shard)
        traffic = self._traffic_by_shard()
        live = range(len(shard_map.shards))
        source = max(live, key=lambda s: traffic.get(s, 0))
        try:
            self._controller.split(source, new_addr=addr)
        except Exception:
            # The pod exists but the routes never moved: tear it back
            # down rather than leak an unreferenced pod.
            logger.exception(
                "split onto new row-service pod %s failed; draining "
                "the unused pod", addr,
            )
            self._im.drain_row_service_shard(im_shard)
            return None
        self._m_pods.labels("add").inc()
        event = {"action": "add", "im_shard": im_shard,
                 "addr": addr, "source": int(source)}
        self.events.append(event)
        logger.info(
            "row-service pod scale-up: shard %d (%s) split from "
            "shard %d", im_shard, addr, source,
        )
        return event

    def shrink(self) -> Optional[dict]:
        """Merge the coldest scaler-managed pod's shard into the
        busiest survivor; the pod itself drains on a later ``tick``
        once the controller retires the slot. Returns
        ``{"addr", "source", "target"}`` or None (nothing safely
        removable)."""
        shard_map = self._controller.map
        if shard_map is None or len(shard_map.shards) <= 1:
            return None
        by_addr = self._addr_to_im_shard()
        candidates = [
            s for s, addr in enumerate(shard_map.shards)
            if addr in by_addr and addr not in self._pending_drain
        ]
        if len(candidates) < 1 or len(shard_map.shards) - len(
            self._pending_drain
        ) <= 1:
            return None
        traffic = self._traffic_by_shard()
        source = min(candidates, key=lambda s: traffic.get(s, 0))
        survivors = [
            s for s in range(len(shard_map.shards))
            if s != source
            and shard_map.shards[s] not in self._pending_drain
        ]
        if not survivors:
            return None
        target = max(survivors, key=lambda s: traffic.get(s, 0))
        addr = shard_map.shards[source]
        self._controller.merge(source, target)
        self._pending_drain.add(addr)
        self._m_pods.labels("merge").inc()
        event = {"action": "merge", "addr": addr,
                 "source": int(source), "target": int(target)}
        self.events.append(event)
        logger.info(
            "row-service pod scale-down: shard %d (%s) merging into "
            "shard %d; pod drains after retirement", source, addr,
            target,
        )
        return event

    def tick(self) -> Optional[int]:
        """Drain the pod behind any pending address the controller
        has retired from the map. Returns the drained instance-manager
        shard index, or None."""
        if not self._pending_drain:
            return None
        shard_map = self._controller.map
        live = set(shard_map.shards) if shard_map is not None else set()
        by_addr = self._addr_to_im_shard()
        for addr in sorted(self._pending_drain):
            if addr in live:
                continue  # not retired yet: keep serving stale routes
            self._pending_drain.discard(addr)
            im_shard = by_addr.get(addr)
            if im_shard is None:
                continue  # pod already gone (master restart raced)
            self._im.drain_row_service_shard(im_shard)
            self._m_pods.labels("drain").inc()
            self.events.append({"action": "drain",
                                "im_shard": im_shard, "addr": addr})
            logger.info(
                "row-service pod drained after retirement: shard %d "
                "(%s)", im_shard, addr,
            )
            return im_shard
        return None


# ---- signal extraction ---------------------------------------------------


def utilization_from_snapshots(snapshots: Dict[int, dict],
                               ) -> Optional[float]:
    """Mean ``worker_step_utilization`` across the live cluster
    snapshots; None when no worker has published the gauge."""
    values = []
    for snapshot in snapshots.values():
        for family in snapshot.get("families", []):
            if family.get("name") == "edl_tpu_worker_step_utilization":
                for series in family.get("series", []):
                    values.append(float(series.get("value", 0.0)))
    if not values:
        return None
    return sum(values) / len(values)


def p99_attribution(spans: List[dict]) -> tuple:
    """(p99_task_secs, dominant_phase) from the collected span trees —
    the critical-path reduction's headline, as an autoscale input."""
    from elasticdl_tpu.observability import critical_path

    if not spans:
        return 0.0, None
    report = critical_path.analyze(spans)
    tasks = report.get("tasks")
    if not tasks:
        return 0.0, None
    p99 = tasks.get("p99") or {}
    return float(tasks.get("p99_secs", 0.0)), p99.get("dominant_phase")


def utilization_from_timeseries(store, window_secs: float,
                                ) -> Optional[float]:
    """Mean ``worker_step_utilization`` over the trailing time-series
    window — the trend-backed alternative to the instantaneous
    snapshot mean. One worker flapping between 0.9 and 0.1 across two
    report intervals reads as ~0.5 here instead of whichever extreme
    the tick happened to land on; None when the window holds no points
    (same don't-guess contract as the snapshot path)."""
    values = store.gauge_values(
        "edl_tpu_worker_step_utilization", window_secs
    )
    if not values:
        return None
    return sum(values) / len(values)


def master_signals(dispatcher, servicer, metrics_plane,
                   live_workers_fn: Callable[[], int],
                   with_traces: bool = True,
                   timeseries=None,
                   trend_window_secs: float = 120.0,
                   ) -> Callable[[], AutoscaleSignals]:
    """Bind the master's live objects into a ``signals_fn``.

    ``timeseries`` (a ``TimeSeriesStore``, opted in via
    ``--autoscale_from_timeseries``) replaces the instantaneous
    utilization snapshot with the mean over ``trend_window_secs`` —
    decisions then damp over the window like the SRE-style alerts do,
    instead of reacting to whichever report the tick caught. The
    snapshot path stays the default (and the fallback while the window
    is still empty)."""

    def signals() -> AutoscaleSignals:
        queue_depth, doing = dispatcher.queue_depths()
        util = None
        if timeseries is not None:
            util = utilization_from_timeseries(
                timeseries, trend_window_secs
            )
        if util is None:
            util = utilization_from_snapshots(
                metrics_plane.cluster.snapshots()
            )
        p99_secs, p99_phase = (0.0, None)
        if with_traces and queue_depth > 0:
            # The p99 attribution only gates the scale-UP veto, and
            # merging + analyzing the full span store is O(collected
            # spans) — skip it on idle ticks (empty queue can never
            # scale up).
            p99_secs, p99_phase = p99_attribution(
                metrics_plane.trace_spans()
            )
        return AutoscaleSignals(
            queue_depth=queue_depth,
            doing=doing,
            live_workers=max(1, int(live_workers_fn())),
            step_utilization=util,
            p99_task_secs=p99_secs,
            p99_dominant_phase=p99_phase,
            resize_pending=servicer.resize_status() is not None,
        )

    return signals
