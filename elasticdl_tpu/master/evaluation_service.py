"""Master-side evaluation: triggers + metric accumulation.

Counterpart of the reference's ``master/evaluation_service.py``:
- step-based trigger: every ``eval_steps`` model versions (reported by the
  training plane via ``report_version``) a batch of EVALUATION tasks is
  queued (reference :171-186),
- time-based trigger: a thread queues eval jobs every ``throttle_secs``
  after ``start_delay_secs`` (reference ``_EvaluationTrigger`` :52-85),
- workers report *raw model outputs and labels*; metrics are computed on
  the master (reference evaluation_utils.py:50-97) in chunks.

Crash survival (master/journal.py): with a journal attached, round
state is event-sourced — ``eval_round`` open/task_done/close events
plus per-task ``eval_fold`` records carrying the raw outputs/labels
(ndarrays ride the journal's msgpack serde the same way they ride
checkpoints). A recovered (or hot-standby) master rebuilds the OPEN
round — accumulated outputs, completed count, folded task ids,
``_last_eval_version`` — via ``restore_recovered``, so a master death
mid-round costs nothing: the surviving eval tasks re-report against
the restored round and it closes with the same metrics a never-killed
master would have produced. Fold records cost one journal fsync per
eval task report — eval-task granularity, not step granularity.
"""

import threading
import time
from typing import Callable, Dict, Optional

import numpy as np

from elasticdl_tpu.common.constants import TaskType
from elasticdl_tpu.common.log_utils import get_logger

logger = get_logger("evaluation_service")

# Metric update chunk size (reference evaluation_utils.py:83-97 uses 500 to
# bound per-call memory).
_CHUNK = 500


class EvaluationMetrics:
    """Accumulates raw outputs/labels and computes metric fns lazily."""

    def __init__(self, metrics_fns: Dict[str, Callable]):
        self._metrics_fns = metrics_fns
        self._outputs = []
        self._labels = []

    def update(self, outputs, labels):
        outputs = np.asarray(outputs)
        labels = np.asarray(labels)
        for i in range(0, outputs.shape[0], _CHUNK):
            self._outputs.append(outputs[i:i + _CHUNK])
            self._labels.append(labels[i:i + _CHUNK])

    def result(self) -> Dict[str, float]:
        if not self._outputs:
            return {}
        outputs = np.concatenate(self._outputs, axis=0)
        labels = np.concatenate(self._labels, axis=0)
        return {
            name: float(fn(labels, outputs))
            for name, fn in self._metrics_fns.items()
        }


class EvaluationJob:
    """One evaluation round at one model version (reference :11-49)."""

    def __init__(self, metrics_fns: Dict[str, Callable], model_version: int,
                 total_tasks: int = -1):
        self.model_version = model_version
        self._total_tasks = total_tasks
        self._completed_tasks = 0
        self.evaluation_metrics = EvaluationMetrics(metrics_fns)
        # Task ids whose raw outputs already folded: the fold is a
        # plain accumulate, so an at-least-once re-send (RpcStub
        # retries DEADLINE_EXCEEDED; the worker's master-outage
        # ride-out retries harder) must not count the same samples
        # twice.
        self._folded_tasks = set()

    def complete_task(self):
        self._completed_tasks += 1

    def finished(self) -> bool:
        return (
            self._total_tasks >= 0
            and self._completed_tasks >= self._total_tasks
        )

    def report_evaluation_metrics(self, outputs, labels,
                                  task_id: int = -1) -> bool:
        """Fold one task's raw outputs; False iff this task id already
        folded (at-least-once re-send — callers must not journal or
        re-count it)."""
        if task_id >= 0:
            if task_id in self._folded_tasks:
                logger.info(
                    "eval task %d outputs already folded; ignoring "
                    "duplicate report", task_id,
                )
                return False
            self._folded_tasks.add(task_id)
        self.evaluation_metrics.update(outputs, labels)
        return True


class EvaluationService:
    def __init__(
        self,
        task_dispatcher,
        metrics_fns: Dict[str, Callable],
        eval_steps: int = 0,
        start_delay_secs: int = 0,
        throttle_secs: int = 0,
        eval_only: bool = False,
        summary_writer=None,
    ):
        self._task_d = task_dispatcher
        self._metrics_fns = metrics_fns or {}
        self._eval_steps = eval_steps
        self._start_delay_secs = start_delay_secs
        self._throttle_secs = throttle_secs
        self._eval_only = eval_only
        self._summary_writer = summary_writer
        self._lock = threading.Lock()
        self._eval_job: Optional[EvaluationJob] = None
        self._last_eval_version = -1
        # Watermark-based trigger (streaming ingestion,
        # docs/online_learning.md): rounds open every N committed
        # stream records instead of every N model versions — a stream
        # has no epochs, so epoch-end eval never fires there.
        self._eval_watermark_records = 0
        self._last_eval_watermark = 0
        self.completed_results: Dict[int, Dict[str, float]] = {}
        self._trigger_thread = None
        self._stop = threading.Event()
        # Write-ahead journal (master/journal.py): round open/fold/
        # task_done/close events write through so an open round
        # survives a master crash. Attached AFTER construction (and
        # after restore_recovered on a recovery path), mirroring
        # TaskDispatcher.attach_journal.
        self._journal = None
        if eval_only:
            # Evaluation-only jobs: the dispatcher queued the EVALUATION
            # tasks at construction; open the job that will collect their
            # results (reference evaluation_service.py init_eval_only path).
            self._eval_job = EvaluationJob(
                self._metrics_fns, model_version=-1,
                total_tasks=self._count_eval_tasks(),
            )

    # ---- journal (master/journal.py) -----------------------------------

    def attach_journal(self, journal):
        """Write round events through ``journal`` from now on. On a
        recovery path, call ``restore_recovered`` FIRST — the restore
        must not re-append the events it is replaying."""
        with self._lock:
            self._journal = journal

    def restore_recovered(self, state: Optional[dict]):
        """Install the journal's replayed eval carry (see
        ``journal.new_eval_state``): the open round — completed count,
        folded task ids, re-folded raw outputs — plus
        ``_last_eval_version`` and the completed-results history. The
        journal must not be attached yet."""
        if not state:
            return
        with self._lock:
            if self._journal is not None:
                raise RuntimeError(
                    "detach the journal before restore_recovered"
                )
            self._last_eval_version = int(
                state.get("last_eval_version", self._last_eval_version)
            )
            for version, metrics in (state.get("results") or {}).items():
                self.completed_results[int(version)] = dict(metrics)
            open_round = state.get("open")
            if open_round is None:
                return
            job = self._eval_job
            if job is None:
                # total_tasks -1 only happens for eval-only rounds,
                # whose job is rebuilt at construction (the branch
                # below); a journaled open round always recorded it.
                job = EvaluationJob(
                    self._metrics_fns,
                    model_version=int(open_round.get("model_version",
                                                     -1)),
                    total_tasks=int(open_round.get("total_tasks", -1)),
                )
                self._eval_job = job
            # Eval-only jobs keep the constructed job (same config by
            # construction) and replay progress onto it.
            job._completed_tasks = max(
                job._completed_tasks,
                int(open_round.get("completed", 0)),
            )
            for task_id, outputs, labels in open_round.get("folds", []):
                job.report_evaluation_metrics(
                    outputs, labels, task_id=int(task_id)
                )
            if job.finished():
                # Crash window between the final task's REPORT record
                # and the round's close record: replay counted the
                # round complete, so close it HERE — no completion
                # will ever arrive again (the reports all resolved).
                results = job.evaluation_metrics.result()
                self.completed_results[job.model_version] = results
                self._eval_job = None
                logger.info(
                    "closed recovered eval round @version %d: %s",
                    job.model_version, results,
                )
                if self._summary_writer is not None:
                    self._summary_writer.write_eval_metrics(
                        job.model_version, results
                    )
                return
            remaining = self._task_d.count_tasks(TaskType.EVALUATION)
            if job._completed_tasks + remaining < job._total_tasks:
                # Crash window between the round's open record and its
                # create_tasks record: the journal opened a round whose
                # tasks never existed — unfinishable. Drop it (the
                # round is lost, not wedged; the next version report
                # re-triggers) rather than block evaluation forever.
                logger.warning(
                    "dropping recovered eval round @version %d: only "
                    "%d task(s) outstanding + %d complete of %d (the "
                    "crash preceded its task creation)",
                    job.model_version, remaining,
                    job._completed_tasks, job._total_tasks,
                )
                self._eval_job = None
                return
        logger.info(
            "restored open eval round @version %d: %d/%s task(s) "
            "complete, %d fold(s) re-applied",
            job.model_version, job._completed_tasks,
            job._total_tasks, len(open_round.get("folds", [])),
        )

    # ---- triggers ------------------------------------------------------

    def start_time_trigger(self):
        """Time-based eval trigger thread (reference _EvaluationTrigger)."""
        if self._throttle_secs <= 0:
            return

        def _loop():
            time.sleep(self._start_delay_secs)
            while not self._stop.is_set():
                self.try_to_create_new_job(model_version=-1)
                if self._stop.wait(self._throttle_secs):
                    return

        self._trigger_thread = threading.Thread(target=_loop, daemon=True)
        self._trigger_thread.start()

    def stop(self):
        self._stop.set()

    def add_evaluation_task_if_needed(self, model_version: int):
        """Step-based trigger, called on report_version
        (reference evaluation_service.py:171-186)."""
        if self._eval_steps <= 0:
            return False
        # Elapsed-steps check rather than exact modulo: workers may report
        # versions at a coarser granularity than every step.
        if model_version - max(self._last_eval_version, 0) >= self._eval_steps:
            return self.try_to_create_new_job(model_version)
        return False

    def configure_watermark_eval(self, every_records: int,
                                 start_at: int = 0):
        """Arm the watermark trigger: one eval round per
        ``every_records`` committed stream records. ``start_at`` seeds
        the marker (the ingestor passes the recovered committed total
        so a master restart does not fire a spurious burst)."""
        with self._lock:
            self._eval_watermark_records = int(every_records)
            self._last_eval_watermark = max(
                self._last_eval_watermark, int(start_at)
            )

    def add_watermark_eval_if_needed(self, committed_records: int,
                                     model_version: int = -1) -> bool:
        """Watermark trigger, called by the stream ingestor's pump as
        committed watermarks advance (the streaming replacement for
        epoch-end / step-based triggering). The marker only advances
        when a round actually opens, so progress made while a previous
        round is still running re-triggers as soon as it closes."""
        if self._eval_watermark_records <= 0:
            return False
        if (committed_records - self._last_eval_watermark
                < self._eval_watermark_records):
            return False
        if self.try_to_create_new_job(model_version):
            with self._lock:
                self._last_eval_watermark = int(committed_records)
            return True
        return False

    def try_to_create_new_job(self, model_version: int) -> bool:
        with self._lock:
            if self._eval_job is not None and not self._eval_job.finished():
                return False  # previous round still running
            num_tasks = self._count_eval_tasks()
            if num_tasks == 0:
                return False
            self._eval_job = EvaluationJob(
                self._metrics_fns, model_version, total_tasks=num_tasks
            )
            self._last_eval_version = model_version
            if self._journal is not None:
                # Inside the lock and BEFORE create_tasks below, so
                # the journal's order (open, then create_tasks)
                # matches the state-mutation order replay re-runs.
                self._journal.append(
                    "eval_round", event="open",
                    model_version=int(model_version),
                    total_tasks=int(num_tasks),
                    last_eval_version=int(model_version),
                )
        self._task_d.create_tasks(TaskType.EVALUATION, model_version)
        return True

    def _count_eval_tasks(self) -> int:
        shards = self._task_d._shards_for(TaskType.EVALUATION)
        per_task = self._task_d._records_per_task
        count = 0
        for _name, (_start, n) in shards.items():
            count += (n + per_task - 1) // per_task
        return count

    # ---- worker reports ------------------------------------------------

    def report_evaluation_metrics(self, outputs, labels,
                                  task_id: int = -1) -> bool:
        with self._lock:
            if self._eval_job is None:
                return False
            folded = self._eval_job.report_evaluation_metrics(
                outputs, labels, task_id=task_id
            )
            if folded and self._journal is not None:
                # First applications only: a duplicate fold was
                # ignored above and must not re-fold on replay either.
                self._journal.append(
                    "eval_fold", task_id=int(task_id),
                    outputs=np.asarray(outputs),
                    labels=np.asarray(labels),
                )
            return True

    def complete_task(
        self, model_version: int = -1
    ) -> Optional[Dict[str, float]]:
        """Count one finished eval task toward the current round.
        ``model_version`` is the completed TASK's version: a completion
        from a different round — e.g. a version-V task still draining
        after a master restart opened a fresh round at V' — must not
        count toward this one, or the round closes early on partial
        data. -1 counts unconditionally (eval-only jobs and callers
        predating versioned tasks)."""
        with self._lock:
            if self._eval_job is None:
                return None
            if (model_version >= 0
                    and self._eval_job.model_version >= 0
                    and model_version != self._eval_job.model_version):
                logger.warning(
                    "eval task @version %d completed but the current "
                    "round is @version %d; not counting it",
                    model_version, self._eval_job.model_version,
                )
                return None
            # No journal append for the count itself: round progress
            # rides the dispatcher's REPORT record (task_type/
            # model_version/requeued fields), so the resolution and
            # the completion are ONE fsynced record — a crash cannot
            # separate them and wedge the round.
            self._eval_job.complete_task()
            if not self._eval_job.finished():
                return None
            results = self._eval_job.evaluation_metrics.result()
            version = self._eval_job.model_version
            self.completed_results[version] = results
            self._eval_job = None
            if self._journal is not None:
                # Close supersedes the round's folds/task_done records
                # — a recovered master keeps the results, not the
                # round (journal-side state folds it the same way).
                self._journal.append(
                    "eval_round", event="close",
                    model_version=int(version),
                    results={str(k): float(v)
                             for k, v in results.items()},
                )
        logger.info("Eval @version %d: %s", version, results)
        if self._summary_writer is not None:
            self._summary_writer.write_eval_metrics(version, results)
        return results
