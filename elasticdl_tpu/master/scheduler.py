"""Multi-tenant gang scheduler: many jobs on one elastic fleet.

Every plane below this one is single-job by construction — the
dispatcher hands shards of ONE job to workers, the autoscaler sizes
ONE gang, the journal event-sources ONE control plane. This module
turns the master into a multi-job arbiter (the resource-allocation
shape of Podracer's multi-workload orchestration and the cluster
half of the MPMD pipeline scheduler; PAPERS.md):

- **Job table** — ``{job_id: spec, priority, gang_size, lifecycle
  state, preemption count}`` with the state machine ``submitted ->
  scheduled -> running -> (preempted -> scheduled -> running)* ->
  done`` (``cancel`` exits any non-terminal state). Every transition
  is event-sourced onto the master journal as a ``sched`` record
  (master/journal.py), so the table survives failover, warm-replays
  into the hot standby, and a fenced zombie cannot mutate it — its
  append raises ``JournalFencedError`` before any byte lands.
- **Gang scheduling** — a job runs only when its whole gang fits:
  each tick re-derives the allocation from scratch (priority-ordered
  first-fit over the live slot count), so fleet growth and shrink
  (the autoscaler's doing) re-arbitrate automatically.
- **Priority preemption** — a higher-priority job that cannot fit
  evicts the lowest-priority running gang: ``preempt`` = the job's
  ``checkpoint_now`` callback (the existing checkpoint chain), then a
  journaled preemption record, then the gang's leases hand back
  through the dispatcher's graceful-preemption path (retry budgets
  untouched, resolved-ledger idempotence intact — exactly-once
  accounting across the eviction). ``resume`` = the restore chain +
  push-WAL tail replay, both existing paths, via the job's resume
  callback.
- **Fair share** — among equal priorities the arbiter orders by the
  PR 16 usage plane's per-job share (``/usage``): the job that has
  consumed the least fleet time schedules first, so back-to-back
  equal-priority jobs converge toward equal shares instead of
  first-come-forever.

Workers bind to jobs lazily (``lease_for``): a worker slot asking for
work is bound to the allocated job with the emptiest gang, and the
binding drops when the job is preempted or done — the fleet is shared
capacity, not per-job silos. ``docs/scheduler.md`` is the operator
view; ``chaos/sched_drill.py`` is the adversarial proof and
``tools/check_sched.py`` its fsck.
"""

import threading
import time
from typing import Callable, Dict, List, Optional, Tuple

from elasticdl_tpu.common.log_utils import get_logger

logger = get_logger("scheduler")

# Lifecycle states (journal fold: master/journal.py apply_sched_record).
SUBMITTED = "submitted"
SCHEDULED = "scheduled"
RUNNING = "running"
PREEMPTED = "preempted"
DONE = "done"
CANCELLED = "cancelled"

ACTIVE_STATES = (SCHEDULED, RUNNING)
WAITING_STATES = (SUBMITTED, PREEMPTED)
TERMINAL_STATES = (DONE, CANCELLED)


def default_dispatcher_factory(spec: dict):
    """Build a ``TaskDispatcher`` from a submitted job spec:
    ``{"shards": {name: [start, end]}, "records_per_task": int,
    "num_epochs": int}`` — the portable subset a journal-replayed
    table can rebuild on any incarnation. A streaming job
    (docs/online_learning.md) declares ``{"stream": true}`` instead of
    shards: its task queue comes from the live tail, so the rebuilt
    dispatcher starts empty in streaming mode and the journal's STREAM
    records / the re-bound ingestor repopulate it."""
    from elasticdl_tpu.master.task_dispatcher import TaskDispatcher

    if spec.get("stream"):
        return TaskDispatcher(
            training_shards={},
            records_per_task=int(spec.get("records_per_task", 1)),
            shuffle=False,
            seed=int(spec.get("seed", 0)),
            streaming=True,
        )
    shards = {
        str(name): (int(lo), int(hi))
        for name, (lo, hi) in (spec.get("shards") or {}).items()
    }
    if not shards:
        raise ValueError("job spec has no shards")
    return TaskDispatcher(
        training_shards=shards,
        records_per_task=int(spec.get("records_per_task", 1)),
        num_epochs=int(spec.get("num_epochs", 1)),
        shuffle=False,
        seed=int(spec.get("seed", 0)),
    )


class GangScheduler:
    """The job table + arbiter. Thread-safe: ``tick`` runs on the
    master loop, ``lease_for``/``dispatcher_of`` on RPC threads,
    ``submit`` on either."""

    def __init__(
        self,
        slots_fn: Callable[[], int],
        journal=None,
        dispatcher_factory: Optional[Callable[[dict], object]] = None,
        usage_fn: Optional[Callable[[], dict]] = None,
        registry=None,
    ):
        from elasticdl_tpu.observability import default_registry

        self._slots_fn = slots_fn
        self._journal = journal
        self._factory = dispatcher_factory or default_dispatcher_factory
        self._usage_fn = usage_fn
        self._lock = threading.RLock()
        # job table: {job_id: entry dict} — same shape the journal
        # fold produces (apply_sched_record), plus volatile fields the
        # journal deliberately omits (dispatcher, callbacks, bindings).
        self._jobs: Dict[str, dict] = {}
        self._dispatchers: Dict[str, object] = {}
        self._preempt_cbs: Dict[str, Callable] = {}
        self._resume_cbs: Dict[str, Callable] = {}
        self._submit_seq: Dict[str, int] = {}
        self._next_seq = 0
        self._alloc: Dict[str, int] = {}   # job -> allocated slots
        self._bound: Dict[int, str] = {}   # worker_id -> job
        self.preemptions = 0
        registry = registry or default_registry()
        self._m_jobs = registry.gauge(
            "sched_jobs", "Jobs in the gang scheduler's table, "
            "by lifecycle state", ["state"],
        )
        self._m_preempt = registry.counter(
            "sched_preemptions_total",
            "Gang evictions by a higher-priority job",
        )
        self._m_slots_total = registry.gauge(
            "sched_slots_total", "Worker slots the arbiter sees",
        )
        self._m_slots_alloc = registry.gauge(
            "sched_slots_allocated",
            "Worker slots currently allocated to gangs",
        )

    # ---- journal plumbing ----------------------------------------------

    def _journal_event(self, event: str, job: str, **fields):
        if self._journal is not None:
            # JournalFencedError propagates: a fenced incarnation must
            # not mutate the table (the servicer's pre-check turns it
            # into a clean stale_master response first).
            self._journal.append("sched", event=event, job=job,
                                 **fields)

    # ---- submission -----------------------------------------------------

    def submit(self, job_id: str, spec: Optional[dict] = None,
               priority: int = 0, gang_size: int = 1,
               dispatcher=None,
               preempt_cb: Optional[Callable] = None,
               resume_cb: Optional[Callable] = None) -> dict:
        """Add a job. ``dispatcher`` (optional) serves the job's tasks
        directly; without it the spec must carry enough to build one
        (``default_dispatcher_factory``). Journals the submission
        BEFORE the table mutates — a fenced zombie's submit must leave
        no trace."""
        job_id = str(job_id)
        if not job_id:
            raise ValueError("job_id must be non-empty")
        spec = dict(spec or {})
        with self._lock:
            existing = self._jobs.get(job_id)
            if existing is not None and (
                existing["state"] not in TERMINAL_STATES
            ):
                raise ValueError(f"job {job_id!r} already active")
            self._journal_event("submit", job_id, spec=spec,
                                priority=int(priority),
                                gang_size=int(gang_size))
            self._jobs[job_id] = {
                "spec": spec,
                "priority": int(priority),
                "gang_size": max(1, int(gang_size)),
                "state": SUBMITTED,
                "preemptions": 0,
            }
            if dispatcher is not None:
                self._dispatchers[job_id] = dispatcher
            if preempt_cb is not None:
                self._preempt_cbs[job_id] = preempt_cb
            if resume_cb is not None:
                self._resume_cbs[job_id] = resume_cb
            self._submit_seq[job_id] = self._next_seq
            self._next_seq += 1
            logger.info(
                "job %s submitted (priority %d, gang %d)",
                job_id, int(priority), int(gang_size),
            )
            return dict(self._jobs[job_id])

    def cancel(self, job_id: str) -> bool:
        job_id = str(job_id)
        with self._lock:
            entry = self._jobs.get(job_id)
            if entry is None or entry["state"] in TERMINAL_STATES:
                return False
            self._journal_event("cancel", job_id)
            entry["state"] = CANCELLED
            self._release_locked(job_id)
            return True

    def restore(self, sched_state: Optional[dict]):
        """Re-arm from a replay carry's ``sched`` fold (cold recovery
        or warm standby takeover). Jobs the journal saw in flight
        (scheduled/running) come back as PREEMPTED: their gang died
        with the old incarnation, and the resume path — restore chain
        + WAL tail replay — is exactly the preemption contract. Their
        journaled preemption counts are preserved; the demotion
        itself is NOT journaled (replay must stay idempotent — the
        next tick's resume record captures the restart)."""
        if not sched_state:
            return
        with self._lock:
            for job_id, entry in (sched_state.get("jobs") or {}).items():
                job_id = str(job_id)
                restored = {
                    "spec": dict(entry.get("spec") or {}),
                    "priority": int(entry.get("priority", 0)),
                    "gang_size": max(1, int(entry.get("gang_size", 1))),
                    "state": str(entry.get("state", SUBMITTED)),
                    "preemptions": int(entry.get("preemptions", 0)),
                }
                if restored["state"] in ACTIVE_STATES:
                    restored["state"] = PREEMPTED
                self._jobs[job_id] = restored
                self._submit_seq.setdefault(job_id, self._next_seq)
                self._next_seq += 1
            self.preemptions = int(sched_state.get("preemptions", 0))

    def bind_job(self, job_id: str, dispatcher=None,
                 preempt_cb: Optional[Callable] = None,
                 resume_cb: Optional[Callable] = None):
        """Attach volatile per-job machinery (dispatcher, checkpoint
        callbacks) to a restored table entry — the journal carries
        the durable half only."""
        job_id = str(job_id)
        with self._lock:
            if dispatcher is not None:
                self._dispatchers[job_id] = dispatcher
            if preempt_cb is not None:
                self._preempt_cbs[job_id] = preempt_cb
            if resume_cb is not None:
                self._resume_cbs[job_id] = resume_cb

    # ---- fair share ------------------------------------------------------

    def _job_shares(self) -> Dict[str, float]:
        """Per-job consumed share from the usage plane: the mean of
        the share axes the ``/usage`` summary reports for principals
        carrying this job label. Missing plane or job -> 0.0 (never
        scheduled = most deserving)."""
        if self._usage_fn is None:
            return {}
        try:
            usage = self._usage_fn() or {}
        except Exception:
            logger.exception("usage_fn failed; fair share degraded")
            return {}
        shares: Dict[str, float] = {}
        for row in usage.get("principals") or []:
            who = row.get("principal") or {}
            job = str(who.get("job", ""))
            share = row.get("share") or {}
            values = [float(v) for v in share.values()]
            if not values:
                continue
            mean = sum(values) / len(values)
            shares[job] = max(shares.get(job, 0.0), mean)
        return shares

    # ---- arbitration -----------------------------------------------------

    def tick(self) -> List[str]:
        """One arbitration pass; returns the transitions made (for
        logs/drills), e.g. ``["done:a", "preempt:b", "schedule:c"]``.
        Never raises except ``JournalFencedError`` (a fenced arbiter
        must stop, not continue on stale state)."""
        actions: List[str] = []
        shares = self._job_shares()
        with self._lock:
            slots = max(0, int(self._slots_fn()))
            # 1. Completion sweep: a job whose dispatcher drained is
            # done — journal it and free the gang.
            for job_id, entry in list(self._jobs.items()):
                if entry["state"] not in ACTIVE_STATES:
                    continue
                disp = self._dispatchers.get(job_id)
                if disp is not None and disp.finished():
                    self._journal_event("done", job_id)
                    entry["state"] = DONE
                    self._release_locked(job_id)
                    actions.append(f"done:{job_id}")
                    logger.info("job %s done", job_id)
            # 2. Target allocation from scratch: priority first, then
            # least consumed share (fair share), then submit order.
            candidates = [
                (job_id, entry)
                for job_id, entry in self._jobs.items()
                if entry["state"] in ACTIVE_STATES + WAITING_STATES
            ]
            candidates.sort(key=lambda kv: (
                -kv[1]["priority"],
                shares.get(kv[0], 0.0),
                self._submit_seq.get(kv[0], 0),
            ))
            target: Dict[str, int] = {}
            free = slots
            for job_id, entry in candidates:
                gang = entry["gang_size"]
                if gang <= free:
                    target[job_id] = gang
                    free -= gang
            # 3. Evict active gangs that lost their allocation
            # (checkpoint -> journal -> release leases -> unbind).
            for job_id, entry in self._jobs.items():
                if entry["state"] in ACTIVE_STATES and (
                    job_id not in target
                ):
                    self._preempt_locked(job_id, entry)
                    actions.append(f"preempt:{job_id}")
            # 4. Admit waiting gangs that won one (build/rebind the
            # dispatcher, journal schedule/resume).
            for job_id in target:
                entry = self._jobs[job_id]
                if entry["state"] not in WAITING_STATES:
                    continue
                resuming = entry["state"] == PREEMPTED
                if job_id not in self._dispatchers:
                    try:
                        self._dispatchers[job_id] = self._factory(
                            entry["spec"]
                        )
                    except Exception:
                        logger.exception(
                            "job %s: dispatcher build failed; "
                            "cancelling", job_id,
                        )
                        self._journal_event("cancel", job_id)
                        entry["state"] = CANCELLED
                        continue
                self._journal_event(
                    "resume" if resuming else "schedule", job_id
                )
                entry["state"] = SCHEDULED
                if resuming:
                    cb = self._resume_cbs.get(job_id)
                    if cb is not None:
                        cb(job_id, entry)
                actions.append(
                    f"{'resume' if resuming else 'schedule'}:{job_id}"
                )
                logger.info(
                    "job %s %s (%d slot(s))", job_id,
                    "resumed" if resuming else "scheduled",
                    target[job_id],
                )
            # 5. Promote scheduled -> running (the gang holds its
            # slots from this tick on).
            for job_id in target:
                entry = self._jobs[job_id]
                if entry["state"] == SCHEDULED:
                    self._journal_event("run", job_id)
                    entry["state"] = RUNNING
                    actions.append(f"run:{job_id}")
            self._alloc = target
            # Drop bindings to jobs that no longer hold slots.
            for worker_id, job_id in list(self._bound.items()):
                if job_id not in target:
                    del self._bound[worker_id]
            self._m_slots_total.set(float(slots))
            self._m_slots_alloc.set(float(slots - free))
            counts: Dict[str, int] = {}
            for entry in self._jobs.values():
                counts[entry["state"]] = counts.get(
                    entry["state"], 0
                ) + 1
            for state in (SUBMITTED, SCHEDULED, RUNNING, PREEMPTED,
                          DONE, CANCELLED):
                self._m_jobs.labels(state).set(
                    float(counts.get(state, 0))
                )
        return actions

    def _preempt_locked(self, job_id: str, entry: dict):
        """checkpoint_now -> journal the preemption -> release the
        gang's leases through the dispatcher's graceful-preemption
        path -> unbind its workers. The checkpoint runs FIRST: once
        the preemption record is durable the gang may be reassigned
        immediately, and the job's next life must restore everything
        it had."""
        cb = self._preempt_cbs.get(job_id)
        if cb is not None:
            cb(job_id, entry)
        self._journal_event("preempt", job_id)
        entry["state"] = PREEMPTED
        entry["preemptions"] = int(entry.get("preemptions", 0)) + 1
        self.preemptions += 1
        self._m_preempt.inc()
        disp = self._dispatchers.get(job_id)
        if disp is not None:
            handed_back = disp.preempt_leases(
                f"preempted: gang released ({job_id})"
            )
            if handed_back:
                logger.info(
                    "job %s: %d leased task(s) handed back on "
                    "preemption", job_id, handed_back,
                )
        self._release_locked(job_id)
        logger.warning(
            "job %s preempted (count %d)", job_id,
            entry["preemptions"],
        )

    def _release_locked(self, job_id: str):
        self._alloc.pop(job_id, None)
        for worker_id, bound in list(self._bound.items()):
            if bound == job_id:
                del self._bound[worker_id]

    # ---- worker-facing (RPC threads) ------------------------------------

    def lease_for(self, worker_id: int) -> Tuple[Optional[str], object]:
        """The job this worker slot serves right now: its existing
        binding while that job still holds slots, else the allocated
        job with the emptiest gang. ``(None, None)`` = no allocated
        job wants a worker — the servicer answers WAIT."""
        worker_id = int(worker_id)
        with self._lock:
            job_id = self._bound.get(worker_id)
            if job_id is not None and job_id in self._alloc:
                return job_id, self._dispatchers.get(job_id)
            bound_counts: Dict[str, int] = {}
            for bound in self._bound.values():
                bound_counts[bound] = bound_counts.get(bound, 0) + 1
            best = None
            best_gap = 0
            for job_id, slots in self._alloc.items():
                gap = slots - bound_counts.get(job_id, 0)
                if gap > best_gap:
                    best, best_gap = job_id, gap
            if best is None:
                return None, None
            self._bound[worker_id] = best
            return best, self._dispatchers.get(best)

    def dispatcher_of(self, job_id: str):
        with self._lock:
            return self._dispatchers.get(str(job_id))

    def active_dispatchers(self) -> Dict[str, object]:
        """{job_id: dispatcher} for jobs currently holding slots —
        the servicer's straggler scan walks these next to the
        primary dispatcher."""
        with self._lock:
            return {
                job_id: self._dispatchers[job_id]
                for job_id in self._alloc
                if job_id in self._dispatchers
            }

    def idle(self) -> bool:
        """True when no job needs the fleet (all terminal)."""
        with self._lock:
            return all(
                entry["state"] in TERMINAL_STATES
                for entry in self._jobs.values()
            )

    def job_state(self, job_id: str) -> Optional[str]:
        with self._lock:
            entry = self._jobs.get(str(job_id))
            return entry["state"] if entry else None

    # ---- export (journal snapshot provider / endpoint) -------------------

    def export_state(self) -> dict:
        """The durable half of the table — same shape as the journal
        fold (``new_sched_state``)."""
        with self._lock:
            return {
                "jobs": {
                    job_id: {
                        "spec": dict(entry["spec"]),
                        "priority": entry["priority"],
                        "gang_size": entry["gang_size"],
                        "state": entry["state"],
                        "preemptions": entry["preemptions"],
                    }
                    for job_id, entry in self._jobs.items()
                },
                "preemptions": int(self.preemptions),
            }

    def render(self) -> dict:
        """The ``/sched`` endpoint body: job table + allocation +
        fair-share target vs consumed share."""
        shares = self._job_shares()
        with self._lock:
            slots = max(0, int(self._slots_fn()))
            jobs = {}
            bound_counts: Dict[str, int] = {}
            for bound in self._bound.values():
                bound_counts[bound] = bound_counts.get(bound, 0) + 1
            active = [
                e for e in self._jobs.values()
                if e["state"] in ACTIVE_STATES + WAITING_STATES
            ]
            fair = 1.0 / len(active) if active else 0.0
            for job_id, entry in self._jobs.items():
                disp = self._dispatchers.get(job_id)
                todo, doing = (
                    disp.queue_depths() if disp is not None else (0, 0)
                )
                jobs[job_id] = {
                    "priority": entry["priority"],
                    "gang_size": entry["gang_size"],
                    "state": entry["state"],
                    "preemptions": entry["preemptions"],
                    "allocated_slots": self._alloc.get(job_id, 0),
                    "bound_workers": bound_counts.get(job_id, 0),
                    "todo": todo,
                    "doing": doing,
                    "usage_share": shares.get(job_id, 0.0),
                    "fair_share": (
                        fair if entry["state"] not in TERMINAL_STATES
                        else 0.0
                    ),
                }
            return {
                "slots": {
                    "total": slots,
                    "allocated": sum(self._alloc.values()),
                },
                "preemptions": int(self.preemptions),
                "jobs": jobs,
                "now": time.time(),
            }
