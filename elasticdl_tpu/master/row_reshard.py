"""Shard-map authority: live row-service resharding + hot-row
replication control loop.

The one writer of shard-map epochs (embedding/shard_map.py). Every
topology change runs the same generation-fenced protocol, shaped like
PR 8's resize barrier — move state first, flip the version last:

1. **plan** — persist the migration record (source, target, bucket
   range) to the state file *before* any byte moves: a controller
   crash at any later point finds the record and re-runs the
   migration (re-copy is idempotent — ``ingest_rows`` overwrites).
2. **copy** — ``begin_ingest`` on the target (generation fence: only
   this migration's chunks are accepted), then ``migrate_out`` on the
   source: bulk chunks, catch-up deltas bounded by the source's
   touched-set tracking, and a brief write fence for the final delta.
3. **cutover** — persist the NEW map (version + 1, range reassigned)
   while the range is still fenced, then distribute it target-first
   (the target must accept the range before the source starts
   redirecting to it), source second (its fence turns into a
   redirect and it erases the moved rows), rest last. Stale clients
   converge through REDIRECTs; no client ever observes two owners.
4. **done** — ``end_ingest`` releases the target's fence; the state
   file drops the migration record.

The controller also closes the autoscaling loop for the STATE plane:
``tick()`` polls per-shard load (``shard_stats``), and the policy
triggers range moves off load imbalance and refreshes the hot-row
replica designation from the shards' pull-frequency top-K — the
skew-vs-throughput half of the ROADMAP item (one hot shard caps fleet
throughput; replicas spread its reads).

Persistence: the state file is the authority's truth (tmp+rename, the
same publish discipline as checkpoints); when a ``MasterJournal`` is
attached, every epoch also appends a ``shard_map`` record so the map
rides the master's write-ahead journal (audit + recovery aid — the
state file wins; journal compaction may drop old epoch records).

Ops note: splitting onto a NEW shard needs a process to exist at the
target address first (start ``row_service`` main with the same model
module, no checkpoint restore needed — the migration streams its
state). The controller never spawns processes.
"""

import json
import os
import threading
import time
from dataclasses import dataclass
from typing import Callable, Dict, List, Optional

from elasticdl_tpu.common.log_utils import get_logger
from elasticdl_tpu.embedding.shard_map import ShardMap

logger = get_logger("row_reshard")


# Chaos seam: raised-through hook between persisting the cutover map
# and distributing it — the drill's "kill the master mid-cutover".
_mid_cutover_hook: Optional[Callable] = None


def set_reshard_chaos_hooks(mid_cutover: Optional[Callable] = None):
    global _mid_cutover_hook
    _mid_cutover_hook = mid_cutover


class RideOutTransport:
    """Default shard transport: rides out a shard relaunch with the
    row-service client's own bounded-backoff + channel-rebuild retry
    (a resharding authority faces restarting shards as a matter of
    course — a wedged channel must not fail a resumable migration).
    Delegating to ``_call_with_retry`` also puts every migration RPC
    under the shared ``RowService:rideout`` retry budget and its
    decorrelated-jitter backoff (comm/overload.py): background
    migration traffic is rate-capped during an overload instead of
    amplifying into it."""

    def __init__(self, addr: str, retries: int = 8,
                 backoff_secs: float = 0.25):
        from elasticdl_tpu.comm.rpc import RpcStub
        from elasticdl_tpu.embedding.row_service import SERVICE_NAME

        self._stub = RpcStub(addr, SERVICE_NAME, max_retries=0)
        self._retries = retries
        self._backoff = backoff_secs

    def call(self, method: str, **fields):
        from elasticdl_tpu.embedding.row_service import (
            _call_with_retry,
        )

        return _call_with_retry(
            self._stub, method, self._retries, self._backoff, **fields
        )

    def close(self):
        self._stub.close()


@dataclass
class ReshardPolicy:
    """Pure decision thresholds for the controller's tick (injectable,
    unit-testable — the same discipline as AutoscalePolicy).

    A rebalance MOVE triggers when the hottest shard's pull+push row
    rate exceeds ``imbalance_factor`` x the coldest's (with at least
    ``min_rows_per_tick`` observed — an idle fleet has no signal).
    Hot-vs-cold, not hot-vs-mean: on a small fleet max/mean is
    bounded by the fleet size, and a 2-shard fleet at 90/10 load is
    exactly the imbalance a move should fix.
    Replica designation takes each table's globally hottest ids (by
    the shards' pull-frequency top-K) that drew at least
    ``replica_min_pulls`` since the last tick."""

    imbalance_factor: float = 1.8
    min_rows_per_tick: int = 1000
    replica_top_k: int = 64
    replica_min_pulls: int = 64
    # Replicas per hot id (capped by fleet size - 1); 0 disables
    # replication entirely.
    replica_count: int = 2
    cooldown_secs: float = 30.0

    def pick_move(self, rates: Dict[int, float]) -> Optional[tuple]:
        """(source, target) off per-shard row rates, or None."""
        if len(rates) < 2:
            return None
        total = sum(rates.values())
        if total < self.min_rows_per_tick:
            return None
        hot = max(rates, key=lambda s: rates[s])
        cold = min(rates, key=lambda s: rates[s])
        if hot == cold or rates[hot] < self.imbalance_factor * max(
            rates[cold], 1.0
        ):
            return None
        return hot, cold

    def pick_replicas(
        self, hot_counts: Dict[str, Dict[int, int]], num_shards: int,
        home_of: Callable[[str, int], int],
    ) -> Dict[str, Dict[int, tuple]]:
        """{table: {id: replica shards}} from aggregated pull counts.
        Replicas are the shards after the home in ring order — spread
        deterministic, no state to persist beyond the map itself."""
        count = min(self.replica_count, num_shards - 1)
        if count <= 0:
            return {}
        out: Dict[str, Dict[int, tuple]] = {}
        for table, counts in hot_counts.items():
            ranked = sorted(
                counts.items(), key=lambda kv: -kv[1]
            )[: self.replica_top_k]
            per = {}
            for i, n in ranked:
                if n < self.replica_min_pulls:
                    continue
                home = home_of(table, i)
                per[int(i)] = tuple(
                    (home + 1 + k) % num_shards for k in range(count)
                )
            if per:
                out[table] = per
        return out


@dataclass
class MigrationRecord:
    """One in-flight (or crashed-in-flight) range move — exactly what
    the state file carries so a restarted controller can resume."""

    migration_id: str
    source: int
    target: int
    lo: int
    hi: int
    phase: str  # "copy" | "cutover"

    def to_json(self) -> dict:
        return {
            "migration_id": self.migration_id, "source": self.source,
            "target": self.target, "lo": self.lo, "hi": self.hi,
            "phase": self.phase,
        }

    @classmethod
    def from_json(cls, blob: dict) -> "MigrationRecord":
        return cls(
            str(blob["migration_id"]), int(blob["source"]),
            int(blob["target"]), int(blob["lo"]), int(blob["hi"]),
            str(blob["phase"]),
        )


class ShardMapController:
    """The single authority over one row-service fleet's shard map.

    ``transport_factory(addr) -> obj with .call(method, **fields)``
    defaults to RPC stubs; tests/drills inject in-process transports.
    ``state_path`` is required: an authority that cannot persist its
    epoch cannot survive itself, and resharding without crash safety
    is how rows get lost."""

    def __init__(self, state_path: str,
                 transport_factory: Optional[Callable] = None,
                 journal=None,
                 policy: Optional[ReshardPolicy] = None):
        if not state_path:
            raise ValueError("state_path must be non-empty")
        self.state_path = state_path
        self.policy = policy or ReshardPolicy()
        self._journal = journal
        self._transport_factory = transport_factory
        self._transports: Dict[str, object] = {}
        self._lock = threading.Lock()
        self._map: Optional[ShardMap] = None
        self._migration: Optional[MigrationRecord] = None
        self._mig_seq = 0
        self._last_rates: Dict[int, int] = {}
        self._last_action_at = 0.0
        # Drained-but-not-retired shards (merge leaves the slot in the
        # address list): [{"shard", "addr", "epoch"}], persisted so a
        # restarted authority still retires them. Quiescence baselines
        # (traffic totals + clock) stay in-memory — they re-arm after
        # a restart, which only delays retirement by one window.
        self._drained: List[dict] = []
        self._drained_baseline: Dict[int, dict] = {}
        from elasticdl_tpu.observability import default_registry

        registry = default_registry()
        self._m_epochs = registry.counter(
            "row_reshard_epochs_total",
            "Shard-map epochs published by the authority",
        )
        self._m_migrations = registry.counter(
            "row_reshard_migrations_total",
            "Range migrations driven to completion",
            ["kind"],
        )
        if os.path.exists(state_path):
            self._load_state()

    # ---- persistence ---------------------------------------------------

    def _load_state(self):
        with open(self.state_path) as fh:
            state = json.load(fh)
        self._map = ShardMap.from_json(state["map"])
        mig = state.get("migration")
        self._migration = (
            MigrationRecord.from_json(mig) if mig else None
        )
        self._mig_seq = int(state.get("mig_seq", 0))
        self._drained = list(state.get("drained", []))

    def _persist(self):
        """Publish the authority's truth with the checkpoint publish
        discipline: no epoch is visible until fully durable."""
        state = {
            "map": self._map.to_json(),
            "migration": (
                self._migration.to_json() if self._migration else None
            ),
            "mig_seq": self._mig_seq,
            "drained": list(self._drained),
        }
        tmp = self.state_path + ".tmp"
        os.makedirs(os.path.dirname(self.state_path) or ".",
                    exist_ok=True)
        with open(tmp, "w") as fh:
            json.dump(state, fh, indent=2, sort_keys=True)
            fh.flush()
            os.fsync(fh.fileno())
        os.replace(tmp, self.state_path)
        if self._journal is not None:
            try:
                self._journal.append(
                    "shard_map", version=self._map.version,
                    map=self._map.to_json(),
                )
            except Exception as exc:
                logger.warning("journal shard_map append failed: %s",
                               exc)

    # ---- transports ----------------------------------------------------

    def _transport(self, addr: str):
        transport = self._transports.get(addr)
        if transport is None:
            if self._transport_factory is not None:
                transport = self._transport_factory(addr)
            else:
                transport = RideOutTransport(addr)
            self._transports[addr] = transport
        return transport

    # ---- map lifecycle -------------------------------------------------

    @property
    def map(self) -> Optional[ShardMap]:
        return self._map

    def bootstrap(self, addrs: List[str]) -> ShardMap:
        """First epoch over a fresh fleet (no-op if state already
        exists — a restarted master must not regress the map)."""
        with self._lock:
            if self._map is None:
                self._map = ShardMap.bootstrap(addrs)
                self._persist()
                self._m_epochs.inc()
            self._sync_locked()
            return self._map

    def sync(self) -> int:
        """Distribute the current map to every shard (idempotent —
        versions fence). Returns how many shards accepted."""
        with self._lock:
            return self._sync_locked()

    def _sync_locked(self, order: Optional[List[int]] = None) -> set:
        """Install the current map on every shard; returns the set of
        shard indices that ACCEPTED. Failures are logged, not raised —
        but callers that need a specific shard installed (the cutover's
        target) must check membership: clients converge via REDIRECT,
        SERVERS only converge through this call (tick() re-syncs
        laggards it sees in poll_stats)."""
        m = self._map
        ok = set()
        shards = order if order is not None else range(len(m.shards))
        for s in shards:
            try:
                self._transport(m.shards[s]).call(
                    "set_shard_map", map=m.to_json(), shard_id=int(s),
                )
                ok.add(int(s))
            except Exception as exc:
                logger.warning(
                    "set_shard_map on shard %d (%s) failed: %s "
                    "(tick() re-syncs laggards)",
                    s, m.shards[s], exc,
                )
        return ok

    def add_shard(self, addr: str) -> int:
        """Register a new (empty) shard and give it the map — the
        split target. Returns its shard index."""
        with self._lock:
            self._map = self._map.add_shard(addr)
            self._persist()
            self._m_epochs.inc()
            self._sync_locked()
            return len(self._map.shards) - 1

    # ---- migrations ----------------------------------------------------

    def move_range(self, source: int, lo: int, hi: int,
                   target: int) -> dict:
        """Drive one live range move end to end (the docstring's
        plan/copy/cutover/done). Raises on failure with the migration
        record persisted — ``resume()`` re-runs it."""
        with self._lock:
            if self._migration is not None:
                raise RuntimeError(
                    f"migration {self._migration.migration_id} already "
                    "in flight; resume() it first"
                )
            self._mig_seq += 1
            record = MigrationRecord(
                f"mig-{self._mig_seq}-v{self._map.version}"
                f"-{lo}-{hi}", int(source), int(target), int(lo),
                int(hi), "copy",
            )
            self._migration = record
            self._persist()
        return self._run_migration(record)

    def _run_migration(self, record: MigrationRecord) -> dict:
        m = self._map
        source_addr = m.shards[record.source]
        target_addr = m.shards[record.target]
        stats = {}
        if record.phase == "copy":
            self._transport(target_addr).call(
                "begin_ingest", migration_id=record.migration_id,
                lo=record.lo, hi=record.hi,
            )
            stats = self._transport(source_addr).call(
                "migrate_out", migration_id=record.migration_id,
                lo=record.lo, hi=record.hi, target_addr=target_addr,
            )
            # Cutover: persist the flipped map FIRST (a crash after
            # this point re-distributes; a crash before re-copies).
            with self._lock:
                self._map = self._map.move_range(
                    record.lo, record.hi, record.target
                )
                record.phase = "cutover"
                self._migration = record
                self._persist()
                self._m_epochs.inc()
        hook = _mid_cutover_hook
        if hook is not None:
            hook(self, record)
        with self._lock:
            # Target first: it must accept the range before the source
            # starts redirecting clients to it.
            order = [record.target, record.source] + [
                s for s in range(len(self._map.shards))
                if s not in (record.target, record.source)
            ]
            accepted = self._sync_locked(order)
            if record.target not in accepted:
                # Without the target on the new epoch, every redirect
                # sends clients to a shard that bounces them back
                # (carrying the OLDER map, which they ignore) — an
                # unservable range. Keep the migration record (phase
                # cutover) and fail: resume() re-distributes.
                raise RuntimeError(
                    f"cutover: target shard {record.target} did not "
                    f"accept map v{self._map.version}; migration "
                    f"{record.migration_id} kept for resume()"
                )
            try:
                self._transport(
                    self._map.shards[record.target]
                ).call("end_ingest",
                       migration_id=record.migration_id)
            except Exception as exc:
                logger.warning("end_ingest failed: %s", exc)
            self._migration = None
            self._persist()
        self._m_migrations.labels("move").inc()
        logger.info(
            "migrated buckets [%d, %d) shard %d -> %d (v%d): %s",
            record.lo, record.hi, record.source, record.target,
            self._map.version, stats,
        )
        return stats

    def resume(self) -> Optional[dict]:
        """Crash recovery: finish whatever the state file says was in
        flight. Phase "copy" re-runs the whole move (idempotent);
        phase "cutover" re-distributes the already-persisted map and
        releases the target. Returns the move's stats (None if there
        was nothing to resume)."""
        with self._lock:
            record = self._migration
        if record is None:
            with self._lock:
                if self._map is not None:
                    self._sync_locked()
            return None
        logger.info(
            "resuming migration %s (phase %s)", record.migration_id,
            record.phase,
        )
        return self._run_migration(record)

    # ---- convenience topologies ----------------------------------------

    def split(self, source: int, new_addr: Optional[str] = None,
              target: Optional[int] = None) -> dict:
        """Split the source shard: move the upper half of its largest
        range to ``new_addr`` (a fresh shard) or an existing
        ``target``."""
        if (new_addr is None) == (target is None):
            raise ValueError("pass exactly one of new_addr/target")
        if new_addr is not None:
            target = self.add_shard(new_addr)
        lo, hi = self._map.split_plan(source)
        return self.move_range(source, lo, hi, target)

    def merge(self, source: int, target: int) -> List[dict]:
        """Drain the source shard into ``target`` (one move per owned
        range). The drained slot stays in the address list until the
        tick's compaction step retires it — once every client has
        converged past the drained shard's last epoch (see
        ``_maybe_retire_locked``)."""
        out = []
        for lo, hi in list(self._map.ranges_of(source)):
            # Each constituent move already counts in
            # row_reshard_migrations_total{kind=move}.
            out.append(self.move_range(source, lo, hi, target))
        with self._lock:
            self._drained.append({
                "shard": int(source),
                "addr": self._map.shards[int(source)],
                # Clients at epochs below this could still route ids
                # to the drained shard; retirement waits until no one
                # does (quiescence) and every server converged past.
                "epoch": int(self._map.version),
            })
            self._persist()
        return out

    def _maybe_retire_locked(self, stats: Dict[int, dict],
                             now: float) -> Optional[int]:
        """Compaction: retire ONE drained shard per tick once it is
        provably unreferenced — every reachable server installed an
        epoch >= the drain epoch, and the drained shard served ZERO
        pulls/pushes for a full policy cooldown window (a client
        still holding a pre-drain map would route ids at it, so
        sustained silence is the observable form of "every client has
        converged past the drained shard's last epoch"). Returns the
        retired index or None. Caller holds the lock."""
        for record in list(self._drained):
            shard = int(record["shard"])
            if shard >= len(self._map.shards) or (
                self._map.shards[shard] != record["addr"]
            ):
                # Index no longer names the drained address (map
                # evolved unexpectedly, e.g. hand-edited state) —
                # drop the stale record instead of retiring the
                # wrong shard.
                self._drained.remove(record)
                self._persist()
                continue
            if self._map.buckets_owned(shard):
                # Re-split onto the drained slot: it is live again.
                self._drained.remove(record)
                self._drained_baseline.pop(shard, None)
                self._persist()
                continue
            behind = [
                s for s, per in stats.items()
                if per.get("map_version", 0) < record["epoch"]
            ]
            if behind:
                continue
            per = stats.get(shard)
            traffic = (
                (per.get("pulled_rows", 0) + per.get("pushed_rows", 0))
                if per is not None else None
            )
            baseline = self._drained_baseline.get(shard)
            if baseline is None or (
                traffic is not None and traffic != baseline["traffic"]
            ):
                # (Re-)arm the quiescence window; an unreachable
                # drained shard (ops already killed the process)
                # quiesces trivially (traffic None == None holds).
                self._drained_baseline[shard] = {
                    "traffic": traffic, "t": now,
                }
                continue
            if now - baseline["t"] < self.policy.cooldown_secs:
                continue
            m = self._map
            # Replica designation may still point at the drained slot
            # (ring-order spread counts every slot): filter the
            # drained MEMBER out of each set — the surviving replicas
            # keep serving the hot reads (dropping whole entries
            # would collapse the fan-in onto the home until the next
            # update_replicas tick).
            replicas = {}
            for table, per_table in m.replicas.items():
                kept = {}
                for i, reps in per_table.items():
                    filtered = tuple(s for s in reps if s != shard)
                    if filtered:
                        kept[i] = filtered
                if kept:
                    replicas[table] = kept
            if replicas != m.replicas:
                m = m.with_replicas(replicas)
            self._map = m.retire_shard(shard)
            self._drained.remove(record)
            self._drained_baseline.pop(shard, None)
            # Surviving drained records + baselines shift down past
            # the removed slot; per-index rate history is stale now.
            for other in self._drained:
                if int(other["shard"]) > shard:
                    other["shard"] = int(other["shard"]) - 1
            self._drained_baseline = {
                (s - 1 if s > shard else s): b
                for s, b in self._drained_baseline.items()
            }
            self._last_rates = {}
            self._persist()
            self._m_epochs.inc()
            self._m_migrations.labels("retire").inc()
            self._sync_locked()
            logger.info(
                "retired drained shard %d (%s) from the map (v%d): "
                "%d shard(s) remain",
                shard, record["addr"], self._map.version,
                len(self._map.shards),
            )
            return shard
        return None

    # ---- autoscaler hook (the policy tick) -----------------------------

    def poll_stats(self, top_k: Optional[int] = None) -> Dict[int, dict]:
        """shard_stats from every reachable shard."""
        m = self._map
        out = {}
        for s, addr in enumerate(m.shards):
            try:
                out[s] = self._transport(addr).call(
                    "shard_stats",
                    top_k=int(top_k or self.policy.replica_top_k),
                )
            except Exception as exc:
                logger.warning("shard_stats on %s failed: %s", addr,
                               exc)
        return out

    def update_replicas(self) -> bool:
        """Recompute the hot-row replica designation from the shards'
        pull-frequency top-K; publish a new epoch only when it
        changed. Returns whether an epoch was published."""
        stats = self.poll_stats()
        hot: Dict[str, Dict[int, int]] = {}
        for per_shard in stats.values():
            for table, pairs in (per_shard.get("hot") or {}).items():
                bucket = hot.setdefault(table, {})
                for i, n in pairs:
                    bucket[int(i)] = bucket.get(int(i), 0) + int(n)
        m = self._map
        replicas = self.policy.pick_replicas(
            hot, len(m.shards),
            lambda table, i: int(m.home_of_ids([i])[0]),
        )
        with self._lock:
            if replicas == self._map.replicas:
                return False
            self._map = self._map.with_replicas(replicas)
            self._persist()
            self._m_epochs.inc()
            self._sync_locked()
        logger.info(
            "replica designation updated (v%d): %s",
            self._map.version,
            {t: len(p) for t, p in replicas.items()},
        )
        return True

    def tick(self, now: Optional[float] = None) -> Optional[str]:
        """One control-loop pass (called from the master's run tick):
        refresh replicas off the hot sets, and rebalance a range off
        load imbalance. Rate-limited by the policy cooldown; never
        raises (a flaky shard must not take the master loop down)."""
        now = time.monotonic() if now is None else now
        if self._map is None or self._migration is not None:
            return None
        if now - self._last_action_at < self.policy.cooldown_secs:
            return None
        try:
            stats = self.poll_stats()
            if not stats:
                return None
            # Laggard repair: a shard that missed a distribution (it
            # was restarting, or a cutover's tail sync failed) only
            # converges through set_shard_map — clients' REDIRECTs
            # never teach servers.
            behind = [
                s for s, per in stats.items()
                if per.get("map_version", 0) < self._map.version
            ]
            if behind:
                with self._lock:
                    self._sync_locked(behind)
            # Compaction: retire a drained (merged-away) shard once
            # clients provably converged past its last epoch.
            with self._lock:
                retired = self._maybe_retire_locked(stats, now)
            if retired is not None:
                self._last_action_at = now
                return f"retire:{retired}"
            primed = bool(self._last_rates)
            totals = {
                s: per.get("pulled_rows", 0) + per.get("pushed_rows", 0)
                for s, per in stats.items()
            }
            # Clamped per-tick deltas: a restarted shard's counters
            # reset (delta would go negative), and an unprimed first
            # tick would read lifetime totals as one tick's load.
            rates = {
                s: max(0, t - self._last_rates.get(s, t))
                for s, t in totals.items()
            }
            self._last_rates = totals
            if not primed:
                return None
            acted = None
            move = self.policy.pick_move(rates)
            if move is not None:
                source, target = move
                try:
                    lo, hi = self._map.split_plan(source)
                    self.move_range(source, lo, hi, target)
                    acted = f"move:{source}->{target}"
                except Exception as exc:
                    logger.warning("rebalance move failed: %s", exc)
            if self.update_replicas():
                acted = (acted + "+replicas") if acted else "replicas"
            if acted:
                self._last_action_at = now
            return acted
        except Exception as exc:
            logger.warning("reshard tick failed: %s", exc)
            return None

    def close(self):
        for transport in self._transports.values():
            close = getattr(transport, "close", None)
            if close is not None:
                try:
                    close()
                except Exception:
                    pass
        self._transports.clear()
