"""Master process entry point (reference master/main.py + master/master.py).

``python -m elasticdl_tpu.master.main <flags>`` builds the whole control
plane: model-spec load → reader shards → TaskDispatcher → EvaluationService
(+ TensorBoard) → MasterServicer → gRPC RpcServer → (optionally, on k8s)
InstanceManager spawning worker pods — then the run loop sleeps until the
dispatcher drains, checking straggler timeouts each tick (reference
master.py:218-238, :487-509).

``Master`` is also constructible in-process for tests (no k8s, no RPC port
conflicts) — the same assembly the reference exercises via
``distributed_train_and_evaluate``.
"""

import os
import sys
import time

from elasticdl_tpu.common.args import (
    build_arguments_from_parsed_result,
    parse_envs,
    parse_master_args,
)
from elasticdl_tpu.common.log_utils import get_logger
from elasticdl_tpu.comm.rpc import RpcServer
from elasticdl_tpu.core.model_spec import get_model_spec
from elasticdl_tpu.data.factory import (
    create_data_reader,
    parse_data_reader_params,
)
from elasticdl_tpu.master.evaluation_service import EvaluationService
from elasticdl_tpu.master.servicer import SERVICE_NAME, MasterServicer
from elasticdl_tpu.master.task_dispatcher import TaskDispatcher

logger = get_logger("master")


def build_dispatcher(args, spec) -> TaskDispatcher:
    """The job's TaskDispatcher from its parsed args + model spec —
    shards, sizing, deferred train-end callback, max-steps bounds.
    Factored out of ``Master.__init__`` so the ``--standby`` role can
    keep a warm continuously-replayed dispatcher built from the
    IDENTICAL config (the contract every journal-replay path depends
    on) and hand it over at promotion."""
    reader_params = parse_data_reader_params(
        getattr(args, "data_reader_params", "")
    )
    reader_of = lambda origin: create_data_reader(  # noqa: E731
        data_origin=origin,
        custom_reader=spec.custom_data_reader,
        **reader_params,
    )
    training_data = getattr(args, "training_data", "")
    validation_data = getattr(args, "validation_data", "")
    prediction_data = getattr(args, "prediction_data", "")
    if getattr(args, "stream_dir", ""):
        # Streaming mode (master/stream_ingest.py): no finite training
        # shard table — tasks are generated from the stream tail by the
        # StreamIngestor, so the dispatcher starts empty and never
        # finishes until the stream closes. Eval shards still come from
        # --validation_data; rounds open on watermark progress instead
        # of epoch end.
        return TaskDispatcher(
            training_shards={},
            evaluation_shards=(
                reader_of(validation_data).create_shards()
                if validation_data else {}
            ),
            records_per_task=(
                args.minibatch_size * args.num_minibatches_per_task
            ),
            streaming=True,
        )
    dispatcher = TaskDispatcher(
        training_shards=(
            reader_of(training_data).create_shards()
            if training_data else {}
        ),
        evaluation_shards=(
            reader_of(validation_data).create_shards()
            if validation_data else {}
        ),
        prediction_shards=(
            reader_of(prediction_data).create_shards()
            if prediction_data else {}
        ),
        records_per_task=(
            args.minibatch_size * args.num_minibatches_per_task
        ),
        num_epochs=getattr(args, "num_epochs", 1),
    )
    if training_data:
        # Queue the train-end callback task when the job drains so a
        # worker runs on_train_end (SavedModelExporter etc. — reference
        # task_dispatcher.py:206-241).
        dispatcher.add_deferred_callback(
            dispatcher.create_train_end_callback_task
        )
    if getattr(args, "max_steps", 0):
        dispatcher.set_max_steps(args.max_steps, args.minibatch_size)
    # MaxStepsStopping callback also bounds dispatch
    # (reference callbacks.py:57-98).
    from elasticdl_tpu.callbacks import MaxStepsStopping, find_callback

    cbs = spec.callbacks_fn() if spec.callbacks_fn else []
    ms = find_callback(cbs, MaxStepsStopping)
    # CLI --max_steps wins over the callback (same precedence as
    # LocalExecutor).
    if ms is not None and not getattr(args, "max_steps", 0):
        dispatcher.set_max_steps(ms.max_steps, args.minibatch_size)
    return dispatcher


class _ProberTenantDispatcher:
    """Dispatcher stand-in for the synthetic-prober tenant
    (observability/prober.py PROBER_TENANT): the prober consumes no
    worker leases and never drains — it registers with the gang
    scheduler only so preemption/resume of the canary plane is
    arbitrated and observable like any real job. ``finished()`` is
    always False; _job_finished() cancels the tenant once it is the
    only job keeping the scheduler busy, so it cannot wedge master
    exit. Must be passed explicitly at submit() — a dispatcher-less
    job would be rebuilt from ``default_dispatcher_factory`` at admit
    time and cancelled as unloadable."""

    def finished(self) -> bool:
        return False

    def queue_depths(self):
        return (0, 0)

    def preempt_leases(self, reason: str = "") -> int:
        return 0

    def get(self, worker_id):
        return None

    def apply_report(self, task_id, success, err_reason=""):
        return None, -1, False, True


class Master:
    def __init__(self, args, k8s_client=None, warm_state=None):
        """``warm_state`` (the ``--standby`` promotion handover):
        ``{"dispatcher": a continuously-replayed TaskDispatcher,
        "stats": its replay carry}``. With it, construction SKIPS the
        cold journal replay — the standby already folded every record
        into the dispatcher it hands over, and the caller already
        published the fence — and only opens the new generation +
        re-arms around the warm state. Without it (the default),
        behavior is unchanged: fresh dispatcher, full recovery replay
        when the journal has state."""
        self._args = args
        self._spec = get_model_spec(
            model_zoo=args.model_zoo,
            model_def=args.model_def,
            dataset_fn=args.dataset_fn,
            loss=args.loss,
            optimizer=args.optimizer,
            eval_metrics_fn=args.eval_metrics_fn,
            callbacks=args.callbacks,
            custom_data_reader=args.custom_data_reader,
        )
        validation_data = getattr(args, "validation_data", "")
        training_data = getattr(args, "training_data", "")
        if warm_state is not None:
            self.task_dispatcher = warm_state["dispatcher"]
        else:
            self.task_dispatcher = build_dispatcher(args, self._spec)

        # Master crash recovery (master/journal.py): with --journal_dir
        # the dispatcher writes every dispatch/report through a
        # checksummed write-ahead journal; a restarted master replays
        # snapshot + tail here — AFTER the deferred callback and
        # max-steps config above, which replay depends on, and BEFORE
        # the servicer exists, so it is born with the recovered state.
        from elasticdl_tpu.master.journal import (
            MasterJournal,
            recover_master_state,
        )

        self._journal = None
        self._recovery_stats = None
        journal_dir = getattr(args, "journal_dir", "")
        if warm_state is not None and not journal_dir:
            raise ValueError(
                "warm_state handover requires --journal_dir (the "
                "standby replays FROM it)"
            )
        if journal_dir:
            self._journal = MasterJournal(journal_dir)
            if warm_state is not None:
                # Warm promotion: no replay — the handed-over
                # dispatcher IS the replayed state (tail included; the
                # caller drained it after publishing the fence). Open
                # our generation above the fence, stamp the fence
                # record, and re-attach write-through.
                stats = dict(warm_state["stats"])
                stats["known_workers"] = sorted(
                    stats["known_workers"]
                )
                generation = self._journal.open_generation()
                self._journal.append("fence", generation=generation)
                self.task_dispatcher.attach_journal(self._journal)
                stats["generation"] = generation
                self._recovery_stats = stats
            elif self._journal.has_state():
                self._recovery_stats = recover_master_state(
                    self._journal, self.task_dispatcher
                )
            else:
                self._journal.open_generation()
                self.task_dispatcher.attach_journal(self._journal)

        tb_service = None
        if getattr(args, "tensorboard_log_dir", ""):
            from elasticdl_tpu.master.tensorboard_service import (
                TensorboardService,
            )

            tb_service = TensorboardService(args.tensorboard_log_dir)
        self.tb_service = tb_service
        metrics_fns = (
            self._spec.eval_metrics_fn()
            if self._spec.eval_metrics_fn else {}
        )
        self.evaluation_service = EvaluationService(
            self.task_dispatcher,
            metrics_fns,
            eval_steps=getattr(args, "evaluation_steps", 0),
            start_delay_secs=getattr(
                args, "evaluation_start_delay_secs", 0
            ),
            throttle_secs=getattr(args, "evaluation_throttle_secs", 0),
            # A streaming job trains without --training_data, and its
            # dispatcher holds the eval shards back for the watermark
            # trigger — eval_only would open a round whose tasks were
            # never queued and wedge every later trigger behind it.
            eval_only=bool(
                validation_data and not training_data
                and not getattr(args, "stream_dir", "")
            ),
            summary_writer=tb_service,
        )
        if self._journal is not None:
            # Round state is event-sourced onto the same journal:
            # restore the replayed open round FIRST (a recovered
            # master resumes it instead of dropping the metrics),
            # then attach for write-through.
            if self._recovery_stats is not None:
                self.evaluation_service.restore_recovered(
                    self._recovery_stats.get("eval")
                )
            self.evaluation_service.attach_journal(self._journal)
        # Telemetry plane: master-local registry (dispatcher gauges,
        # straggler counter) + worker snapshot aggregation + /metrics;
        # selected aggregates mirror into TensorBoard each run tick.
        from elasticdl_tpu.observability import MetricsPlane

        metrics_ttl = getattr(args, "metrics_ttl_secs", None)
        if metrics_ttl is None:
            # Documented default: 2x the straggler deadline, so a worker
            # that is merely slow (silent for one whole task) is never
            # aged out of the cluster view.
            metrics_ttl = 2.0 * getattr(args, "task_timeout_secs", 300.0)
        self.metrics_plane = MetricsPlane(
            ttl_secs=metrics_ttl,
            summary_writer=tb_service,
        )
        # SLO engine (observability/timeseries.py + slo.py): the run
        # loop samples the cluster view into a bounded time-series
        # store and evaluates burn-rate / threshold / absence rules on
        # it; /timeseries and /alerts serve next to /metrics, and with
        # --incident_dir a firing rule captures a black-box bundle.
        ts_secs = float(getattr(args, "timeseries_secs", 5.0) or 0.0)
        if ts_secs > 0:
            from elasticdl_tpu.observability import slo as slo_mod

            self.metrics_plane.enable_timeseries(cadence_secs=ts_secs)
            rules_path = getattr(args, "slo_rules", "")
            rules = (
                slo_mod.load_rules(rules_path) if rules_path else None
            )
            recorder = None
            incident_dir = getattr(args, "incident_dir", "")
            if incident_dir:
                if not int(getattr(args, "flight_recorder", 0) or 0):
                    logger.warning(
                        "--incident_dir without --flight_recorder: "
                        "incident bundles will carry an empty trace "
                        "timeline (series window, attribution, and "
                        "journal tail are still captured)"
                    )
                recorder = slo_mod.IncidentRecorder(
                    incident_dir,
                    metrics_plane=self.metrics_plane,
                    store=self.metrics_plane.timeseries,
                    journal_tail_fn=(
                        self._journal.tail if self._journal else None
                    ),
                )
            self.metrics_plane.enable_slo(
                rules=rules, incident_recorder=recorder
            )
        # Workload attribution (observability/principal.py): the
        # master's own outbound RPCs are control-plane by definition.
        from elasticdl_tpu.observability import principal as _principal

        _principal.set_process_principal(
            job=str(getattr(args, "job_name", "") or ""),
            component="master", purpose="control",
        )
        # Distributed tracing (observability/tracing.py): with a
        # recorder installed, dispatch spans + collected worker spans
        # serve on /traces next to /metrics.
        recorder_spans = int(getattr(args, "flight_recorder", 0) or 0)
        if recorder_spans > 0:
            from elasticdl_tpu.observability import tracing

            tracing.set_process_role("master")
            tracing.install_recorder(
                tracing.FlightRecorder(recorder_spans)
            )
        # Continuous profiling (observability/profiler.py): flame-table
        # windows from this process land on /profile next to the
        # piggybacked worker/component profiles.
        from elasticdl_tpu.observability import profiler as _profiler

        _profiler.maybe_start_from_args(args, "master")
        # Usage-plane tenant cap (observability/usage.py): a multi-job
        # fleet must not fold real tenants into __other__.
        from elasticdl_tpu.observability import usage as _usage

        _usage.set_max_jobs(
            int(getattr(args, "usage_max_jobs", 0) or 0) or None
        )
        # Multi-job control plane (master/scheduler.py, --sched): the
        # gang scheduler's job table event-sources onto the same
        # journal; cold recovery and the warm-standby handover both
        # restore it from the replay carry below.
        self.scheduler = None
        if getattr(args, "sched", False):
            from elasticdl_tpu.master.scheduler import GangScheduler

            def sched_slots():
                # getattr: render()/this closure can run during
                # __init__ (primary-job adoption below), before the
                # instance_manager attribute is assigned.
                im = getattr(self, "instance_manager", None)
                if im is not None:
                    return len(im.live_workers)
                live = len(self.servicer.worker_liveness())
                return live or int(getattr(args, "num_workers", 1))

            self.scheduler = GangScheduler(
                sched_slots,
                journal=self._journal,
                usage_fn=self.metrics_plane.usage,
                registry=self.metrics_plane.registry,
            )
            if self._recovery_stats is not None:
                self.scheduler.restore(
                    self._recovery_stats.get("sched")
                )
            # The CLI's own job enters the table like any tenant —
            # in --sched mode leases come exclusively from the
            # arbiter, so an unsubmitted primary job would never
            # dispatch. A fresh start submits it (journaled); after
            # recovery the entry is already in the restored table and
            # only the volatile half (the recovered dispatcher) needs
            # re-binding.
            primary_job = getattr(args, "job_name", "") or "default"
            if not self.task_dispatcher.finished():
                try:
                    self.scheduler.submit(
                        primary_job,
                        gang_size=max(1, int(
                            getattr(args, "num_workers", 1) or 1
                        )),
                        dispatcher=self.task_dispatcher,
                    )
                except ValueError:
                    # Already in the restored table (recovery path):
                    # re-bind the volatile half only.
                    self.scheduler.bind_job(
                        primary_job, dispatcher=self.task_dispatcher
                    )
            self.metrics_plane.add_json_route(
                "/sched", lambda params: self.scheduler.render()
            )
        self.servicer = MasterServicer(
            self.task_dispatcher,
            self.evaluation_service,
            task_timeout_secs=getattr(args, "task_timeout_secs", 300.0),
            metrics_plane=self.metrics_plane,
            journal=self._journal,
            generation=(
                self._journal.generation if self._journal else 0
            ),
            scheduler=self.scheduler,
        )
        if self._recovery_stats is not None:
            # Re-arm the servicer with the recovered high-water marks:
            # eval triggering continues from the journaled model
            # version, and surviving leases get fresh straggler clocks.
            self.servicer.model_version = self._recovery_stats[
                "model_version"
            ]
            self.servicer.seed_task_start_times(
                list(self.task_dispatcher.doing_start_times())
            )
            if self._recovery_stats.get("resize"):
                # Crash mid-resize: re-offer the pending directive.
                self.servicer.rearm_resize(
                    self._recovery_stats["resize"]
                )
        # Streaming ingestion (master/stream_ingest.py): tail the
        # --stream_dir partitions into the streaming dispatcher. Built
        # AFTER the servicer so watermark-triggered eval rounds carry
        # the live model version, and after recovery so the ingestor's
        # eval marker seeds from the RESTORED committed watermark (a
        # relaunch resumes pumping from the journaled cursors — offsets
        # below the watermark are never re-tasked).
        self.stream_ingestor = None
        if getattr(args, "stream_dir", ""):
            from elasticdl_tpu.data.stream import FileTailStream
            from elasticdl_tpu.master.stream_ingest import (
                StreamIngestor,
            )

            self.stream_ingestor = StreamIngestor(
                FileTailStream(args.stream_dir),
                self.task_dispatcher,
                max_todo=int(getattr(args, "stream_max_todo", 64)),
                eval_service=self.evaluation_service,
                eval_every_records=int(
                    getattr(args, "stream_eval_every_records", 0)
                ),
                model_version_fn=lambda: self.servicer.model_version,
                metrics_registry=self.metrics_plane.registry,
            )
            self.metrics_plane.add_json_route(
                "/stream",
                lambda params: self.stream_ingestor.render(),
            )
        self._server = None
        self.instance_manager = None
        self.autoscaler = None
        self.row_reshard = None
        self.row_pod_scaler = None
        # Synthetic canary plane (observability/prober.py, --probes):
        # built in prepare() once the RPC server's port is known — the
        # probes go through the PUBLIC wire surfaces, including the
        # master's own.
        self.prober = None
        self._k8s_client = k8s_client
        # SIGTERM grace path (main() installs the handler): the run
        # loop exits at the next poll tick and stop() tears the job
        # down in order — workers get THEIR SIGTERMs (pod deletion) and
        # checkpoint + hand tasks back inside their own grace windows.
        self._stop_requested = False

    # ---- assembly -------------------------------------------------------

    def _master_port(self) -> int:
        addr = getattr(self._args, "master_addr", "") or ":50001"
        try:
            return int(addr.rsplit(":", 1)[1])
        except (IndexError, ValueError):
            return 50001

    def _worker_command(self, worker_id: int):
        """Re-serialize parsed args into the worker CLI
        (reference master.py:365-485 + build_arguments_from_parsed_result)."""
        passthrough = build_arguments_from_parsed_result(
            self._args,
            # jax_process_id filtered: the master's own value (-1) must
            # not override the per-worker flag set below.
            filter_args=["worker_id", "force", "master_addr",
                         "jax_process_id", "row_service_addr"],
        )
        # The user's --checkpoint_dir_for_init (warm start) passes through
        # untouched; elastic relaunch resume comes from the worker itself
        # preferring the rolling --checkpoint_dir when it holds a valid
        # version (worker/main.py resolve_init_checkpoint).
        cmd = [sys.executable, "-m", "elasticdl_tpu.worker.main",
               "--worker_id", str(worker_id),
               "--master_addr", self._master_addr_for_workers()]
        if self._uses_row_service():
            cmd += ["--row_service_addr", self._row_service_addr()]
        if getattr(self._args, "num_jax_processes", 1) > 1:
            # Stable jax.distributed process id across gang restarts
            # (multi-host workers always relaunch with original ids).
            cmd += ["--jax_process_id", str(worker_id)]
        return (
            cmd
            + passthrough
        )

    def _uses_row_service(self) -> bool:
        """Host-tier models whose zoo module defines make_row_service get
        a service pod (the reference always ran PS pods for the PS
        strategy; modules wanting process-local tables simply don't
        define the factory)."""
        return (
            self._spec.make_host_runner is not None
            and getattr(self._spec.module, "make_row_service", None)
            is not None
        )

    def _num_row_service_shards(self) -> int:
        n = max(
            1, int(getattr(self._args, "num_row_service_shards", 1) or 1)
        )
        if n > 16:
            # `clean` sweeps per-shard Services over a fixed 0..15
            # range (k8s_client.delete_job_resources) — more shards
            # would leak Services on cleanup.
            raise ValueError(
                f"--num_row_service_shards={n} exceeds the supported "
                "maximum of 16"
            )
        return n

    def _row_service_addr(self) -> str:
        """Comma list of per-shard addresses: the workers scatter rows
        by id % N client-side (row_service._ShardedTable — the
        reference's N PS pods, worker.py:404-414)."""
        from elasticdl_tpu.platform.k8s_client import (
            ROW_SERVICE_PORT,
            get_row_service_service_name,
        )

        return ",".join(
            "%s:%d" % (
                get_row_service_service_name(
                    self._args.job_name, shard
                ),
                ROW_SERVICE_PORT,
            )
            for shard in range(self._num_row_service_shards())
        )

    def _row_service_command(self, shard: int = 0):
        from elasticdl_tpu.platform.k8s_client import ROW_SERVICE_PORT

        cmd = [sys.executable, "-m", "elasticdl_tpu.embedding.row_service",
               "--model_zoo", self._args.model_zoo,
               "--model_def", self._args.model_def,
               "--addr", f"[::]:{ROW_SERVICE_PORT}"]
        ckpt = getattr(self._args, "checkpoint_dir", "")
        if ckpt:
            # Its own subdir: the service's row payload is keyed by push
            # count, the workers' by model version. checkpoint_steps is
            # in model versions; the service counts gradient pushes
            # (~num_workers per version), so scale unless the user set
            # the push-unit knob explicitly.
            steps = int(getattr(
                self._args, "row_service_checkpoint_steps", 0
            ) or 0)
            if not steps:
                steps = int(getattr(self._args, "checkpoint_steps", 0)) * max(
                    1, int(getattr(self._args, "num_workers", 1))
                )
            # Per-shard subdir: each shard owns exactly its id%N rows
            # (client-side scatter), so checkpoints must not collide.
            # Shard 0 keeps the legacy path (single-shard jobs resume
            # pre-shard checkpoints unchanged).
            subdir = (
                "row_service" if shard == 0 else f"row_service/s{shard}"
            )
            cmd += ["--checkpoint_dir", f"{ckpt}/{subdir}",
                    "--checkpoint_steps", str(steps),
                    "--keep_checkpoint_max",
                    str(getattr(self._args, "keep_checkpoint_max", 3))]
            push_log = str(getattr(
                self._args, "row_service_push_log", "durable"
            ))
            if push_log != "off":
                # Zero-RPO by default wherever durability is
                # configured at all: the write-ahead push log rides
                # next to the checkpoint chain, so a SIGKILLed shard
                # pod loses no acked push (docs/fault_tolerance.md
                # "Zero-RPO row plane"). --row_service_push_log
                # applied|off tunes/disables it (slow-fsync media).
                cmd += ["--push_log_dir", f"{ckpt}/{subdir}_pushlog",
                        "--push_log_ack", push_log,
                        "--push_log_group_ms",
                        str(getattr(
                            self._args,
                            "row_service_push_log_group_ms", 2.0,
                        ))]
            cmd += [
                    # Layout guard: a relaunch with a different
                    # --num_row_service_shards must fail loudly, not
                    # silently lose the rows whose id%N home moved
                    # (row_service.validate_shard_layout).
                    "--shard_id", str(shard),
                    "--num_shards",
                    str(self._num_row_service_shards())]
        admission = int(getattr(
            self._args, "row_service_admission_limit", 0
        ))
        if admission > 0:
            cmd += ["--admission_limit", str(admission)]
        durable_wait = float(getattr(
            self._args, "row_service_push_durable_wait_secs", 60.0
        ))
        if durable_wait != 60.0:
            cmd += ["--push_durable_wait_secs", str(durable_wait)]
        return cmd

    def _master_addr_for_workers(self) -> str:
        from elasticdl_tpu.platform.k8s_client import (
            get_master_service_name,
        )

        return "%s:%d" % (
            get_master_service_name(self._args.job_name),
            self._master_port(),
        )

    def prepare(self):
        """Start services: eval trigger, RPC server, worker pods
        (reference Master.prepare, master.py:184-216)."""
        self.evaluation_service.start_time_trigger()
        if self.stream_ingestor is not None:
            self.stream_ingestor.start(
                interval_secs=float(
                    getattr(self._args, "stream_poll_secs", 0.5)
                )
            )
        admission = None
        admission_limit = int(getattr(
            self._args, "master_admission_limit", 0
        ))
        if admission_limit > 0:
            from elasticdl_tpu.comm import overload

            # One gate for every master handler: the thing being
            # protected (the servicer lock, the worker pool) is
            # per-server, and the ladder keeps control/serving traffic
            # ahead of background reporting when the master saturates.
            admission = overload.AdmissionController(
                admission_limit, tag="master",
            )
        self._server = RpcServer(
            f"[::]:{self._master_port()}",
            {SERVICE_NAME: self.servicer.handlers()},
            admission=admission,
        ).start()
        logger.info("Master RPC serving on port %d", self._server.port)
        self._setup_prober()
        metrics_port = int(getattr(self._args, "metrics_port", -1))
        if metrics_port >= 0:
            self.metrics_plane.serve(port=metrics_port)
        if self.tb_service is not None:
            self.tb_service.start()
        if self._k8s_client is not None:
            from elasticdl_tpu.master.instance_manager import (
                InstanceManager,
            )
            from elasticdl_tpu.platform.k8s_client import (
                get_master_pod_name,
            )

            # Owner reference master→workers so deleting the master pod
            # garbage-collects the whole job (reference
            # k8s_client.py:329-344). Absent when not running as a pod.
            owner = None
            try:
                me = self._k8s_client.get_pod(
                    get_master_pod_name(self._args.job_name)
                )
                if me is not None:
                    owner = {
                        "name": me.metadata.name,
                        "uid": me.metadata.uid,
                    }
            except Exception as exc:
                logger.warning("No master pod owner reference: %s", exc)

            self.instance_manager = InstanceManager(
                self.task_dispatcher,
                self._k8s_client,
                job_name=self._args.job_name,
                image_name=self._args.image_name,
                worker_command=self._worker_command,
                num_workers=self._args.num_workers,
                namespace=self._args.namespace,
                worker_resource_request=(
                    self._args.worker_resource_request
                ),
                worker_resource_limit=self._args.worker_resource_limit,
                volume=self._args.volume,
                envs=parse_envs(self._args.envs),
                restart_policy=self._args.restart_policy,
                owner=owner,
                multihost=(
                    getattr(self._args, "num_jax_processes", 1) > 1
                ),
                row_service_command=(
                    self._row_service_command
                    if self._uses_row_service() else None
                ),
                row_service_resource_request=getattr(
                    self._args, "row_service_resource_request",
                    "cpu=1,memory=4096Mi",
                ),
                row_service_resource_limit=getattr(
                    self._args, "row_service_resource_limit", ""
                ),
                num_row_service_shards=self._num_row_service_shards(),
                journal=self._journal,
            )
            self.instance_manager.start_watch()
            if self._recovery_stats is not None:
                # Recovered master: the job's pods are still running
                # and their workers are riding out the outage on their
                # reattach grace (worker/task_data_service.py) —
                # re-creating them would 409 AND strand the survivors.
                # Adopt the ids the journal saw; pods that actually
                # died during the outage surface as watch events /
                # straggler timeouts and recover through the normal
                # paths.
                relaunch = self._recovery_stats.get("relaunch") or {}
                self.instance_manager.adopt_workers(
                    self._recovery_stats["known_workers"]
                    or list(range(self._args.num_workers)),
                    gang_generation=int(relaunch.get("gang", 0)),
                )
                self.instance_manager.adopt_row_service(
                    relaunch.get("row_service")
                )
            else:
                # Row service first (reference Master.prepare starts PS
                # pods before workers, master.py:202-205); workers
                # retry until it answers.
                self.instance_manager.start_row_service()
                self.instance_manager.start_workers()
        if getattr(self._args, "autoscale", False):
            self._build_autoscaler()
        if getattr(self._args, "row_reshard", False):
            self._build_row_reshard()
        if (
            getattr(self._args, "row_pod_autoscale", False)
            and self.row_reshard is not None
            and self.instance_manager is not None
        ):
            # Pod-closing autoscaling (master/autoscaler.py
            # RowServicePodScaler): split/merge decisions can now
            # actually spawn and drain row-service pods instead of
            # being confined to the launch-time fleet.
            from elasticdl_tpu.master.autoscaler import (
                RowServicePodScaler,
            )
            from elasticdl_tpu.platform.k8s_client import (
                ROW_SERVICE_PORT,
                get_row_service_service_name,
            )

            job_name = self._args.job_name

            def rs_addr(shard: int) -> str:
                name = get_row_service_service_name(job_name,
                                                    shard=shard)
                return f"{name}:{ROW_SERVICE_PORT}"

            self.row_pod_scaler = RowServicePodScaler(
                self.row_reshard, self.instance_manager, rs_addr,
                metrics_registry=self.metrics_plane.registry,
            )

    def _build_row_reshard(self):
        """Row-plane elasticity (master/row_reshard.py): the master
        hosts the shard-map authority over the --row_service_addr
        fleet and ticks its policy next to the autoscaler — live
        range rebalancing off per-shard load plus hot-row replica
        designation off the shards' pull-frequency top-K."""
        from elasticdl_tpu.master.row_reshard import (
            ReshardPolicy,
            ShardMapController,
        )

        args = self._args
        addrs = [
            a.strip()
            for a in getattr(args, "row_service_addr", "").split(",")
            if a.strip()
        ]
        if not addrs:
            logger.warning(
                "--row_reshard needs --row_service_addr; controller "
                "disabled"
            )
            return
        state_path = getattr(args, "row_reshard_state", "")
        if not state_path:
            journal_dir = getattr(args, "journal_dir", "")
            if not journal_dir:
                logger.warning(
                    "--row_reshard needs --row_reshard_state (or a "
                    "--journal_dir to default into); controller "
                    "disabled"
                )
                return
            state_path = os.path.join(journal_dir, "shard_map.json")
        self.row_reshard = ShardMapController(
            state_path,
            journal=self._journal,
            policy=ReshardPolicy(
                replica_top_k=int(
                    getattr(args, "row_replica_top_k", 64)
                ),
                replica_count=int(
                    getattr(args, "row_replica_count", 2)
                ),
                cooldown_secs=float(
                    getattr(args, "row_reshard_cooldown_secs", 30.0)
                ),
            ),
        )
        if self.row_reshard.map is None:
            self.row_reshard.bootstrap(addrs)
        else:
            # Restarted authority: finish any in-flight migration and
            # re-distribute the persisted epoch.
            self.row_reshard.resume()

    def _build_autoscaler(self):
        """Closed-loop autoscaling (master/autoscaler.py): pod scaling
        through the InstanceManager when one exists; without k8s the
        loop still runs (decision telemetry, barrier upkeep) but both
        actions are no-ops — in-process mesh scaling is driven by the
        drill/bench harnesses instead."""
        from elasticdl_tpu.master.autoscaler import (
            Autoscaler,
            AutoscalePolicy,
            master_signals,
        )

        args = self._args
        max_workers = int(
            getattr(args, "autoscale_max_workers", 0)
            or getattr(args, "num_workers", 1)
        )
        policy = AutoscalePolicy(
            min_workers=int(getattr(args, "autoscale_min_workers", 1)),
            max_workers=max_workers,
            scale_up_backlog_factor=float(
                getattr(args, "autoscale_up_backlog_factor", 2.0)
            ),
            scale_up_utilization=float(
                getattr(args, "autoscale_up_utilization", 0.7)
            ),
            scale_down_utilization=float(
                getattr(args, "autoscale_down_utilization", 0.3)
            ),
            hysteresis_ticks=int(
                getattr(args, "autoscale_hysteresis_ticks", 3)
            ),
            cooldown_secs=float(
                getattr(args, "autoscale_cooldown_secs", 60.0)
            ),
        )
        manager = self.instance_manager

        def live_count():
            if manager is not None:
                return len(manager.live_workers)
            return max(1, len(self.servicer.worker_liveness()))

        def scale_up(_signals):
            if manager is not None:
                manager.scale_up(1)

        def scale_down(_signals):
            if manager is None:
                return
            live = manager.live_workers
            if live:
                # Drain the youngest worker (highest id): oldest
                # workers hold the warmest compile caches.
                victim = max(live)
                manager.drain_worker(victim)
                self.servicer.remove_worker_metrics(victim)

        # Opt-in trend signal: utilization as the mean over the
        # time-series window instead of the instantaneous snapshot
        # (the old path stays the default; see master_signals).
        timeseries = None
        if getattr(args, "autoscale_from_timeseries", False):
            timeseries = self.metrics_plane.timeseries
            if timeseries is None:
                logger.warning(
                    "--autoscale_from_timeseries needs "
                    "--timeseries_secs > 0; falling back to the "
                    "snapshot utilization signal"
                )
        self.autoscaler = Autoscaler(
            policy,
            master_signals(
                self.task_dispatcher, self.servicer,
                self.metrics_plane, live_count,
                timeseries=timeseries,
                trend_window_secs=float(getattr(
                    args, "autoscale_trend_window_secs", 120.0
                )),
            ),
            scale_up, scale_down,
        )

    def _setup_prober(self):
        """Synthetic canary plane (--probes; observability/prober.py):
        black-box probes on intervals against the reserved canary
        keyspace, every run tagged with the ``canary`` principal
        purpose. Wired in prepare() because the dispatch probe targets
        the master's OWN public RPC port. Mounts ``/probes`` and the
        aggregated ``/healthz`` verdict, and — in --sched mode —
        registers the prober as a low-priority tenant so it survives
        and observes preemption."""
        args = self._args
        if not getattr(args, "probes", False):
            return
        from elasticdl_tpu.observability import prober as prober_mod

        interval = float(
            getattr(args, "probe_interval_secs", 15.0) or 15.0
        )
        recorder = (
            self.metrics_plane.slo.incident_recorder
            if self.metrics_plane.slo is not None else None
        )
        sched = prober_mod.ProbeScheduler(
            registry=self.metrics_plane.registry,
            incident_recorder=recorder,
        )
        # Dispatch plane: through the wire, like a worker would.
        # worker_id -1 records no liveness; a leased task hands
        # straight back under the graceful "preempted:" reason (no
        # retry budget burned).
        sched.register(
            "dispatch_roundtrip",
            prober_mod.make_dispatch_roundtrip_probe(
                f"localhost:{self._server.port}"
            ),
            interval_secs=interval,
            description="get_task/report_task_result roundtrip "
                        "against the master's dispatch plane",
        )
        # Row tier: read-your-writes + fresh-client reshard
        # convergence whenever a row-service fleet is addressable.
        row_addr = getattr(args, "row_service_addr", "") or (
            self._row_service_addr()
            if self._k8s_client is not None and self._uses_row_service()
            else ""
        )
        if row_addr:
            canary_client = prober_mod.RowCanaryClient(row_addr)
            sched.register(
                "row_ryw",
                prober_mod.make_row_ryw_probe(canary_client),
                interval_secs=interval,
                description="durable canary push -> immediate pull "
                            "against the row tier (read-your-writes, "
                            "RPO=0 from outside)",
            )
            sched.register(
                "reshard_convergence",
                prober_mod.make_reshard_convergence_probe(row_addr),
                interval_secs=interval,
                description="fresh client (no cached map) rides "
                            "REDIRECTs to a converged canary pull",
            )
            serving_addr = getattr(args, "probe_serving_addr", "")
            if serving_addr:
                feature_key = (
                    getattr(args, "probe_serving_feature_key", "")
                    or "ids"
                )
                canary = prober_mod.canary_id(1)
                predict = prober_mod.make_router_predictor(
                    serving_addr, feature_key, [canary]
                )

                def push_canary(sign, _client=canary_client,
                                _id=canary):
                    import numpy as np

                    dim = _client.dim()
                    _client.push(
                        np.array([_id], np.int64),
                        np.full((1, dim), sign * 1e-3, np.float32),
                    )

                sched.register(
                    "serving_freshness",
                    prober_mod.make_serving_freshness_probe(
                        predict, push_canary
                    ),
                    interval_secs=interval,
                    description="canary push -> serving router "
                                "prediction change (outside-in "
                                "push-to-servable)",
                )
        if getattr(args, "stream_dir", "") and \
                self.stream_ingestor is not None:
            append = prober_mod.make_stream_appender(args.stream_dir)

            def canary_watermark():
                part = self.stream_ingestor.render()["partitions"].get(
                    prober_mod.CANARY_STREAM_PARTITION
                )
                return None if part is None else int(part["committed"])

            sched.register(
                "stream_watermark",
                prober_mod.make_stream_watermark_probe(
                    append, canary_watermark
                ),
                interval_secs=interval,
                description="canary stream append -> committed "
                            "watermark advances past it",
            )
        if self.scheduler is not None:
            tenant = prober_mod.PROBER_TENANT
            tenant_disp = _ProberTenantDispatcher()
            try:
                self.scheduler.submit(
                    tenant, spec={"synthetic": True}, priority=-100,
                    gang_size=1, dispatcher=tenant_disp,
                    preempt_cb=sched.note_preempted,
                    resume_cb=sched.note_resumed,
                )
            except ValueError:
                # Already in the journal-restored table (recovery):
                # re-bind the volatile half only.
                self.scheduler.bind_job(
                    tenant, dispatcher=tenant_disp,
                    preempt_cb=sched.note_preempted,
                    resume_cb=sched.note_resumed,
                )
            sched.note_registered()
        self.metrics_plane.add_json_route(
            "/probes", lambda params: sched.render()
        )
        self.metrics_plane.set_health(sched.healthz)
        sched.start(poll_secs=min(1.0, max(0.05, interval / 4.0)))
        self.prober = sched

    def request_stop(self):
        """Ask the run loop to exit at the next tick (SIGTERM path).
        Signal-handler safe: sets a flag, no locks, no teardown here."""
        self._stop_requested = True

    def _job_finished(self) -> bool:
        """The run loop's exit gate: the primary dispatcher drained
        AND (in --sched mode) every scheduler job reached a terminal
        state — a preempted job still owed a resume must keep the
        fleet up."""
        if not self.task_dispatcher.finished():
            return False
        if self.scheduler is None:
            return True
        if not self.scheduler.idle() and self.prober is not None:
            # The prober tenant never drains by design. When it is the
            # ONLY job still non-terminal, the real work is done:
            # retire the canary tenant so it cannot wedge master exit.
            from elasticdl_tpu.master.scheduler import TERMINAL_STATES
            from elasticdl_tpu.observability.prober import PROBER_TENANT

            jobs = self.scheduler.export_state()["jobs"]
            open_jobs = [
                job_id for job_id, job in jobs.items()
                if job["state"] not in TERMINAL_STATES
            ]
            if open_jobs == [PROBER_TENANT]:
                self.scheduler.cancel(PROBER_TENANT)
        return self.scheduler.idle()

    def run(self, poll_secs: float = 5.0):
        """Sleep until the dispatcher drains (reference master.py:218-238);
        each tick, kill stragglers (3× mean task time, :487-509)."""
        try:
            while not self._job_finished():
                if self._stop_requested:
                    logger.warning(
                        "stop requested (SIGTERM); tearing the job "
                        "down gracefully with tasks still pending"
                    )
                    break
                time.sleep(poll_secs)
                for task_id, worker_id in self.servicer.find_timeout_tasks():
                    logger.warning(
                        "Task %d on worker %d timed out; recovering",
                        task_id, worker_id,
                    )
                    if self.instance_manager is not None:
                        self.instance_manager.kill_worker(worker_id)
                    else:
                        self.task_dispatcher.recover_tasks(worker_id)
                    # The relaunch comes back under a NEW worker id —
                    # drop the dead id's series now, not at the TTL.
                    self.servicer.remove_worker_metrics(worker_id)
                # Resize-barrier upkeep: refresh membership from the
                # live fleet so a worker that died mid-barrier (its
                # tasks recovered above / by the watch path) cannot
                # wedge it — its replacement acks under its own id.
                if self.servicer.resize_status() is not None:
                    live = (
                        list(self.instance_manager.live_workers)
                        if self.instance_manager is not None
                        else list(self.servicer.worker_liveness())
                    )
                    self.servicer.maybe_complete_resize(live)
                if self.autoscaler is not None:
                    self.autoscaler.tick()
                if self.scheduler is not None:
                    # Multi-job arbitration: completion sweep, gang
                    # allocation, preemption, resume. A fenced journal
                    # aborts the tick (JournalFencedError) — a zombie
                    # arbiter must stop, and the run loop exits on the
                    # next _job_finished/servicer fence check.
                    try:
                        self.scheduler.tick()
                    except Exception:
                        logger.exception("scheduler tick failed")
                if self.row_reshard is not None:
                    # Row-plane elasticity: rebalance ranges / refresh
                    # hot-row replicas (tick() contains its own
                    # failures — a flaky shard must not take the master
                    # loop down).
                    self.row_reshard.tick()
                if self.row_pod_scaler is not None:
                    # Pod-closing half of merges: drain the pod behind
                    # any slot the controller just retired.
                    try:
                        self.row_pod_scaler.tick()
                    except Exception:
                        logger.exception("row pod scaler tick failed")
                # SLO plane: sample the time-series store (if due) and
                # evaluate the rules on the fresh window.
                self.metrics_plane.slo_tick()
                self.metrics_plane.publish_tensorboard(
                    self.servicer.model_version
                )
            if self.scheduler is not None and not self._stop_requested:
                # In --sched mode the finished signal flips at the
                # same arbitration tick that satisfies the exit gate
                # above — unlike the single-job plane, where workers
                # observe it the moment the last report lands, a full
                # poll window before the master exits. Serve the
                # finished response for a couple of poll intervals so
                # the fleet learns completion from get_task instead of
                # burning its reattach grace on a drained job.
                time.sleep(min(10.0, 2 * poll_secs))
        finally:
            # The last tasks finish during the final poll sleep; flush
            # that interval's aggregates to TensorBoard before stop()
            # tears down the plane, or the tfevents tail under-counts.
            self.metrics_plane.publish_tensorboard(
                self.servicer.model_version
            )
            self.stop()
        return 0

    def stop(self):
        if self.prober is not None:
            # Before the metrics plane: a probe red landing mid-teardown
            # must not race the incident recorder's flush.
            self.prober.stop()
        if self.stream_ingestor is not None:
            self.stream_ingestor.stop()
        if self.row_reshard is not None:
            self.row_reshard.close()
        self.metrics_plane.stop()
        self.evaluation_service.stop()
        if self.instance_manager is not None:
            self.instance_manager.stop()
        if self._server is not None:
            self._server.stop(grace=2.0)
        # After the server: an in-flight report draining through the
        # grace period still writes through the journal; closing first
        # would turn it into an INTERNAL error at the worker.
        if self._journal is not None:
            self._journal.close()
        # Keep serving TensorBoard after training like the reference
        # master (master.py:256-269) only in the CLI path (main()).

    @property
    def port(self):
        return self._server.port if self._server else None


def run_standby(args, k8s_client=None) -> int:
    """``--standby`` role (docs/fault_tolerance.md "Hot standby &
    failover"): keep a WARM continuously-replayed dispatcher by
    tailing the primary's journal, heartbeat the primary, and on
    missed heartbeats FENCE the old incarnation and promote into a
    full ``Master`` that ADOPTS the warm dispatcher.

    Two costs used to sit between detection and serving: the cold
    start (pod reschedule, interpreter boot, imports, model-spec
    load) and the full journal replay. This role pays the first up
    front and AMORTIZES the second across the standby's lifetime —
    each poll folds only the appended tail into the warm dispatcher
    (``StandbyMaster.poll_journal``: incremental read cursor +
    seq-gated ``apply_replay``), so promotion replays nothing but the
    last partial poll. ``Master(args, warm_state=...)`` then skips
    ``recover_master_state`` entirely and re-arms the full feature
    set (metrics plane, autoscaler, k8s adoption of running pods)
    around the handed-over state — pinned by
    ``tests/test_failover.py::test_warm_handover_skips_full_replay``.
    """
    import time as _time

    from elasticdl_tpu.master.standby import StandbyMaster
    from elasticdl_tpu.observability import default_registry

    journal_dir = getattr(args, "journal_dir", "")
    if not journal_dir:
        logger.error("--standby requires --journal_dir (shared with "
                     "the primary)")
        return 2
    primary = getattr(args, "primary_addr", "") or args.master_addr
    heartbeat_secs = float(
        getattr(args, "standby_heartbeat_secs", 1.0)
    )
    miss_threshold = int(getattr(args, "standby_miss_threshold", 3))
    registry = default_registry()
    m_failover = registry.histogram(
        "master_failover_seconds",
        "Hot-standby takeover latency: primary declared dead -> "
        "promoted master serving",
    )
    # Pre-warm the expensive import path (model zoo + spec); the spec
    # also feeds the warm dispatcher factory below — the standby MUST
    # build dispatchers from the identical job config the primary
    # used, or its replay diverges. Bounded retries: a transient
    # zoo/volume read error at pod start must not one-shot the
    # process and silently strip the job's failover protection.
    spec = None
    for attempt in range(5):
        try:
            spec = get_model_spec(
                model_zoo=args.model_zoo, model_def=args.model_def,
                dataset_fn=args.dataset_fn, loss=args.loss,
                optimizer=args.optimizer,
                eval_metrics_fn=args.eval_metrics_fn,
                callbacks=args.callbacks,
                custom_data_reader=args.custom_data_reader,
            )
            break
        except Exception as exc:
            logger.warning(
                "standby spec load failed (attempt %d/5): %s",
                attempt + 1, exc,
            )
            _time.sleep(2.0)
    if spec is None:
        logger.error(
            "standby cannot load the model spec; exiting (the spec "
            "builds the warm dispatcher — without it promotion would "
            "diverge from the primary's replay)"
        )
        return 2
    # Report into the primary's cluster view so the master-side
    # absence rule on the heartbeat series can fire when this standby
    # dies (failover protection gone).
    from elasticdl_tpu.observability.reporter import (
        ComponentMetricsReporter,
    )

    reporter = ComponentMetricsReporter(primary, "standby")
    reporter.start()
    # The warm tail: StandbyMaster's poll/heartbeat halves only — the
    # promotion itself goes through Master(warm_state=) below so the
    # CLI role keeps the full production assembly (assemble/serve_addr
    # are the embedded path's concern and stay unused here).
    standby = StandbyMaster(
        journal_dir,
        dispatcher_factory=lambda: build_dispatcher(args, spec),
        assemble=None,
        primary_addr=primary,
        serve_addr="",
        heartbeat_secs=heartbeat_secs,
        miss_threshold=miss_threshold,
    )
    logger.info(
        "standby: heartbeating %s every %.2fs (takeover after %d "
        "misses), warm-tailing %s", primary, heartbeat_secs,
        miss_threshold, standby._journal.path,
    )
    while True:
        standby.heartbeat()
        standby.poll_journal()
        if standby._misses >= miss_threshold:
            break
        _time.sleep(heartbeat_secs)
    t_detect = _time.monotonic()
    reporter.stop()
    standby.stop()
    # Fence FIRST (a partitioned-but-alive primary must be locked out
    # of the journal before the promoted master trusts its replay),
    # drain the race, release the journal — StandbyMaster.hand_over
    # keeps this ordering in ONE place with the embedded take_over.
    warm = standby.hand_over()
    logger.warning(
        "standby taking over: fence generation %d published; "
        "promoting the WARM dispatcher into a full master "
        "(%d record(s) were warm-replayed over this standby's "
        "lifetime)", warm["fence_generation"],
        warm["stats"]["replayed"],
    )
    master = Master(args, k8s_client=k8s_client, warm_state=warm)
    master.prepare()
    m_failover.observe(_time.monotonic() - t_detect)
    return master.run()


def main(argv=None):
    args = parse_master_args(argv)
    k8s_client = None
    if getattr(args, "image_name", ""):
        from elasticdl_tpu.platform import k8s_client as k8s_mod

        try:
            k8s_client = k8s_mod.Client(
                namespace=args.namespace,
                force_kube_config=args.force_use_kube_config_file,
            )
        except k8s_mod.K8sUnavailableError as exc:
            logger.warning("k8s unavailable (%s); running master-only", exc)
    if getattr(args, "standby", False):
        return run_standby(args, k8s_client=k8s_client)
    master = Master(args, k8s_client=k8s_client)
    master.prepare()
    # Graceful pod eviction: without a handler, SIGTERM kills the
    # master mid-poll and the workers' pods linger ownerless with
    # in-flight work; with it, run() exits at the next tick and stop()
    # deletes worker pods (each then runs its own SIGTERM checkpoint +
    # task hand-back) inside the master's grace period.
    import signal

    try:
        signal.signal(
            signal.SIGTERM, lambda *_: master.request_stop()
        )
    except ValueError:
        pass  # not the main thread (embedded use)
    code = master.run()
    if master.tb_service is not None:
        # The post-training TensorBoard keep-alive must not outlive a
        # SIGTERM: the handler swallows further signals, so looping
        # here would burn the whole grace period and end in SIGKILL.
        while (not master._stop_requested
               and master.tb_service.keep_running()):
            time.sleep(10)
        master.tb_service.close()
    return code


if __name__ == "__main__":
    sys.exit(main())
