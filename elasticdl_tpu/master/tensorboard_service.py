"""Master-side TensorBoard service with a dependency-free tfevents writer.

The reference's ``TensorboardService`` wraps a ``tf.summary`` writer and
spawns a ``tensorboard`` subprocess on the master
(reference master/tensorboard_service.py:8-50). This framework has no
TensorFlow, so the event-file format is implemented directly:

- TFRecord framing: ``uint64 length, masked_crc32c(length), payload,
  masked_crc32c(payload)``,
- payload: a hand-encoded ``tensorflow.Event`` protobuf
  (wall_time=1:double, step=2:int64, summary=5 → repeated
  ``Summary.Value`` with tag=1:string, simple_value=2:float),

which standard TensorBoard reads natively. Scalars are also mirrored to
``scalars.jsonl`` for toolless inspection.
"""

import json
import os
import socket
import struct
import subprocess
import time
from typing import Dict, Optional

from elasticdl_tpu.common.log_utils import get_logger

logger = get_logger("tensorboard")

# crc32c (Castagnoli), table-driven, reflected polynomial 0x82F63B78.
_CRC_TABLE = []
for _i in range(256):
    _c = _i
    for _ in range(8):
        _c = (_c >> 1) ^ (0x82F63B78 & -(_c & 1))
    _CRC_TABLE.append(_c & 0xFFFFFFFF)


def _crc32c(data: bytes) -> int:
    crc = 0xFFFFFFFF
    for b in data:
        crc = _CRC_TABLE[(crc ^ b) & 0xFF] ^ (crc >> 8)
    return crc ^ 0xFFFFFFFF


def _masked_crc(data: bytes) -> int:
    crc = _crc32c(data)
    return (((crc >> 15) | (crc << 17)) + 0xA282EAD8) & 0xFFFFFFFF


def _varint(n: int) -> bytes:
    out = bytearray()
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def _field(num: int, wire: int) -> bytes:
    return _varint((num << 3) | wire)


def _encode_scalar_event(step: int, wall_time: float,
                         scalars: Dict[str, float]) -> bytes:
    values = b""
    for tag, val in scalars.items():
        tag_b = tag.encode()
        v = (
            _field(1, 2) + _varint(len(tag_b)) + tag_b
            + _field(2, 5) + struct.pack("<f", float(val))
        )
        values += _field(1, 2) + _varint(len(v)) + v
    event = (
        _field(1, 1) + struct.pack("<d", wall_time)
        + _field(2, 0) + _varint(step & 0xFFFFFFFFFFFFFFFF)
        + _field(5, 2) + _varint(len(values)) + values
    )
    return event


def _frame(payload: bytes) -> bytes:
    header = struct.pack("<Q", len(payload))
    return (
        header
        + struct.pack("<I", _masked_crc(header))
        + payload
        + struct.pack("<I", _masked_crc(payload))
    )


class SummaryWriter:
    """Append-only tfevents writer for scalar summaries.

    A context manager (``with SummaryWriter(d) as w:``) that flushes on
    every write — a crashed master must leave readable event files, not
    a buffered tail."""

    def __init__(self, logdir: str):
        # exist_ok + recursive: the logdir (and any missing parents —
        # jobs point this at per-run subdirs that don't exist yet) is
        # created on first use.
        os.makedirs(logdir, exist_ok=True)
        fname = "events.out.tfevents.%d.%s" % (
            int(time.time()), socket.gethostname(),
        )
        self._path = os.path.join(logdir, fname)
        self._jsonl = os.path.join(logdir, "scalars.jsonl")
        self._f = open(self._path, "ab")
        # File-version event TensorBoard expects first.
        ver = b"brain.Event:2"
        first = (
            _field(1, 1) + struct.pack("<d", time.time())
            + _field(3, 2) + _varint(len(ver)) + ver
        )
        self._f.write(_frame(first))
        self._f.flush()

    def add_scalars(self, scalars: Dict[str, float], step: int):
        if self._f.closed:
            raise ValueError("SummaryWriter is closed")
        now = time.time()
        self._f.write(_frame(_encode_scalar_event(step, now, scalars)))
        self._f.flush()
        with open(self._jsonl, "a") as jf:
            jf.write(json.dumps(
                {"step": int(step), "wall_time": now, **{
                    k: float(v) for k, v in scalars.items()
                }}
            ) + "\n")

    def flush(self):
        if not self._f.closed:
            self._f.flush()

    def close(self):
        self._f.close()

    def __enter__(self) -> "SummaryWriter":
        return self

    def __exit__(self, exc_type, exc, tb):
        self.close()
        return False


class TensorboardService:
    """Scalar sink for train loss + eval metrics, with an optional
    ``tensorboard`` subprocess like the reference master
    (reference tensorboard_service.py:23-50)."""

    def __init__(self, tensorboard_log_dir: str, master_ip: str = ""):
        self._logdir = tensorboard_log_dir
        self._writer = SummaryWriter(tensorboard_log_dir)
        self._master_ip = master_ip
        self._tb_process: Optional[subprocess.Popen] = None

    def write_dict_to_summary(self, scalars: Dict[str, float], version: int):
        self._writer.add_scalars(scalars, version)

    def write_eval_metrics(self, version: int, results: Dict[str, float]):
        """EvaluationService summary-writer hook
        (reference evaluation_service.py:196-222 writes eval summaries)."""
        if results:
            self._writer.add_scalars(
                {f"eval/{k}": v for k, v in results.items()}, version
            )

    def start(self):
        """Best-effort launch of a tensorboard subprocess on the master."""
        try:
            self._tb_process = subprocess.Popen(
                ["tensorboard", "--logdir", self._logdir,
                 "--host", self._master_ip or "0.0.0.0"],
                stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
            )
        except FileNotFoundError:
            logger.warning(
                "tensorboard binary not found; event files still written "
                "to %s", self._logdir,
            )

    def keep_running(self) -> bool:
        return self._tb_process is not None and (
            self._tb_process.poll() is None
        )

    def close(self):
        self._writer.close()
        if self._tb_process is not None:
            self._tb_process.terminate()
