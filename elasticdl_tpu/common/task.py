"""Task: one shard range of one file, the unit of dynamic data sharding.

Mirror of the reference's Task proto message (elasticdl.proto Task:
shard_name/start/end/type/model_version) as a plain dataclass — the gRPC
layer converts to/from proto at the boundary.
"""

from dataclasses import dataclass, field


@dataclass
class Task:
    task_id: int = -1
    shard_name: str = ""
    start: int = 0
    end: int = 0
    type: str = "training"
    model_version: int = -1
    extended_config: dict = field(default_factory=dict)

    @property
    def num_records(self) -> int:
        return self.end - self.start

    def to_dict(self) -> dict:
        return {
            "task_id": self.task_id,
            "shard_name": self.shard_name,
            "start": self.start,
            "end": self.end,
            "type": self.type,
            "model_version": self.model_version,
            "extended_config": self.extended_config,
        }

    @classmethod
    def from_dict(cls, d: dict) -> "Task":
        return cls(**d)
