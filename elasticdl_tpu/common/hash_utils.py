"""Stable partitioning hashes.

Counterpart of the reference's ``elasticdl/python/common/hash_utils.py`` and
``elasticdl/pkg/ps/checkpoint.go:17-34``: dense variables partition by a
sha256 hash of their name, embedding rows by ``id % n``. The same functions
are used for checkpoint sharding, so a checkpoint written with N shards can be
restored onto M shards deterministically.
"""

import hashlib


def string_to_id(name: str, num_shards: int) -> int:
    """Stable shard index for a named dense variable."""
    if num_shards <= 0:
        raise ValueError("num_shards must be positive")
    digest = hashlib.sha256(name.encode("utf-8")).digest()
    return int.from_bytes(digest[:8], "big") % num_shards


def int_to_id(embedding_id: int, num_shards: int) -> int:
    """Stable shard index for an embedding row id."""
    if num_shards <= 0:
        raise ValueError("num_shards must be positive")
    return int(embedding_id) % num_shards
