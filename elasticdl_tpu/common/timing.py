"""Per-phase wall-clock accumulators (reference common/timing_utils.py:3-44).

Phases mirror the reference's {task_process, batch_process, get_model,
report_gradient}; this framework adds {compile, host_to_device} because those
are the TPU-specific costs worth watching.

Beyond the reference: per-phase min/max, and ``publish(registry)`` wires
the accumulators into the unified metrics registry
(elasticdl_tpu/observability/) — every phase duration then also lands in
the ``edl_tpu_worker_phase_seconds{phase=...}`` histogram, so phase costs
reach the master's ``/metrics`` instead of living in debug logs only.
"""

import contextlib
import time
from collections import defaultdict


class Timing:
    def __init__(self, enabled: bool = False, logger=None):
        self.enabled = enabled
        self._logger = logger
        self._phase_hist = None
        self.reset()

    def reset(self):
        self._totals = defaultdict(float)
        self._counts = defaultdict(int)
        self._mins = {}
        self._maxs = {}
        self._starts = {}

    def publish(self, registry) -> "Timing":
        """Land phase durations in ``registry`` as histograms
        (``edl_tpu_worker_phase_seconds{phase=...}``) from now on.
        Publishing enables timing — asking for metrics means asking for
        the data; the per-phase cost is two monotonic reads."""
        self._phase_hist = registry.histogram(
            "worker_phase_seconds",
            "Wall-clock duration of worker host phases",
            ["phase"],
        )
        self.enabled = True
        return self

    def start_record_time(self, phase: str):
        if self.enabled:
            self._starts[phase] = time.monotonic()

    def end_record_time(self, phase: str):
        if self.enabled and phase in self._starts:
            elapsed = time.monotonic() - self._starts.pop(phase)
            self._totals[phase] += elapsed
            self._counts[phase] += 1
            if phase not in self._mins or elapsed < self._mins[phase]:
                self._mins[phase] = elapsed
            if phase not in self._maxs or elapsed > self._maxs[phase]:
                self._maxs[phase] = elapsed
            if self._phase_hist is not None:
                self._phase_hist.labels(phase).observe(elapsed)

    @contextlib.contextmanager
    def record(self, phase: str):
        self.start_record_time(phase)
        try:
            yield
        finally:
            self.end_record_time(phase)

    def summary(self) -> dict:
        return {
            phase: {
                "total_secs": total,
                "count": self._counts[phase],
                "min_secs": self._mins[phase],
                "max_secs": self._maxs[phase],
            }
            for phase, total in sorted(self._totals.items())
        }

    def report_timing(self, reset: bool = False):
        if self.enabled and self._logger is not None:
            for phase, stats in self.summary().items():
                self._logger.debug(
                    "Phase %s: %.3fs over %d calls (min %.3fs, max %.3fs)",
                    phase, stats["total_secs"], stats["count"],
                    stats["min_secs"], stats["max_secs"],
                )
        if reset:
            self.reset()
