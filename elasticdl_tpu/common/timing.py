"""Per-phase wall-clock accumulators (reference common/timing_utils.py:3-44).

Phases mirror the reference's {task_process, batch_process, get_model,
report_gradient}; this framework adds {compile, host_to_device} because those
are the TPU-specific costs worth watching.
"""

import contextlib
import time
from collections import defaultdict


class Timing:
    def __init__(self, enabled: bool = False, logger=None):
        self.enabled = enabled
        self._logger = logger
        self.reset()

    def reset(self):
        self._totals = defaultdict(float)
        self._counts = defaultdict(int)
        self._starts = {}

    def start_record_time(self, phase: str):
        if self.enabled:
            self._starts[phase] = time.monotonic()

    def end_record_time(self, phase: str):
        if self.enabled and phase in self._starts:
            self._totals[phase] += time.monotonic() - self._starts.pop(phase)
            self._counts[phase] += 1

    @contextlib.contextmanager
    def record(self, phase: str):
        self.start_record_time(phase)
        try:
            yield
        finally:
            self.end_record_time(phase)

    def summary(self) -> dict:
        return {
            phase: {"total_secs": total, "count": self._counts[phase]}
            for phase, total in sorted(self._totals.items())
        }

    def report_timing(self, reset: bool = False):
        if self.enabled and self._logger is not None:
            for phase, stats in self.summary().items():
                self._logger.debug(
                    "Phase %s: %.3fs over %d calls",
                    phase, stats["total_secs"], stats["count"],
                )
        if reset:
            self.reset()
