"""Array / pytree serialization and sparse-gradient merging.

Counterpart of the reference's ``elasticdl/python/common/tensor_utils.py``
(ndarray⇄TensorProto, IndexedSlices merge/dedup) — but the wire format is
msgpack with raw buffers instead of TF ``TensorProto``: this framework only
ships tensors over the network for checkpoints and eval outputs, never on the
training hot path (gradients ride XLA collectives on the mesh).
"""

from dataclasses import dataclass
from typing import Any, Dict

import msgpack
import numpy as np

from elasticdl_tpu.common import dtypes


@dataclass
class IndexedSlices:
    """A sparse update: ``values[i]`` applies to row ``ids[i]`` of a table.

    Mirror of the reference's IndexedSlices (tensor_utils.py, tensor.go:222)
    as a host-side container; on-device sparse grads stay as (ids, values)
    JAX arrays. A dataclass (not NamedTuple) so msgpack routes it through the
    custom encoder instead of flattening it to a list.
    """

    values: np.ndarray  # (n, dim)
    ids: np.ndarray  # (n,)


def serialize_ndarray(arr: np.ndarray) -> dict:
    # np.ascontiguousarray would promote 0-d arrays to 1-d; asarray with
    # order="C" keeps scalar shape () intact.
    arr = np.asarray(arr, order="C")
    return {
        "dtype": dtypes.dtype_name(arr.dtype),
        "shape": list(arr.shape),
        "data": arr.tobytes(),
    }


def deserialize_ndarray(obj: dict) -> np.ndarray:
    arr = np.frombuffer(obj["data"], dtype=dtypes.np_dtype(obj["dtype"]))
    return arr.reshape(obj["shape"]).copy()


def _encode(obj):
    if isinstance(obj, np.ndarray):
        return {"__nd__": serialize_ndarray(obj)}
    if isinstance(obj, (np.integer,)):
        return int(obj)
    if isinstance(obj, (np.floating,)):
        return float(obj)
    if isinstance(obj, IndexedSlices):
        return {
            "__is__": {
                "values": serialize_ndarray(obj.values),
                "ids": serialize_ndarray(obj.ids),
            }
        }
    raise TypeError(f"Cannot serialize {type(obj)}")


def _decode(obj):
    if "__nd__" in obj:
        return deserialize_ndarray(obj["__nd__"])
    if "__is__" in obj:
        return IndexedSlices(
            values=deserialize_ndarray(obj["__is__"]["values"]),
            ids=deserialize_ndarray(obj["__is__"]["ids"]),
        )
    return obj


def dumps(tree: Any) -> bytes:
    """Serialize a pytree of ndarrays/scalars/strings to bytes."""
    return msgpack.packb(tree, default=_encode, use_bin_type=True)


def loads(data: bytes) -> Any:
    return msgpack.unpackb(data, object_hook=_decode, raw=False, strict_map_key=False)


def merge_indexed_slices(*slices: IndexedSlices) -> IndexedSlices:
    """Concatenate sparse updates (reference tensor.go:222 MergeIndexedSlices)."""
    values = np.concatenate([s.values for s in slices], axis=0)
    ids = np.concatenate([s.ids for s in slices], axis=0)
    return IndexedSlices(values=values, ids=ids)


def deduplicate_indexed_slices(values: np.ndarray, ids: np.ndarray):
    """Sum values belonging to duplicated ids (reference tensor_utils.py).

    Returns (summed_values, unique_ids) where ``summed_values[i]`` is the sum
    of all rows whose id == ``unique_ids[i]``.
    """
    unique_ids, inverse = np.unique(ids, return_inverse=True)
    summed = np.zeros((unique_ids.shape[0],) + values.shape[1:], values.dtype)
    np.add.at(summed, inverse, values)
    return summed, unique_ids


def flatten_named(tree: Dict[str, Any], prefix: str = "") -> Dict[str, np.ndarray]:
    """Flatten a nested dict pytree to {'a/b/c': leaf} with '/'-joined names."""
    out = {}
    for key in sorted(tree):
        value = tree[key]
        name = f"{prefix}/{key}" if prefix else str(key)
        if isinstance(value, dict):
            out.update(flatten_named(value, name))
        else:
            out[name] = value
    return out


def unflatten_named(flat: Dict[str, Any]) -> Dict[str, Any]:
    """Inverse of :func:`flatten_named`."""
    tree: Dict[str, Any] = {}
    for name, leaf in flat.items():
        parts = name.split("/")
        node = tree
        for part in parts[:-1]:
            node = node.setdefault(part, {})
        node[parts[-1]] = leaf
    return tree
