"""Uniform stderr logging (reference elasticdl/python/common/log_utils.py)."""

import logging
import sys

_DEFAULT_FMT = (
    "[%(asctime)s] [%(levelname)s] "
    "[%(filename)s:%(lineno)d:%(funcName)s] %(message)s"
)

_loggers = {}


def get_logger(name: str, level: str = "INFO") -> logging.Logger:
    """Get/create the named logger. The level is applied on first creation
    only (loggers are shared per name process-wide)."""
    if name not in _loggers:
        logger = logging.getLogger(name)
        logger.setLevel(level.upper())
        if not logger.handlers:
            handler = logging.StreamHandler(sys.stderr)
            handler.setFormatter(logging.Formatter(_DEFAULT_FMT))
            logger.addHandler(handler)
        logger.propagate = False
        _loggers[name] = logger
    return _loggers[name]


default_logger = get_logger("elasticdl_tpu")
