"""Framework-wide constants.

Counterpart of the reference's ``elasticdl/python/common/constants.py`` — the
gRPC limits, pod type names and strategy names keep the same semantics so a
reference user finds the same knobs, but the values are TPU-deployment flavored.
"""


class GRPC:
    # Tiny control messages only (tasks, versions, metrics); tensors never ride
    # gRPC in this framework — they live sharded on the mesh. 256MB cap kept for
    # eval raw-output reporting parity (reference constants.py:3-5).
    MAX_SEND_MESSAGE_LENGTH = 256 * 1024 * 1024
    MAX_RECEIVE_MESSAGE_LENGTH = 256 * 1024 * 1024


class InstanceManagerStatus:
    PENDING = "Pending"
    RUNNING = "Running"
    FINISHED = "Finished"


class PodStatus:
    PENDING = "Pending"
    RUNNING = "Running"
    SUCCEEDED = "Succeeded"
    FAILED = "Failed"
    DELETED = "Deleted"


class PodType:
    MASTER = "master"
    WORKER = "worker"


class TaskType:
    """Task types dispatched by the master (reference elasticdl.proto:24-30)."""

    TRAINING = "training"
    EVALUATION = "evaluation"
    PREDICTION = "prediction"
    WAIT = "wait"
    TRAIN_END_CALLBACK = "train_end_callback"


class JobType:
    TRAINING_ONLY = "training_only"
    TRAINING_WITH_EVALUATION = "training_with_evaluation"
    EVALUATION_ONLY = "evaluation_only"
    PREDICTION_ONLY = "prediction_only"


class Mode:
    TRAINING = "training"
    EVALUATION = "evaluation"
    PREDICTION = "prediction"


class DistributionStrategy:
    LOCAL = "Local"
    # Mesh data-parallel with sharded optimizer state. Subsumes the reference's
    # ParameterServerStrategy: the ICI mesh *is* the parameter store.
    MESH = "MeshStrategy"
    # Kept as an alias for reference-API compatibility.
    PARAMETER_SERVER = "ParameterServerStrategy"
    ALLREDUCE = "AllreduceStrategy"


class ReaderType:
    CSV = "CSV"
    RECORD_FILE = "RecordFile"
    TEXT = "Text"
    TABLE = "Table"  # row-range table service (ODPS-equivalent)
    STREAM = "Stream"  # append-only record stream (data/stream.py)


class MetricsDictKey:
    MODEL_OUTPUT = "output"
    LABEL = "label"


class SaveModelConfig:
    SAVED_MODEL_PATH = "saved_model_path"


# Exit code k8s gives OOM-killed / preempted containers; the instance manager
# treats it as relaunchable (reference k8s_instance_manager.py:250-271).
EXIT_CODE_KILLED = 137

# Default ports for in-cluster services (reference k8s_client.py:19-22).
MASTER_SERVICE_PORT = 50001
WORKER_COORD_PORT = 50002

MAX_TASK_RETRIES = 3
MAX_MINIBATCH_RETRY_NUM = 64
MAX_ALLREDUCE_RETRY_NUM = 5

# Embedding tables larger than this are auto-sharded across the mesh
# (reference model_handler.py:85-89).
EMBEDDING_AUTO_SHARD_BYTES = 2 * 1024 * 1024

DEFAULT_TASK_TIMEOUT_SECS = 300.0
