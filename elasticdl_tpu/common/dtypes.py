"""Dtype tables mapping framework dtype names to numpy/JAX dtypes.

Counterpart of the reference's ``elasticdl/python/common/dtypes.py`` and
``elasticdl/pkg/common/types.go`` — but keyed on canonical string names rather
than TF ``DataType`` enums, with bfloat16 first-class (it is the TPU MXU's
native matmul dtype).
"""

import jax.numpy as jnp
import numpy as np

# Canonical name -> (numpy dtype, byte size)
_DTYPES = {
    "bool": (np.dtype(np.bool_), 1),
    "int8": (np.dtype(np.int8), 1),
    "uint8": (np.dtype(np.uint8), 1),
    "int16": (np.dtype(np.int16), 2),
    "uint16": (np.dtype(np.uint16), 2),
    "int32": (np.dtype(np.int32), 4),
    "uint32": (np.dtype(np.uint32), 4),
    "int64": (np.dtype(np.int64), 8),
    "uint64": (np.dtype(np.uint64), 8),
    "float16": (np.dtype(np.float16), 2),
    "bfloat16": (np.dtype(jnp.bfloat16), 2),
    "float32": (np.dtype(np.float32), 4),
    "float64": (np.dtype(np.float64), 8),
}

_NP_TO_NAME = {v[0]: k for k, v in _DTYPES.items()}


def dtype_size(name: str) -> int:
    """Byte size of one element of the named dtype."""
    return _DTYPES[name][1]


def np_dtype(name: str) -> np.dtype:
    """Numpy dtype for a canonical name."""
    return _DTYPES[name][0]


def dtype_name(dtype) -> str:
    """Canonical name for a numpy/JAX dtype (raises KeyError if unsupported)."""
    return _NP_TO_NAME[np.dtype(dtype)]


def is_floating(name: str) -> bool:
    return name in ("float16", "bfloat16", "float32", "float64")


def is_allowed_param_dtype(dtype) -> bool:
    """Trainable parameters must be floating point (reference dtypes.py)."""
    try:
        return is_floating(dtype_name(dtype))
    except KeyError:
        return False
