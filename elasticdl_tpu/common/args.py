"""The flag system: argparse groups per role + arg re-serialization.

Counterpart of the reference's ``elasticdl/python/common/args.py`` (721 LoC,
~70 flags). Same structure: shared arg groups composed into per-role parsers
(client train/evaluate/predict/clean, master, worker), plus
``build_arguments_from_parsed_result`` so the master can re-serialize its own
parsed args into the CLI of the pods it spawns, and ``parse_envs`` for k=v
env plumbing (reference args.py:61-87).

TPU-specific flags replace the PS flags: ``--num_workers`` describes TPU-VM
worker pods, ``--mesh_shape``/``--dp_axis`` describe the device mesh, and the
sync-SGD knobs (``grads_to_wait``, staleness) map onto gradient-accumulation +
LR modulation in the mesh step.
"""

import argparse
from itertools import chain


def pos_int(value):
    res = int(value)
    if res <= 0:
        raise ValueError(f"Positive integer required, got {value}")
    return res


def non_neg_int(value):
    res = int(value)
    if res < 0:
        raise ValueError(f"Non-negative integer required, got {value}")
    return res


def pos_float(value):
    res = float(value)
    if res <= 0:
        raise ValueError(f"Positive float required, got {value}")
    return res


def parse_envs(arg):
    """Parse ``key1=val1,key2=val2`` into a dict (reference args.py:61-87)."""
    envs = {}
    if not arg:
        return envs
    for kv in arg.split(","):
        kv = kv.strip()
        if not kv:
            continue
        if "=" not in kv:
            raise ValueError(f"Malformed env entry {kv!r}; expected k=v")
        key, _, value = kv.partition("=")
        envs[key.strip()] = value.strip()
    return envs


def str2bool(value):
    if isinstance(value, bool):
        return value
    if value.lower() in ("yes", "true", "t", "y", "1"):
        return True
    if value.lower() in ("no", "false", "f", "n", "0"):
        return False
    raise argparse.ArgumentTypeError(f"Boolean value expected, got {value!r}")


def add_bool_param(parser, name, default, help_msg):
    parser.add_argument(
        name, type=str2bool, nargs="?", const=True, default=default, help=help_msg
    )


def add_common_params(parser):
    """Flags shared by every role (reference args.py add_common_params)."""
    parser.add_argument(
        "--model_zoo", help="Directory containing user-defined model modules",
        required=True,
    )
    parser.add_argument(
        "--model_def",
        help="Model module path, e.g. mnist.custom_model",
        required=True,
    )
    parser.add_argument("--dataset_fn", default="dataset_fn")
    parser.add_argument("--loss", default="loss")
    parser.add_argument("--optimizer", default="optimizer")
    parser.add_argument("--eval_metrics_fn", default="eval_metrics_fn")
    parser.add_argument("--custom_data_reader", default="custom_data_reader")
    parser.add_argument(
        "--prediction_outputs_processor", default="PredictionOutputsProcessor"
    )
    parser.add_argument("--callbacks", default="callbacks")
    parser.add_argument(
        "--distribution_strategy",
        default="Local",
        choices=["Local", "MeshStrategy", "ParameterServerStrategy",
                 "AllreduceStrategy"],
    )
    parser.add_argument("--job_name", default="elasticdl-tpu-job")
    parser.add_argument("--envs", type=str, default="",
                        help="Runtime environment variables, k1=v1,k2=v2")
    parser.add_argument("--data_reader_params", type=str, default="")
    parser.add_argument("--log_level", default="INFO",
                        choices=["DEBUG", "INFO", "WARNING", "ERROR"])
    parser.add_argument("--image_name", default="",
                        help="Container image for spawned pods")
    parser.add_argument("--namespace", default="default")
    parser.add_argument("--num_workers", type=pos_int, default=1)
    parser.add_argument("--checkpoint_shards", type=pos_int, default=1,
                        help="Shard files per checkpoint version "
                             "(reference: one file per PS pod)")
    parser.add_argument("--worker_resource_request",
                        default="cpu=1,memory=4096Mi")
    parser.add_argument("--worker_resource_limit", default="")
    parser.add_argument("--master_resource_request",
                        default="cpu=0.1,memory=1024Mi")
    parser.add_argument("--master_resource_limit", default="")
    parser.add_argument("--volume", default="")
    parser.add_argument("--restart_policy", default="Never")
    parser.add_argument("--master_addr", default="localhost:50001")
    parser.add_argument("--docker_image_repository", default="")
    add_bool_param(parser, "--force_use_kube_config_file", False,
                   "Use kube config file instead of in-cluster config")
    parser.add_argument("--cluster_spec", default="")
    # Mesh flags (TPU-native replacement for the PS flags).
    parser.add_argument(
        "--mesh_shape", default="",
        help="Device mesh shape, e.g. '8' (dp) or '2,4' (dp,mp); empty = all "
             "devices on one dp axis",
    )
    parser.add_argument(
        "--mesh_axes", default="dp",
        help="Comma-separated mesh axis names matching --mesh_shape",
    )
    add_bool_param(parser, "--use_bf16", True,
                   "Run matmuls in bfloat16 on the MXU")
    add_bool_param(parser, "--wait", False,
                   "After submitting to k8s, poll the job to completion "
                   "(exit 0 on master Succeeded) — reference "
                   "k8s_job_monitor semantics")
    add_bool_param(parser, "--wait_unknown_ok", False,
                   "With --wait: treat a master pod that vanishes while "
                   "Running as completed (clusters that GC finished pods "
                   "between polls); default treats it as not-success")


def add_train_params(parser):
    parser.add_argument("--tensorboard_log_dir", default="")
    parser.add_argument("--num_epochs", type=pos_int, default=1)
    parser.add_argument("--grads_to_wait", type=pos_int, default=1,
                        help="Gradient accumulation count before a sync apply")
    parser.add_argument("--training_data", default="")
    parser.add_argument("--validation_data", default="")
    parser.add_argument("--evaluation_steps", type=non_neg_int, default=0)
    parser.add_argument("--evaluation_start_delay_secs", type=pos_int,
                        default=100)
    parser.add_argument("--evaluation_throttle_secs", type=non_neg_int,
                        default=0)
    parser.add_argument("--checkpoint_steps", type=non_neg_int, default=0)
    parser.add_argument("--checkpoint_dir", default="")
    parser.add_argument("--keep_checkpoint_max", type=non_neg_int, default=3)
    parser.add_argument("--checkpoint_delta_chain", type=non_neg_int,
                        default=0,
                        help="Max incremental delta checkpoints riding "
                             "one full base before a save compacts into "
                             "a fresh base (host-tier embedding rows "
                             "only; dense state always rides in full). "
                             "0 (default) = full snapshots only. "
                             "docs/fault_tolerance.md")
    parser.add_argument("--checkpoint_dir_for_init", default="")
    parser.add_argument("--output", default="",
                        help="Export directory for the trained model")
    parser.add_argument("--minibatch_size", type=pos_int, required=True)
    parser.add_argument("--num_minibatches_per_task", type=pos_int, default=2)
    add_bool_param(parser, "--use_async", False,
                   "Async apply (staleness-modulated LR) instead of sync")
    parser.add_argument("--lr_staleness_modulation", type=str2bool,
                        nargs="?", const=True, default=False)
    parser.add_argument("--sync_version_tolerance", type=non_neg_int, default=0)
    parser.add_argument("--get_model_steps", type=pos_int, default=1,
                        help=">1 enables SSP-style local updates between syncs")
    parser.add_argument("--random_seed", type=non_neg_int, default=0)
    parser.add_argument("--max_steps", type=non_neg_int, default=0)
    parser.add_argument("--num_jax_processes", type=pos_int, default=1,
                        help=">1 wires jax.distributed across worker "
                             "processes (multi-host mesh over DCN)")
    parser.add_argument("--coordinator_addr", default="",
                        help="jax.distributed coordinator host:port "
                             "(required when num_jax_processes > 1)")
    parser.add_argument("--jax_process_id", type=int, default=-1,
                        help="Stable process id for jax.distributed; "
                             "-1 = use worker_id. Elastic relaunches "
                             "must reuse the dead worker's id")
    parser.add_argument("--prefetch_depth", type=non_neg_int, default=2,
                        help="Background batch-decode queue depth "
                             "(0 disables prefetching)")
    parser.add_argument("--host_prefetch_depth", type=pos_int, default=2,
                        help="Host-tier row pull-ahead depth: how many "
                             "upcoming batches the sparse pipeline "
                             "prepares (dedup + row pull + pad) while "
                             "the current batch steps. Widens the "
                             "async-apply staleness window to "
                             "depth + 3 batches (docs/sparse_path.md); "
                             "must be >= 1")
    parser.add_argument("--row_service_addr", default="",
                        help="Address(es) of the shared host-tier row "
                             "service (embedding/row_service.py) — "
                             "required for host-tier models with "
                             "num_workers > 1. A comma list means N "
                             "shards: rows scatter client-side by "
                             "id %% N (the reference's N parameter "
                             "servers, worker.py:404-414)")
    parser.add_argument("--num_row_service_shards", type=pos_int,
                        default=1,
                        help="Row-service shard pods (reference "
                             "--num_ps_pods): rows live by id %% N, one "
                             "stable Service + pod per shard, each with "
                             "its own checkpoint subdir (max 16)")
    parser.add_argument("--row_service_resource_request",
                        default="cpu=1,memory=4096Mi",
                        help="Resources for the row-service pod (the "
                             "reference's --ps_resource_request role); "
                             "CPU-only, independent of worker sizing")
    parser.add_argument("--row_service_resource_limit", default="")
    parser.add_argument("--row_service_checkpoint_steps", type=non_neg_int,
                        default=0,
                        help="Checkpoint interval for the row service, in "
                             "gradient PUSHES (its version unit). 0 = "
                             "derive from --checkpoint_steps scaled by "
                             "num_workers (each worker step pushes once "
                             "per table-holding step), so the service "
                             "checkpoints at roughly the cadence the "
                             "user asked for in model versions")
    parser.add_argument("--row_service_push_log",
                        choices=["durable", "applied", "off"],
                        default="durable",
                        help="Write-ahead push log mode for launched "
                             "row-service pods (with --checkpoint_dir; "
                             "docs/fault_tolerance.md 'Zero-RPO row "
                             "plane'): durable (default, acked-push "
                             "RPO=0), applied (RPO = one group "
                             "window; for media with slow fsync), "
                             "off (pre-WAL checkpoint-bounded loss)")
    parser.add_argument("--row_service_push_log_group_ms", type=float,
                        default=2.0,
                        help="Group-commit window for the row-service "
                             "push log (one fsync covers every push "
                             "landing within it)")
    parser.add_argument("--row_service_admission_limit", type=int,
                        default=0,
                        help="Priority admission control on launched "
                             "row-service pods: bound on concurrently "
                             "admitted handlers; beyond it requests "
                             "shed lowest-priority-first by principal "
                             "purpose (docs/fault_tolerance.md "
                             "'Graceful degradation'). 0 (default) = "
                             "off")
    parser.add_argument("--row_service_push_durable_wait_secs",
                        type=float, default=60.0,
                        help="Ceiling on the row-service durable-ack "
                             "fsync wait; a propagated request "
                             "deadline shrinks it per-push")
    parser.add_argument("--master_admission_limit", type=int,
                        default=0,
                        help="Priority admission control on the "
                             "master RPC servicer (same ladder as the "
                             "row plane). 0 (default) = off")
    add_bool_param(parser, "--fuse_task_steps", False,
                   "Scan a whole task's minibatches in one XLA program "
                   "(removes per-step host dispatch)")
    parser.add_argument("--compilation_cache_dir", default="",
                        help="Persistent XLA compilation cache; elastic "
                             "relaunches skip recompiling unchanged "
                             "programs (point at a shared volume)")
    parser.add_argument("--profile_dir", default="",
                        help="Write a jax.profiler trace (TensorBoard/"
                             "Perfetto) for a step window")
    parser.add_argument("--profile_start_step", type=non_neg_int,
                        default=5)
    parser.add_argument("--profile_steps", type=pos_int, default=5)
    # Continuous profiling plane (observability/profiler.py;
    # docs/observability.md "Continuous profiling & exemplars"): an
    # always-on sampling profiler whose flame-table windows ride the
    # metrics piggyback into the master's /profile endpoint.
    parser.add_argument("--profile_hz", type=float, default=0.0,
                        help="Always-on sampling-profiler rate (Hz) "
                             "for master and workers; flame-table "
                             "windows serve on the master's /profile "
                             "endpoint. ~67 is the intended default "
                             "rate; 0 (default) = off")
    parser.add_argument("--profile_window_secs", type=pos_float,
                        default=10.0,
                        help="Sampling-profiler window length: stacks "
                             "fold per window, windows ride the "
                             "metrics piggyback to the master")
    parser.add_argument("--task_timeout_secs", type=pos_float, default=300.0)
    parser.add_argument("--journal_dir", default="",
                        help="Master write-ahead job-state journal "
                             "directory (docs/fault_tolerance.md): "
                             "dispatch/report events + periodic "
                             "snapshots, replayed on master restart so "
                             "task accounting survives the crash. "
                             "Point at a volume that outlives the "
                             "master pod; empty (default) disables")
    add_bool_param(parser, "--standby", False,
                   help_msg="Run this master as a HOT STANDBY "
                             "(docs/fault_tolerance.md 'Hot standby "
                             "& failover'): tail --journal_dir into "
                             "a continuously-replayed warm state and "
                             "heartbeat --primary_addr; on missed "
                             "heartbeats fence the old incarnation "
                             "and take over serving. Requires "
                             "--journal_dir on storage shared with "
                             "the primary")
    parser.add_argument("--primary_addr", default="",
                        help="Standby role: the primary master "
                             "address to heartbeat (defaults to "
                             "--master_addr)")
    parser.add_argument("--standby_heartbeat_secs", type=pos_float,
                        default=1.0,
                        help="Standby role: primary heartbeat cadence")
    parser.add_argument("--standby_miss_threshold", type=int,
                        default=3,
                        help="Standby role: consecutive missed "
                             "heartbeats before takeover")
    parser.add_argument("--master_reattach_grace", type=pos_float,
                        default=60.0,
                        help="How long a worker rides out master "
                             "unavailability before treating the job "
                             "as finished. Size it to measured master "
                             "recovery time (master_recovery_seconds "
                             "on /metrics) when running with "
                             "--journal_dir; the default matches the "
                             "old hard-coded ~60s budget")
    parser.add_argument("--metrics_port", type=int, default=-1,
                        help="Master Prometheus endpoint (/metrics + "
                             "/healthz): port to serve on; 0 picks an "
                             "ephemeral port, -1 (default) disables")
    parser.add_argument("--flight_recorder", type=int, default=0,
                        help="Install a distributed-tracing flight "
                             "recorder of this many spans in the "
                             "master (collected worker spans + its own "
                             "are served on /traces next to /metrics; "
                             "see docs/observability.md). 0 (default) "
                             "= tracing off")
    parser.add_argument("--metrics_report_secs", type=pos_float,
                        default=15.0,
                        help="How often each worker piggybacks a metrics "
                             "registry snapshot on master RPCs")
    # SLO engine (observability/timeseries.py + slo.py;
    # docs/observability.md): the master samples its telemetry into a
    # bounded time-series store each run tick, evaluates declarative
    # SLO rules (burn rate / threshold / absence) on it, and serves
    # /timeseries + /alerts next to /metrics.
    parser.add_argument("--timeseries_secs", type=float, default=5.0,
                        help="Master time-series sampling cadence "
                             "(seconds); 0 disables the store, the SLO "
                             "engine, and the /timeseries + /alerts "
                             "endpoints")
    parser.add_argument("--slo_rules", default="",
                        help="JSON SLO rule file (docs/observability.md "
                             "'SLOs & alerting' for the format); empty "
                             "= the built-in default rules")
    parser.add_argument("--incident_dir", default="",
                        help="Write a black-box incident bundle here "
                             "(flight-recorder trace, time-series "
                             "window, critical-path attribution, "
                             "journal tail) whenever an SLO rule "
                             "starts firing; empty (default) disables "
                             "capture")
    parser.add_argument("--metrics_ttl_secs", type=pos_float, default=None,
                        help="Master drops a worker's metrics after this "
                             "long without a report (elastic resize "
                             "aging). Snapshots only ride existing RPCs, "
                             "so a healthy worker can go silent for a "
                             "whole task (fused steps, stragglers) — "
                             "keep this above the longest task, not just "
                             "a few report intervals; default is 2x "
                             "task_timeout_secs")
    # Closed-loop elastic autoscaling (master/autoscaler.py;
    # docs/elasticity.md): the master watches queue depth, worker step
    # utilization, and p99 straggler attribution, and grows/shrinks the
    # worker fleet between the bounds.
    add_bool_param(parser, "--autoscale", False,
                   "Enable the master's closed-loop autoscaler "
                   "(k8s mode: scales worker pods between "
                   "--autoscale_min_workers/--autoscale_max_workers)")
    parser.add_argument("--autoscale_min_workers", type=pos_int,
                        default=1)
    parser.add_argument("--autoscale_max_workers", type=non_neg_int,
                        default=0,
                        help="0 = use --num_workers as the ceiling")
    parser.add_argument("--autoscale_cooldown_secs", type=pos_float,
                        default=60.0,
                        help="Quiet period after any scale decision")
    parser.add_argument("--autoscale_hysteresis_ticks", type=pos_int,
                        default=3,
                        help="Consecutive agreeing poll ticks required "
                             "before a decision fires")
    parser.add_argument("--autoscale_up_backlog_factor", type=pos_float,
                        default=2.0,
                        help="Scale up when todo depth exceeds this "
                             "many tasks per live worker (and workers "
                             "are saturated)")
    parser.add_argument("--autoscale_up_utilization", type=pos_float,
                        default=0.7,
                        help="Minimum mean worker_step_utilization for "
                             "scale-up (a starved fleet's backlog is an "
                             "input problem, not a capacity problem)")
    parser.add_argument("--autoscale_down_utilization", type=pos_float,
                        default=0.3,
                        help="Scale down when the queue is empty and "
                             "mean utilization sits below this")
    add_bool_param(parser, "--autoscale_from_timeseries", False,
                   "Feed the autoscaler the mean worker utilization "
                   "over --autoscale_trend_window_secs from the "
                   "time-series store instead of the instantaneous "
                   "snapshot (requires --timeseries_secs > 0)")
    parser.add_argument("--autoscale_trend_window_secs", type=pos_float,
                        default=120.0,
                        help="Trailing window for the time-series-"
                             "backed utilization signal")
    # Row-plane elasticity (master/row_reshard.py; docs/sparse_path.md
    # "Live resharding & hot-row replication"): the master runs the
    # shard-map authority over the --row_service_addr fleet — load-
    # imbalance range moves plus hot-row replica designation.
    add_bool_param(parser, "--row_reshard", False,
                   "Run the row-service shard-map controller in the "
                   "master tick (needs --row_service_addr; live range "
                   "rebalancing + hot-row read replicas)")
    parser.add_argument("--row_reshard_state", default="",
                        help="Shard-map authority state file (default: "
                             "<journal_dir>/shard_map.json; required "
                             "when no --journal_dir is set)")
    parser.add_argument("--row_reshard_cooldown_secs", type=pos_float,
                        default=30.0,
                        help="Quiet period between reshard actions "
                             "(range moves / replica updates)")
    parser.add_argument("--row_replica_top_k", type=pos_int, default=64,
                        help="Hottest ids per table eligible for read "
                             "replication")
    parser.add_argument("--row_replica_count", type=non_neg_int,
                        default=2,
                        help="Read replicas per hot id (capped at "
                             "fleet size - 1; 0 disables replication)")
    add_bool_param(parser, "--row_pod_autoscale", False,
                   "Close the split/merge pod loop (master/"
                   "autoscaler.py RowServicePodScaler): grow spawns a "
                   "row-service pod before splitting onto it, and a "
                   "merged-away pod drains once the shard-map "
                   "controller retires its slot (needs --row_reshard "
                   "and k8s)")
    # Multi-tenant gang scheduling (master/scheduler.py;
    # docs/scheduler.md): many jobs on one elastic fleet, with
    # journal-event-sourced job table, priority preemption, and
    # usage-plane fair share.
    add_bool_param(parser, "--sched", False,
                   "Run the multi-job gang scheduler in the master "
                   "(submit_job RPC + /sched endpoint; job table "
                   "event-sources onto --journal_dir and survives "
                   "failover)")
    # Streaming ingestion (master/stream_ingest.py + data/stream.py;
    # docs/online_learning.md): online/continual learning from an
    # append-only record stream instead of a finite shard table.
    parser.add_argument("--stream_dir", default="",
                        help="Directory of *.edlstream append-only "
                             "partitions (data/stream.py). Non-empty "
                             "switches the dispatcher to streaming "
                             "mode: unbounded offset-ranged tasks, "
                             "journaled watermarks, watermark-"
                             "triggered eval, /stream endpoint")
    parser.add_argument("--stream_max_todo", type=pos_int, default=64,
                        help="Backpressure bound: stop generating "
                             "stream tasks while the todo queue holds "
                             "this many (stream_ingest_backpressure_"
                             "seconds meters the stall)")
    parser.add_argument("--stream_eval_every_records", type=non_neg_int,
                        default=0,
                        help="Open an eval round each time this many "
                             "stream records commit past the watermark "
                             "(replaces epoch-end eval in streaming "
                             "mode; 0 disables)")
    parser.add_argument("--stream_poll_secs", type=pos_float,
                        default=0.5,
                        help="Stream tail poll + pump cadence")
    parser.add_argument("--usage_max_jobs", type=non_neg_int, default=0,
                        help="Distinct job labels the usage plane "
                             "admits before folding new tenants into "
                             "__other__ (observability/usage.py); 0 "
                             "(default) keeps the built-in cap of 32. "
                             "Raise on legitimately multi-job fleets "
                             "(--sched) so every tenant keeps its own "
                             "usage series")
    # Synthetic probing (observability/prober.py;
    # docs/observability.md "Synthetic probing"): black-box canary
    # probes against the reserved top-of-int64 id range, the repo's
    # first outside-in SLIs. Served at /probes; /healthz becomes the
    # aggregated probe verdict (200/503).
    add_bool_param(parser, "--probes", False,
                   "Run the synthetic canary prober inside the master "
                   "(dispatch/row/stream probes auto-wire from the "
                   "matching flags; serving needs "
                   "--probe_serving_addr)")
    parser.add_argument("--probe_interval_secs", type=pos_float,
                        default=15.0,
                        help="Cadence for each registered probe")
    parser.add_argument("--probe_serving_addr", default="",
                        help="host:port of a serving router; non-empty "
                             "registers the serving_freshness probe "
                             "(canary push -> prediction change)")
    parser.add_argument("--probe_serving_feature_key", default="",
                        help="Sparse feature key the serving_freshness "
                             "probe queries with a canary id (empty = "
                             "'ids')")


def add_evaluate_params(parser):
    parser.add_argument("--validation_data", default="", required=False)
    parser.add_argument("--checkpoint_dir_for_init", required=True)
    parser.add_argument("--minibatch_size", type=pos_int, required=True)
    parser.add_argument("--num_minibatches_per_task", type=pos_int, default=2)


def add_predict_params(parser):
    parser.add_argument("--prediction_data", required=True)
    parser.add_argument("--checkpoint_dir_for_init", required=True)
    parser.add_argument("--minibatch_size", type=pos_int, required=True)
    parser.add_argument("--num_minibatches_per_task", type=pos_int, default=2)


def add_clean_params(parser):
    add_bool_param(parser, "--force", False, "Force-delete job resources")
    parser.add_argument("--job_name", default="")
    parser.add_argument("--namespace", default="default")
    add_bool_param(parser, "--force_use_kube_config_file", False,
                   "Use kube config file instead of in-cluster config")


def add_worker_params(parser):
    parser.add_argument("--worker_id", type=non_neg_int, required=True)


def build_parser(role: str) -> argparse.ArgumentParser:
    parser = argparse.ArgumentParser(prog=f"elasticdl_tpu-{role}",
                                     allow_abbrev=False)
    if role == "clean":
        add_clean_params(parser)
        return parser
    add_common_params(parser)
    if role in ("train", "master"):
        add_train_params(parser)
    elif role == "evaluate":
        add_evaluate_params(parser)
    elif role == "predict":
        add_predict_params(parser)
    elif role == "worker":
        add_train_params(parser)
        add_worker_params(parser)
    else:
        raise ValueError(f"Unknown role {role}")
    return parser


def parse_master_args(args=None):
    return build_parser("master").parse_args(args=args)


def parse_worker_args(args=None):
    return build_parser("worker").parse_args(args=args)


def build_arguments_from_parsed_result(args, filter_args=None):
    """Reserialize parsed args back into a CLI list for spawning child pods
    (reference args.py build_arguments_from_parsed_result).

    None-valued optionals are SKIPPED, not stringified: an unset
    ``--metrics_ttl_secs`` (default None = "derive from
    task_timeout_secs") would otherwise re-serialize as the literal
    string "None", which the worker parser's ``pos_float`` rejects —
    omitting the flag reproduces the default-deriving behavior in the
    child process."""
    items = vars(args).items()
    if filter_args:
        items = filter(lambda kv: kv[0] not in filter_args, items)

    def _to_pair(key, value):
        if value is None:
            return []
        if isinstance(value, bool):
            return [f"--{key}", "true" if value else "false"]
        return [f"--{key}", str(value)]

    return list(chain.from_iterable(_to_pair(k, v) for k, v in items))


def wrap_python_args_with_string(args):
    """Quote arg values so they survive a shell command line."""
    out = []
    for item in args:
        if not item.startswith("--"):
            out.append(f"'{item}'")
        else:
            out.append(item)
    return out
