"""Feature-column API — declarative feature specs compiled to jnp ops.

Surface twin of the reference's two feature-column modules:

- ``elasticdl/python/elasticdl/feature_column/feature_column.py:12-79``
  (``embedding_column`` whose lookup rides the parameter server instead
  of a local dense variable), and
- ``elasticdl_preprocessing/feature_column/feature_column.py:9-100``
  (``concatenated_categorical_column`` — offset-shifted union of
  categorical columns sharing one embedding table).

The reference builds on TF's FeatureColumn class lattice (DenseColumn /
CategoricalColumn / _DenseColumn...) where each column owns TF graph ops.
The TPU-native design keeps the *constructor surface* (the part user code
touches) but compiles columns in two planes, matching this package's
split:

- **host plane**: ``apply_host_transforms(columns, record)`` runs the
  string-capable numpy work (vocabulary lookup, string hashing,
  to_number) inside ``dataset_fn`` on the worker host — strings never
  reach the device;
- **device plane**: ``DenseFeatures(columns)`` is a flax module of pure
  jnp ops (bucketize, hash-mix, one-hot, embedding gather) jit-safe
  under ``pjit``; embedding tables are ordinary flax params named
  ``embedding`` so the 2MB auto-partition pass (embedding/partition.py)
  shards them over the mesh exactly like hand-built Embedding layers —
  the capability the reference's EmbeddingColumn gets from its PS
  delegate.

Column objects are frozen dataclasses: hashable, reusable across models,
and trivially serializable into model-spec modules.
"""

from dataclasses import dataclass
from typing import Callable, Optional, Sequence, Tuple

import numpy as np

import jax.numpy as jnp
from flax import linen as nn

from elasticdl_tpu.embedding.combiner import RaggedIds
from elasticdl_tpu.embedding.layer import Embedding
from elasticdl_tpu.preprocessing.layers import Discretization, Hashing
from elasticdl_tpu.preprocessing.transforms import (
    CategoryHash,
    CategoryLookup,
    to_number,
)


class FeatureColumn:
    """Marker base. Columns expose:

    - ``key``: the feature-dict entry consumed,
    - ``host(values)``: optional numpy transform (strings allowed),
      identity by default,
    - categorical columns add ``num_buckets``; dense columns add
      ``output_dim``.
    """

    def host(self, values):
        return values


class CategoricalColumn(FeatureColumn):
    num_buckets: int


# ---------------------------------------------------------------- numeric


@dataclass(frozen=True)
class NumericColumn(FeatureColumn):
    key: str
    shape: Tuple[int, ...] = (1,)
    normalizer_fn: Optional[Callable] = None
    default_value: float = 0.0

    @property
    def output_dim(self) -> int:
        return int(np.prod(self.shape))

    def host(self, values):
        # String-tolerant numeric parse (csv readers hand over bytes).
        arr = np.asarray(values)
        if arr.dtype.kind in ("U", "S", "O"):
            arr = to_number(arr, self.default_value)
        return arr.astype(np.float32)

    def device(self, x):
        x = jnp.asarray(x, jnp.float32)
        if self.normalizer_fn is not None:
            x = self.normalizer_fn(x)
        return x.reshape(x.shape[0], self.output_dim)


def numeric_column(key, shape=(1,), normalizer_fn=None, default_value=0.0):
    """A dense float feature (tf.feature_column.numeric_column shape)."""
    if isinstance(shape, int):
        shape = (shape,)
    return NumericColumn(key, tuple(shape), normalizer_fn,
                         float(default_value))


# ----------------------------------------------------------- categorical


@dataclass(frozen=True)
class IdentityCategoricalColumn(CategoricalColumn):
    key: str
    num_buckets: int
    default_value: Optional[int] = None
    validate: bool = False

    def host(self, values):
        arr = np.asarray(values)
        if self.validate and self.default_value is None:
            bad = (arr < 0) | (arr >= self.num_buckets)
            if bad.any():
                sample = np.asarray(arr[bad]).ravel()[:5].tolist()
                raise ValueError(
                    f"identity column {self.key!r}: "
                    f"{int(bad.sum())} id(s) outside "
                    f"[0, {self.num_buckets}), e.g. {sample}"
                )
        return arr

    def device_ids(self, ids):
        ids = jnp.asarray(ids, jnp.int32)
        if self.default_value is not None:
            ids = jnp.where(
                (ids >= 0) & (ids < self.num_buckets),
                ids, jnp.int32(self.default_value),
            )
        return jnp.clip(ids, 0, self.num_buckets - 1)


def categorical_column_with_identity(
    key, num_buckets, default_value=None, validate=False
):
    """TF-surface deviation: with ``default_value=None`` the TF column
    raises on out-of-range ids, but inside jit there is no data-dependent
    raise — so the device plane CLIPS out-of-range ids to the boundary
    buckets [0, num_buckets-1]. Bad input data would then train the edge
    embeddings instead of failing; pass ``validate=True`` to get the TF
    behavior back as a host-side check in ``host()`` (runs in
    ``dataset_fn`` on the worker, before ids reach the device)."""
    if num_buckets <= 0:
        raise ValueError(f"num_buckets must be positive, got {num_buckets}")
    return IdentityCategoricalColumn(
        key, int(num_buckets), default_value, bool(validate)
    )


# Host-side string pre-hash range: strings map to a stable int32 in
# [0, 2^31) WITHOUT bucketing; the device mixer then buckets exactly
# once. (Pre-bucketing on the host and mixing again on device would
# double-hash — the bucket would no longer be the CategoryHash id,
# desyncing any consumer that reads host-transformed ids directly.)
_HASH_PRERANGE = 2**31 - 1


@dataclass(frozen=True)
class HashedCategoricalColumn(CategoricalColumn):
    key: str
    num_buckets: int

    def host(self, values):
        arr = np.asarray(values)
        if arr.dtype.kind in ("U", "S", "O"):
            # Strings hash to a stable wide int on the host (device has
            # no string ops); bucketing happens once, on device.
            return CategoryHash(_HASH_PRERANGE)(arr).astype(
                np.int32
            )
        return arr

    def device_ids(self, ids):
        ids = jnp.asarray(ids)
        if ids.dtype.kind == "f":
            ids = ids.astype(jnp.int32)
        return Hashing(self.num_buckets)(ids)


def categorical_column_with_hash_bucket(key, hash_bucket_size):
    if hash_bucket_size <= 0:
        raise ValueError("hash_bucket_size must be positive")
    return HashedCategoricalColumn(key, int(hash_bucket_size))


@dataclass(frozen=True)
class VocabularyCategoricalColumn(CategoricalColumn):
    key: str
    vocabulary: Tuple = ()
    num_oov_buckets: int = 0
    default_value: int = -1

    @property
    def num_buckets(self) -> int:  # type: ignore[override]
        if self.num_oov_buckets > 0:
            return len(self.vocabulary) + self.num_oov_buckets
        if 0 <= self.default_value < len(self.vocabulary):
            return len(self.vocabulary)
        # TF's default_value=-1 yields invalid ids; on device ids must
        # stay in-table, so a reserved OOV bucket takes that role.
        return len(self.vocabulary) + 1

    def host(self, values):
        lookup = CategoryLookup(
            list(self.vocabulary),
            num_oov_buckets=max(self.num_oov_buckets, 1),
        )
        ids = lookup(np.asarray(values)).astype(np.int32)
        if self.num_oov_buckets == 0 and (
            0 <= self.default_value < len(self.vocabulary)
        ):
            # TF surface: with no OOV buckets, unknowns map to
            # default_value instead of a reserved slot.
            ids = np.where(
                ids >= len(self.vocabulary),
                np.int32(self.default_value), ids,
            )
        return ids

    def device_ids(self, ids):
        return jnp.clip(
            jnp.asarray(ids, jnp.int32), 0, self.num_buckets - 1
        )


def categorical_column_with_vocabulary_list(
    key, vocabulary_list, num_oov_buckets=0, default_value=-1
):
    return VocabularyCategoricalColumn(
        key, tuple(vocabulary_list), int(num_oov_buckets),
        int(default_value),
    )


@dataclass(frozen=True)
class BucketizedColumn(CategoricalColumn):
    source_column: NumericColumn
    boundaries: Tuple[float, ...] = ()

    @property
    def key(self) -> str:
        return self.source_column.key

    @property
    def num_buckets(self) -> int:  # type: ignore[override]
        return len(self.boundaries) + 1

    def host(self, values):
        return self.source_column.host(values)

    def device_ids(self, x):
        return Discretization(list(self.boundaries))(
            jnp.asarray(x, jnp.float32)
        )


def bucketized_column(source_column, boundaries):
    if not isinstance(source_column, NumericColumn):
        raise ValueError("bucketized_column needs a numeric_column source")
    return BucketizedColumn(source_column, tuple(float(b)
                                                for b in boundaries))


@dataclass(frozen=True)
class ConcatenatedCategoricalColumn(CategoricalColumn):
    """Offset-shifted union: sub-column ids share ONE id space (and
    therefore one downstream embedding table) — the reference's
    ``concatenated_categorical_column``
    (elasticdl_preprocessing/feature_column/feature_column.py:9-100)."""

    columns: Tuple[CategoricalColumn, ...] = ()

    @property
    def key(self) -> str:
        return "_".join(c.key for c in self.columns)

    @property
    def num_buckets(self) -> int:  # type: ignore[override]
        return sum(c.num_buckets for c in self.columns)

    @property
    def offsets(self) -> Tuple[int, ...]:
        out, acc = [], 0
        for c in self.columns:
            out.append(acc)
            acc += c.num_buckets
        return tuple(out)

    def device_ids(self, feature_dict):
        parts = []
        for col, off in zip(self.columns, self.offsets):
            ids = col.device_ids(feature_dict[col.key])
            ids = ids.reshape(ids.shape[0], -1)
            parts.append(ids + jnp.int32(off))
        return jnp.concatenate(parts, axis=1)


def concatenated_categorical_column(categorical_columns):
    cols = tuple(categorical_columns)
    if not cols:
        raise ValueError("need at least one categorical column")
    for c in cols:
        if not isinstance(c, CategoricalColumn):
            raise ValueError(
                f"{c!r} is not a categorical column"
            )
        if isinstance(c, ConcatenatedCategoricalColumn):
            # device_ids indexes the feature dict by each member's key;
            # a nested concat has a synthetic key that matches nothing.
            # Flatten at the call site instead (offsets compose).
            raise ValueError(
                "nested concatenated_categorical_column is not "
                "supported — pass the flat list of member columns"
            )
    return ConcatenatedCategoricalColumn(cols)


# ---------------------------------------------------------------- dense-of


@dataclass(frozen=True)
class EmbeddingColumn(FeatureColumn):
    """Categorical ids -> combined embedding rows.

    The table is a flax param named ``embedding`` so the auto-partition
    pass shards it over the mesh (the reference's version instead wires
    an EmbeddingDelegate to the PS —
    elasticdl/python/elasticdl/feature_column/feature_column.py:80+)."""

    categorical_column: CategoricalColumn
    dimension: int
    combiner: str = "mean"
    initializer: Optional[Callable] = None
    trainable: bool = True  # kept for surface parity; flax trainability
    #                         is an optimizer-mask concern, not a layer one

    @property
    def key(self) -> str:
        return self.categorical_column.key

    @property
    def output_dim(self) -> int:
        return self.dimension

    def host(self, values):
        return self.categorical_column.host(values)


def embedding_column(categorical_column, dimension, combiner="mean",
                     initializer=None, trainable=True):
    if dimension is None or dimension < 1:
        raise ValueError(f"Invalid dimension {dimension}.")
    if initializer is not None and not callable(initializer):
        raise ValueError("initializer must be callable if specified.")
    if combiner not in ("mean", "sum", "sqrtn"):
        raise ValueError(f"unsupported combiner {combiner!r}")
    if not isinstance(categorical_column, CategoricalColumn):
        raise ValueError("embedding_column needs a categorical column")
    return EmbeddingColumn(
        categorical_column, int(dimension), combiner, initializer,
        trainable,
    )


@dataclass(frozen=True)
class IndicatorColumn(FeatureColumn):
    """Categorical ids -> multi-hot counts (tf indicator_column)."""

    categorical_column: CategoricalColumn

    @property
    def key(self) -> str:
        return self.categorical_column.key

    @property
    def output_dim(self) -> int:
        return self.categorical_column.num_buckets

    def host(self, values):
        return self.categorical_column.host(values)


def indicator_column(categorical_column):
    if not isinstance(categorical_column, CategoricalColumn):
        raise ValueError("indicator_column needs a categorical column")
    return IndicatorColumn(categorical_column)


# ------------------------------------------------------------ composition


def _leaf_columns(col):
    """Walk wrapper columns (embedding/indicator over concatenated) down
    to the columns that actually consume a record entry."""
    if isinstance(col, (EmbeddingColumn, IndicatorColumn)):
        yield from _leaf_columns(col.categorical_column)
    elif isinstance(col, ConcatenatedCategoricalColumn):
        for sub in col.columns:
            yield from _leaf_columns(sub)
    else:
        yield col


def apply_host_transforms(columns, record):
    """Run every column's host-plane transform over a feature dict of
    numpy arrays (the ``dataset_fn`` hook). Wrapper columns recurse to
    their leaves, so an ``embedding_column`` over a concatenated union
    of string-keyed columns host-transforms each member. Returns a new
    dict keyed by leaf-column key; untouched record entries pass
    through."""
    out = dict(record)
    for col in columns:
        for leaf in _leaf_columns(col):
            out[leaf.key] = leaf.host(record[leaf.key])
    return out


def _column_ids(col, feature_dict):
    if isinstance(col, ConcatenatedCategoricalColumn):
        return col.device_ids(feature_dict)
    ids = col.device_ids(feature_dict[col.key])
    return ids.reshape(ids.shape[0], -1)


class DenseFeatures(nn.Module):
    """Compile a list of columns into one dense (batch, total_dim)
    tensor — the Keras ``DenseFeatures`` role, as a flax module.

    Accepts a dict of arrays (host transforms already applied). Column
    order fixes the concat order; embedding tables are per-column flax
    params named ``{key}_embedding/embedding``.
    """

    columns: Sequence[FeatureColumn]
    param_dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, features):
        parts = []
        for col in self.columns:
            if isinstance(col, NumericColumn):
                parts.append(col.device(features[col.key]))
            elif isinstance(col, EmbeddingColumn):
                ids = _column_ids(col.categorical_column, features)
                # The framework Embedding layer: same lookup path as
                # hand-built models (Pallas auto-dispatch included) and
                # a param path ending in "embedding", so the 2MB
                # auto-partition pass shards the table over the mesh.
                layer = Embedding(
                    input_dim=col.categorical_column.num_buckets,
                    output_dim=col.dimension,
                    combiner=col.combiner,
                    param_dtype=self.param_dtype,
                    initializer=col.initializer,
                    name=f"{col.key}_embedding",
                )
                weights = jnp.ones(ids.shape, jnp.float32)
                parts.append(layer(RaggedIds(ids, weights)))
            elif isinstance(col, IndicatorColumn):
                ids = _column_ids(col.categorical_column, features)
                onehot = jnp.sum(
                    (ids[..., None]
                     == jnp.arange(col.output_dim)[None, None, :])
                    .astype(self.param_dtype),
                    axis=1,
                )
                parts.append(onehot)
            elif isinstance(col, CategoricalColumn):
                raise ValueError(
                    f"bare categorical column {col.key!r}: wrap it in "
                    "embedding_column(...) or indicator_column(...) "
                    "before DenseFeatures"
                )
            else:
                raise ValueError(f"unsupported column {col!r}")
        return jnp.concatenate(parts, axis=1)
