"""Concatenated categorical feature groups.

Twin of the reference's ``concatenated_categorical_column``
(``elasticdl_preprocessing/feature_column/feature_column.py:9``): many
categorical columns share ONE embedding table by offsetting each column's id
range into a disjoint slice of a combined id space. On TPU this is the
difference between N tiny gathers and one large batched gather that keeps the
embedding table a single row-shardable array.
"""

from dataclasses import dataclass
from typing import Callable, Dict, List, Tuple

import numpy as np


@dataclass
class FeatureGroup:
    """An ordered set of (name, transform) categorical columns fused into one
    id space. Each transform maps raw record values → ids in
    [0, transform.num_buckets)."""

    columns: List[Tuple[str, Callable]]

    def __post_init__(self):
        self.offsets = {}
        offset = 0
        for name, transform in self.columns:
            self.offsets[name] = offset
            offset += int(transform.num_buckets)
        self.total_buckets = offset

    def __call__(self, record_values: Dict[str, np.ndarray]) -> np.ndarray:
        """record_values: feature name → (B,) raw values.
        Returns (B, num_columns) int64 ids in [0, total_buckets)."""
        cols = []
        for name, transform in self.columns:
            ids = np.asarray(transform(record_values[name]), np.int64)
            cols.append(ids.reshape(-1, 1) + self.offsets[name])
        return np.concatenate(cols, axis=1)


def concat_feature_ids(groups: List[np.ndarray],
                       group_sizes: List[int]) -> np.ndarray:
    """Concatenate already-grouped id matrices into one id space (the
    multi-group form used by the census wide&deep model's MODEL_INPUTS)."""
    offsets = np.concatenate([[0], np.cumsum(group_sizes)[:-1]])
    return np.concatenate(
        [g + offsets[i] for i, g in enumerate(groups)], axis=1
    )
