"""Device-plane preprocessing: pure jnp ops, jit-safe, static shapes.

Twin of the reference's Keras preprocessing layers for *numeric* inputs
(``elasticdl_preprocessing/layers/discretization.py``, ``round_identity.py``)
— expressed as stateless callables rather than weight-less Keras layers, so
they compose inside any flax module under ``pjit`` with no trace surprises.
All outputs are int32 ids ready for the framework's Embedding layer.
"""

import jax.numpy as jnp
import numpy as np


class Discretization:
    """Bucket numeric data by bin boundaries: id = #boundaries <= x
    (reference ``Discretization.call``). ``searchsorted`` lowers to a
    vectorized comparison-sum on TPU — no gather, MXU-friendly shapes."""

    def __init__(self, bin_boundaries):
        self.bin_boundaries = jnp.asarray(
            np.sort(np.asarray(bin_boundaries, np.float32))
        )

    @property
    def num_buckets(self) -> int:
        return int(self.bin_boundaries.shape[0]) + 1

    def __call__(self, inputs):
        x = jnp.asarray(inputs, jnp.float32)
        return jnp.searchsorted(
            self.bin_boundaries, x, side="right"
        ).astype(jnp.int32)


class RoundIdentity:
    """Round a numeric feature to an integer id clipped to [0, num_buckets)
    (reference ``RoundIdentity.call``: round then min(max_value))."""

    def __init__(self, num_buckets: int):
        if num_buckets <= 0:
            raise ValueError("num_buckets must be positive")
        self.num_buckets = int(num_buckets)

    def __call__(self, inputs):
        x = jnp.round(jnp.asarray(inputs, jnp.float32))
        x = jnp.clip(x, 0.0, float(self.num_buckets - 1))
        return x.astype(jnp.int32)


class Hashing:
    """Integer id → bucket in [0, num_bins) with a splitmix64-style mixer.

    Device twin of the host ``CategoryHash`` for features that are already
    integers (e.g. user/item ids larger than the table). Pure bit ops —
    vectorizes on the VPU, no host round-trip."""

    def __init__(self, num_bins: int):
        if num_bins <= 0:
            raise ValueError("num_bins must be positive")
        self.num_bins = int(num_bins)

    def __call__(self, inputs):
        x = jnp.asarray(inputs).astype(jnp.uint32)
        # 32-bit murmur3-style finalizer (avalanches all input bits).
        x = x ^ (x >> 16)
        x = x * jnp.uint32(0x85EBCA6B)
        x = x ^ (x >> 13)
        x = x * jnp.uint32(0xC2B2AE35)
        x = x ^ (x >> 16)
        return (x % jnp.uint32(self.num_bins)).astype(jnp.int32)


class AddIdOffset:
    """Concatenate categorical id columns into one id space by adding
    per-column offsets (census ``AddIdOffset``; the device half of
    ``concatenated_categorical_column``)."""

    def __init__(self, group_sizes):
        sizes = [int(s) for s in group_sizes]
        self.offsets = jnp.asarray(
            np.concatenate([[0], np.cumsum(sizes)[:-1]]), jnp.int32
        )
        self.total_size = int(sum(sizes))

    def __call__(self, id_columns):
        """id_columns: list of (B,) or (B, 1) int arrays, one per column.
        Returns (B, num_columns) offset ids."""
        cols = []
        for i, col in enumerate(id_columns):
            col = jnp.asarray(col, jnp.int32).reshape(col.shape[0], -1)
            cols.append(col + self.offsets[i])
        return jnp.concatenate(cols, axis=1)
