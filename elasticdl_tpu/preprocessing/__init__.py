"""Preprocessing package — TPU-native twin of ``elasticdl_preprocessing``.

The reference ships Keras preprocessing layers (Discretization, RoundIdentity,
ToNumber, ``elasticdl_preprocessing/layers/``) plus a feature-column extension
(``concatenated_categorical_column``). On TPU the same functionality splits
into two planes:

- **host transforms** (`transforms`): numpy, string-capable, run inside the
  user's ``dataset_fn`` on the worker host (strings never reach the device);
- **device layers** (`layers`): pure jnp ops, jit-safe, static shapes, run
  inside the model under ``pjit``.

``feature_group`` carries the concatenated-categorical-column offset logic
(reference ``elasticdl_preprocessing/feature_column/feature_column.py``).
"""

from elasticdl_tpu.preprocessing.feature_column import (  # noqa: F401
    DenseFeatures,
    apply_host_transforms,
    bucketized_column,
    categorical_column_with_hash_bucket,
    categorical_column_with_identity,
    categorical_column_with_vocabulary_list,
    concatenated_categorical_column,
    embedding_column,
    indicator_column,
    numeric_column,
)
from elasticdl_tpu.preprocessing.feature_group import (  # noqa: F401
    FeatureGroup,
    concat_feature_ids,
)
from elasticdl_tpu.preprocessing.layers import (  # noqa: F401
    AddIdOffset,
    Discretization,
    Hashing,
    RoundIdentity,
)
from elasticdl_tpu.preprocessing.transforms import (  # noqa: F401
    CategoryHash,
    CategoryLookup,
    NumericBucket,
    to_number,
)
