"""Host-plane preprocessing: numpy, string-capable record transforms.

These run inside ``dataset_fn`` on the worker host, before batches reach the
device — the TPU-native seat of everything the reference does on strings
(``elasticdl_preprocessing/layers/to_number.py``, the census model's
``CategoryHash``/``CategoryLookup``/``NumericBucket`` process layers in
``model_zoo/census_wide_deep_model/keras_process_layer.py``). Strings cannot
exist in an XLA program, so string→id work happens here and only integer ids
and floats cross the host→device boundary.
"""

import hashlib

import numpy as np


def to_number(values, default, dtype=np.float32):
    """Convert string-ish values to numbers, mapping empty/invalid entries to
    ``default`` (reference ``layers/to_number.py``: ToNumber.call)."""
    arr = np.asarray(values)
    if np.issubdtype(arr.dtype, np.number):
        return arr.astype(dtype)  # already numeric: skip the parse loop
    flat = arr.reshape(-1)
    out = np.empty(flat.shape, dtype)
    for i, value in enumerate(flat):
        if isinstance(value, bytes):
            value = value.decode("utf-8", "replace")
        try:
            out[i] = dtype(value)
        except (TypeError, ValueError):
            out[i] = default
    return out.reshape(arr.shape)


def _stable_string_hash(value) -> int:
    """Process-stable 64-bit string hash (md5-based; python's ``hash`` is
    salted per process, which would desync workers)."""
    if isinstance(value, bytes):
        data = value
    else:
        data = str(value).encode("utf-8")
    return int.from_bytes(hashlib.md5(data).digest()[:8], "little")


class CategoryHash:
    """String/any → bucket id in [0, num_bins) by stable hashing (census
    ``CategoryHash``; Keras ``Hashing`` layer equivalent for the host)."""

    def __init__(self, num_bins: int):
        if num_bins <= 0:
            raise ValueError("num_bins must be positive")
        self.num_bins = num_bins

    def __call__(self, values):
        arr = np.asarray(values)
        flat = arr.reshape(-1)
        out = np.empty(flat.shape, np.int64)
        for i, value in enumerate(flat):
            out[i] = _stable_string_hash(value) % self.num_bins
        return out.reshape(arr.shape)


class CategoryLookup:
    """Vocabulary lookup: value → index, out-of-vocab → ``num_oov_buckets``
    hashed slots after the vocab (census ``CategoryLookup``; Keras
    ``IndexLookup``/``StringLookup`` equivalent)."""

    def __init__(self, vocabulary, num_oov_buckets: int = 1):
        self.vocabulary = list(vocabulary)
        self.num_oov_buckets = max(int(num_oov_buckets), 1)
        self._index = {v: i for i, v in enumerate(self.vocabulary)}

    @property
    def num_buckets(self) -> int:
        return len(self.vocabulary) + self.num_oov_buckets

    def __call__(self, values):
        arr = np.asarray(values)
        flat = arr.reshape(-1)
        out = np.empty(flat.shape, np.int64)
        vocab_size = len(self.vocabulary)
        for i, value in enumerate(flat):
            if isinstance(value, bytes):
                value = value.decode("utf-8", "replace")
            idx = self._index.get(value)
            if idx is None:
                idx = vocab_size + (
                    _stable_string_hash(value) % self.num_oov_buckets
                )
            out[i] = idx
        return out.reshape(arr.shape)


class NumericBucket:
    """Bucketize numeric values by boundaries → id in [0, len(bounds)]
    (census ``NumericBucket``; host twin of ``layers.Discretization``)."""

    def __init__(self, boundaries):
        self.boundaries = np.asarray(sorted(boundaries), np.float64)

    @property
    def num_buckets(self) -> int:
        return len(self.boundaries) + 1

    def __call__(self, values):
        arr = to_number(values, default=0.0, dtype=np.float64)
        return np.searchsorted(
            self.boundaries, arr, side="right"
        ).astype(np.int64)
