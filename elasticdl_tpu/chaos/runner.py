"""Chaos harness: run a fault plan against the in-process cluster.

``ChaosRunner`` assembles the same job twice:

1. a **fault-free twin** — no injector installed — whose final
   version/loss/parameters become the loss-equivalence baseline;
2. the **faulted run** — the ``FaultInjector`` installed into the RPC,
   checkpoint, and instance-manager seams — where worker deaths are
   handled the way ``master/instance_manager.py`` handles a pod
   DELETED event: re-queue the dead worker's tasks, relaunch under a
   NEW worker id, restore from the rolling checkpoint. Plans with
   ``master_kill`` events additionally run the MASTER over a
   write-ahead journal (master/journal.py): each kill discards the
   live master and recovers an equivalent one by journal replay
   (``MiniCluster.restart_master``), audited by the
   master-restart-equivalence invariant.

Everything is sequential (one live worker at a time, synchronous row
applies, synchronous checkpoint writes), so a plan replays the exact
same schedule every run: ``chaos run --seed 7`` twice writes
byte-identical reports. Wall-clock measurements (recovery latency)
are therefore kept OUT of the default report; pass ``--timings`` to
include them.

Job flavors:

- ``sparse`` (default): the host-tier DeepFM from the model zoo with
  its table served by N in-process ``HostRowService`` shards — the
  deployment shape where shard stalls and row conservation mean
  something;
- ``dense``: the MNIST functional model, no row tier — kill /
  rpc-fault / checkpoint-corruption plans only.

Soak mode generates a ``randomized_plan`` per round from the seed and
stops at the first failed invariant, printing the seed that reproduces
it.
"""

import json
import os
import threading
from typing import Dict, List, Optional

import numpy as np

from elasticdl_tpu.chaos.faults import (
    MASTER_KILL,
    FaultPlan,
    default_plan,
    describe,
    master_kill_plan,
    randomized_plan,
)
from elasticdl_tpu.chaos.interceptors import ChaosKill, FaultInjector
from elasticdl_tpu.chaos.invariants import (
    CheckpointMonotonicity,
    ExactlyOnceTaskAccounting,
    LossTrajectoryEquivalence,
    MasterRestartEquivalence,
    RowConservation,
)
from elasticdl_tpu.common.constants import TaskType
from elasticdl_tpu.common.log_utils import get_logger

logger = get_logger("chaos_runner")

REPORT_VERSION = 1
DEFAULT_REPORT = "CHAOS_r01.json"

SPARSE_MODEL_DEF = "deepfm.deepfm_host.custom_model"
DENSE_MODEL_DEF = "mnist.mnist_functional.custom_model"


class ChaosRunError(RuntimeError):
    """The harness itself failed (kill budget blown, worker crashed on
    a non-injected error) — distinct from a failed invariant, which is
    a report verdict, not an exception."""


class ChaosRunner:
    def __init__(
        self,
        plan: FaultPlan,
        workdir: str,
        model: str = "sparse",
        records: int = 64,
        minibatch_size: int = 8,
        num_minibatches_per_task: int = 2,
        num_row_service_shards: int = 1,
        use_rpc: bool = True,
        twin: bool = True,
        max_kills: int = 8,
        join_timeout: float = 120.0,
        include_timings: bool = False,
        debug_disable_recovery: bool = False,
        flight_recorder_spans: int = 512,
        row_delta_chain: int = 2,
        row_checkpoint_steps: int = 1,
    ):
        if model not in ("sparse", "dense"):
            raise ValueError(f"unknown chaos model flavor {model!r}")
        self.plan = plan
        self.workdir = workdir
        self.model = model
        self.records = int(records)
        self.minibatch_size = int(minibatch_size)
        self.num_minibatches_per_task = int(num_minibatches_per_task)
        # Checkpoint every task (= num_minibatches_per_task versions):
        # kills land at task boundaries (get_task), so the newest valid
        # checkpoint always covers exactly the completed tasks — the
        # alignment loss-trajectory equivalence needs.
        self.checkpoint_steps = self.num_minibatches_per_task
        # Row services checkpoint every push with a SHORT delta chain
        # (full, delta, delta, compaction, ...): the plan's worker
        # kills land between a delta save and the next base compaction
        # — the kill-mid-chain case — and the end-of-run shard
        # relaunch restores across a base+delta chain. Writes are
        # synchronous (async_write=False below) so the save schedule
        # replays byte-identically per seed.
        self.row_delta_chain = max(0, int(row_delta_chain))
        self.row_checkpoint_steps = max(1, int(row_checkpoint_steps))
        self.num_row_service_shards = max(1, int(num_row_service_shards))
        self.use_rpc = bool(use_rpc)
        self.twin = bool(twin)
        self.max_kills = int(max_kills)
        self.join_timeout = float(join_timeout)
        self.include_timings = bool(include_timings)
        # Test-only regression hook: skip recover_tasks on a kill so
        # the exactly-once checker demonstrably catches the lost task
        # (tests/test_chaos.py).
        self.debug_disable_recovery = bool(debug_disable_recovery)
        # Last-N-spans ring attached to FAILED reports (observability/
        # tracing.py) — every red chaos run carries its own timeline.
        self.flight_recorder_spans = max(1, int(flight_recorder_spans))
        # master_kill plans need the write-ahead journal (the restart
        # seam recovers from it) and the restart-equivalence checker.
        self.master_kills_planned = sum(
            1 for e in plan.events if e.kind == MASTER_KILL
        )
        os.makedirs(workdir, exist_ok=True)

    # ---- data / model assembly -----------------------------------------

    def _data_file(self) -> str:
        from elasticdl_tpu.testing.data import (
            create_frappe_record_file,
            create_mnist_record_file,
        )

        path = os.path.join(self.workdir, "train.rec")
        if not os.path.exists(path):
            if self.model == "sparse":
                create_frappe_record_file(path, self.records, seed=11)
            else:
                create_mnist_record_file(path, self.records, seed=11)
        return path

    def _start_row_services(self, subdir: str,
                            with_checkpoint: bool) -> List:
        if self.model != "sparse":
            return []
        from model_zoo.deepfm import deepfm_host

        services = []
        for shard in range(self.num_row_service_shards):
            svc = deepfm_host.make_row_service()
            if with_checkpoint:
                svc.configure_checkpoint(
                    os.path.join(self.workdir, subdir, "rows",
                                 f"s{shard}"),
                    checkpoint_steps=self.row_checkpoint_steps,
                    delta_chain_max=self.row_delta_chain,
                    async_write=False,
                )
            svc.start(tag=f"rowservice/{shard}")
            services.append(svc)
        return services

    def _make_runner(self, services):
        if self.model != "sparse":
            return None
        from model_zoo.deepfm import deepfm_host
        from elasticdl_tpu.embedding import HostStepRunner
        from elasticdl_tpu.embedding.row_service import make_remote_engine

        addr = ",".join(f"localhost:{svc.port}" for svc in services)
        # Synchronous applies (no pull-ahead, no applier thread): chaos
        # replay and the loss-equivalence twin comparison both need a
        # deterministic push order.
        return HostStepRunner(
            make_remote_engine(
                addr,
                id_keys={deepfm_host.TABLE_NAME: deepfm_host.FEATURE_KEY},
            ),
            async_apply=False,
        )

    def _build_cluster(self, subdir: str, injector, services):
        from elasticdl_tpu.testing.cluster import MiniCluster
        from elasticdl_tpu.testing.data import model_zoo_dir

        runner_factory = None
        if self.model == "sparse":
            runner_factory = lambda: self._make_runner(services)  # noqa: E731
        return MiniCluster(
            model_zoo=model_zoo_dir(),
            model_def=(
                SPARSE_MODEL_DEF if self.model == "sparse"
                else DENSE_MODEL_DEF
            ),
            training_data=self._data_file(),
            minibatch_size=self.minibatch_size,
            num_minibatches_per_task=self.num_minibatches_per_task,
            use_rpc=self.use_rpc,
            step_runner_factory=runner_factory,
            checkpoint_dir=os.path.join(self.workdir, subdir, "state"),
            checkpoint_steps=self.checkpoint_steps,
            checkpoint_async=False,
            fault_injector=injector,
            # Journal only on faulted runs with master kills planned:
            # the twin must model the never-crashed job, and journal
            # writes never influence training either way.
            journal_dir=(
                os.path.join(self.workdir, subdir, "journal")
                if injector is not None and self.master_kills_planned
                else ""
            ),
        )

    def _make_replacement(self, cluster, new_id: int, subdir: str,
                          injector, services):
        from elasticdl_tpu.checkpoint import CheckpointHook
        from elasticdl_tpu.worker.master_client import MasterClient
        from elasticdl_tpu.worker.worker import Worker

        if self.use_rpc:
            client = MasterClient(
                f"localhost:{cluster._server.port}", worker_id=new_id,
                connect_timeout=10, retries=1,
            )
        else:
            # Registered with the cluster so a later master_kill
            # restart rebinds this replacement too.
            client = cluster.make_inprocess_client(
                new_id,
                callbacks=(
                    injector.in_process_callbacks()
                    if injector is not None else None
                ),
            )
        runner = self._make_runner(services)
        ckpt_dir = os.path.join(self.workdir, subdir, "state")
        hook = CheckpointHook(
            checkpoint_dir=ckpt_dir,
            checkpoint_steps=self.checkpoint_steps,
            host_tables=getattr(runner, "host_tables", None),
            async_save=False,
        )
        return Worker(
            worker_id=new_id,
            master_client=client,
            model_spec=cluster.spec,
            data_reader=cluster.train_reader,
            minibatch_size=self.minibatch_size,
            step_runner=runner,
            checkpoint_hook=hook,
            checkpoint_dir_for_init=ckpt_dir,
            # Elastic-relaunch semantics: no valid checkpoint yet (the
            # job died before the first save) means start fresh, not
            # crash-loop the replacement.
            checkpoint_init_required=False,
            metrics_report_secs=0.0,
        )

    # ---- worker driving -------------------------------------------------

    @staticmethod
    def _run_worker(worker, timeout: float) -> dict:
        """Run one worker to completion on a watchdog thread. A hang
        past ``timeout`` (e.g. the lost-task regression: the job never
        drains) gets a graceful stop so the harness returns a verdict
        instead of wedging."""
        box: dict = {}

        def target():
            try:
                box["result"] = worker.run()
            except BaseException as exc:  # ChaosKill rides through here
                box["error"] = exc

        thread = threading.Thread(
            target=target, daemon=True, name="chaos-worker"
        )
        thread.start()
        thread.join(timeout)
        if thread.is_alive():
            box["timed_out"] = True
            worker.request_stop()
            thread.join(30.0)
            if thread.is_alive():
                raise ChaosRunError(
                    "worker did not stop within grace after timeout"
                )
        return box

    def _drive_job(self, cluster, subdir: str, injector, services,
                   row_conservation: Optional[RowConservation]) -> dict:
        """The instance-manager role, in-process: run a worker; on a
        ChaosKill, re-queue its tasks and relaunch under a new id."""
        worker = cluster.workers[0]
        worker_id = 0
        next_id = 1
        kills = 0
        timed_out = False
        while True:
            box = self._run_worker(worker, self.join_timeout)
            error = box.get("error")
            if isinstance(error, ChaosKill):
                kills += 1
                if kills > self.max_kills:
                    raise ChaosRunError(
                        f"kill budget ({self.max_kills}) exceeded"
                    )
                if row_conservation is not None and services:
                    row_conservation.snapshot(
                        f"kill-{kills}", self._row_tables(services)
                    )
                if self.debug_disable_recovery:
                    logger.warning(
                        "chaos debug: SKIPPING task recovery for dead "
                        "worker %d (regression hook)", worker_id,
                    )
                else:
                    cluster.dispatcher.recover_tasks(worker_id)
                    cluster.servicer.remove_worker_metrics(worker_id)
                new_id = next_id
                next_id += 1
                logger.info(
                    "chaos: worker %d killed; relaunching as worker %d",
                    worker_id, new_id,
                )
                worker = self._make_replacement(
                    cluster, new_id, subdir, injector, services
                )
                if injector is not None:
                    injector.note_recovered(worker_id, new_id)
                worker_id = new_id
                continue
            if error is not None:
                raise error
            if box.get("timed_out"):
                timed_out = True
            result = box.get("result") or {}
            break
        leaves = {}
        if worker.state is not None:
            from elasticdl_tpu.checkpoint import named_leaves_from_state
            import jax

            leaves = jax.device_get(named_leaves_from_state(worker.state))
        return {
            "final_version": int(result.get("final_version", 0)),
            "final_loss": result.get("final_loss"),
            "trained_batches": int(result.get("trained_batches", 0)),
            "kills": kills,
            "timed_out": timed_out,
            "leaves": leaves,
        }

    # ---- row-service helpers -------------------------------------------

    @staticmethod
    def _row_tables(services) -> Dict:
        """Union view over all shards' checkpoint tables, keyed
        ``shard<i>/<table>`` so conservation tracks each shard."""
        out = {}
        for i, svc in enumerate(services):
            for name, table in svc.host_tables.items():
                out[f"shard{i}/{name}"] = table
        return out

    def _relaunch_row_services(self, services, subdir: str) -> List:
        """Shard-relaunch drill: graceful-drain checkpoint, stop every
        shard, start FRESH services restored from their checkpoints —
        row conservation must survive the full cycle (the reference's
        PS-pod relaunch + restore semantics)."""
        from model_zoo.deepfm import deepfm_host

        relaunched = []
        for shard, svc in enumerate(services):
            svc.checkpoint_now()
            svc.stop(0)
            fresh = deepfm_host.make_row_service()
            # Restore path: configure_checkpoint replays the newest
            # base + delta chain the dead service left behind.
            fresh.configure_checkpoint(
                os.path.join(self.workdir, subdir, "rows", f"s{shard}"),
                checkpoint_steps=self.row_checkpoint_steps,
                delta_chain_max=self.row_delta_chain,
                async_write=False,
            )
            relaunched.append(fresh)
        return relaunched

    # ---- one full job ---------------------------------------------------

    def _run_job(self, subdir: str, injector,
                 checkers: Optional[dict] = None) -> dict:
        services = self._start_row_services(
            subdir, with_checkpoint=injector is not None
        )
        cluster = None
        try:
            cluster = self._build_cluster(subdir, injector, services)
            if injector is not None and self.master_kills_planned:
                restart_checker = (
                    checkers.get("master_restart") if checkers else None
                )

                def _restart_master(cluster=cluster,
                                    checker=restart_checker):
                    # The dead master's in-memory truth, captured for
                    # the equivalence audit only — recovery itself
                    # sees nothing but the journal.
                    dead_state = cluster.dispatcher.export_state()
                    old_generation = cluster.servicer.generation
                    stats = cluster.restart_master()
                    if checker is not None:
                        checker.observe(
                            dead_state,
                            cluster.dispatcher.export_state(),
                            old_generation,
                            stats["generation"],
                            stats["replayed"],
                        )

                injector.set_master_restart(_restart_master)
            row_conservation = (
                checkers.get("rows") if checkers else None
            )
            summary = self._drive_job(
                cluster, subdir, injector, services, row_conservation
            )
            if checkers:
                accounting = checkers.get("accounting")
                if accounting is not None:
                    accounting.bind(cluster.dispatcher)
                if row_conservation is not None and services:
                    row_conservation.snapshot(
                        "pre-relaunch", self._row_tables(services)
                    )
                    relaunched = self._relaunch_row_services(
                        services, subdir
                    )
                    services = relaunched
                    checkers["final_row_tables"] = self._row_tables(
                        services
                    )
            return summary
        finally:
            if cluster is not None:
                if cluster._server is not None:
                    cluster._server.stop(0)
                cluster.stop()
            for svc in services:
                try:
                    svc.stop(0)
                except Exception:
                    pass

    # ---- public API ------------------------------------------------------

    def run(self) -> dict:
        """Twin run (optional) then faulted run; returns the report
        dict (deterministic by construction — see module docstring)."""
        baseline = None
        if self.twin:
            logger.info("chaos: fault-free twin run")
            baseline = self._run_job("twin", injector=None)
        injector = FaultInjector(self.plan)
        monotonic = CheckpointMonotonicity()
        injector.add_checkpoint_listener(
            on_save=monotonic.on_save, on_restore=monotonic.on_restore
        )
        rows = RowConservation() if self.model == "sparse" else None
        accounting = _LateBoundAccounting(
            expected_records={TaskType.TRAINING: self.records},
        )
        equivalence = LossTrajectoryEquivalence(baseline)
        master_restart = (
            MasterRestartEquivalence(self.master_kills_planned)
            if self.master_kills_planned else None
        )
        checkers = {
            "accounting": accounting, "rows": rows,
            "master_restart": master_restart,
        }
        logger.info(
            "chaos: faulted run, %d event(s):\n%s",
            len(self.plan.events), describe(self.plan),
        )
        harness_error = None
        summary = None
        # Flight recorder for the faulted run: every red run ships its
        # own timeline. Installing it cannot perturb determinism (span
        # ids are urandom, never wall-clock, and the injector ignores
        # the _trace_ctx field), and the dump is attached ONLY to
        # failed reports — green same-seed runs stay byte-identical.
        from elasticdl_tpu.observability import tracing

        recorder = tracing.FlightRecorder(
            capacity=self.flight_recorder_spans
        )
        injector.install()
        tracing.install_recorder(recorder)
        try:
            summary = self._run_job("faulted", injector, checkers)
        except ChaosRunError as exc:
            harness_error = str(exc)
        finally:
            tracing.uninstall_recorder()
            injector.uninstall()
        verdicts = []
        if summary is not None:
            equivalence.observe(summary)
        verdicts.append(accounting.check())
        if rows is not None:
            verdicts.append(
                rows.check(checkers.get("final_row_tables") or {})
            )
        verdicts.append(monotonic.check())
        verdicts.append(equivalence.check())
        if master_restart is not None:
            verdicts.append(master_restart.check())
        passed = harness_error is None and all(v.passed for v in verdicts)
        report = {
            "chaos_report_version": REPORT_VERSION,
            "seed": int(self.plan.seed),
            "config": {
                "model": self.model,
                "records": self.records,
                "minibatch_size": self.minibatch_size,
                "num_minibatches_per_task": self.num_minibatches_per_task,
                "checkpoint_steps": self.checkpoint_steps,
                "row_checkpoint_steps": self.row_checkpoint_steps,
                "row_delta_chain": self.row_delta_chain,
                "num_row_service_shards": self.num_row_service_shards,
                "use_rpc": self.use_rpc,
                "twin": self.twin,
            },
            "plan": self.plan.to_dict(),
            "schedule": injector.injected,
            "fault_counts": injector.fault_counts(),
            "job": _round_summary(summary),
            "invariants": [v.to_dict() for v in verdicts],
            "metrics": injector.metric_families(),
            "passed": bool(passed),
        }
        if harness_error is not None:
            report["harness_error"] = harness_error
        if not passed:
            # Dump the last-N-spans ring into the red report: the
            # failed invariant arrives with the timeline that led to it
            # (which task stalled, which RPC retried, which checkpoint
            # write preceded the kill). Green reports never carry it,
            # so same-seed byte-identity is untouched.
            report["flight_recorder"] = {
                "capacity": recorder.capacity,
                "spans": [_round_span(s) for s in recorder.snapshot()],
            }
        if self.include_timings:
            # Wall-clock section: excluded by default so same-seed runs
            # are byte-identical.
            report["timings"] = {
                "recoveries": [
                    {**r, "latency_secs": round(r["latency_secs"], 4)}
                    for r in injector.recoveries
                ],
                "master_restarts": [
                    {**r, "latency_secs": round(r["latency_secs"], 4)}
                    for r in injector.master_restarts
                ],
            }
        return report


class _LateBoundAccounting:
    """ExactlyOnceTaskAccounting whose dispatcher arrives after the
    cluster is built (the checker set is created before the job)."""

    def __init__(self, expected_records, num_epochs: int = 1):
        self._expected = expected_records
        self._epochs = num_epochs
        self._inner = None

    def bind(self, dispatcher):
        self._inner = ExactlyOnceTaskAccounting(
            dispatcher, self._expected, self._epochs
        )

    def check(self):
        from elasticdl_tpu.chaos.invariants import CheckResult

        if self._inner is None:
            return CheckResult(
                ExactlyOnceTaskAccounting.name, False,
                "job never produced a dispatcher to audit",
            )
        return self._inner.check()


def _round_span(span: dict) -> dict:
    """Flight-recorder span for the (red) report: timestamps rebased
    nowhere (monotonic, process-relative) but rounded for readability;
    ids kept so the tree is reconstructable with critical_path.py."""
    out = dict(span)
    out["t0"] = round(float(span.get("t0", 0.0)), 6)
    out["dur"] = round(float(span.get("dur", 0.0)), 6)
    return out


def _round_summary(summary: Optional[dict]) -> Optional[dict]:
    """Job summary for the report: floats rounded (stable text), the
    (large) leaves dict reduced to a per-leaf shape listing."""
    if summary is None:
        return None
    leaves = summary.get("leaves") or {}
    loss = summary.get("final_loss")
    return {
        "final_version": summary["final_version"],
        "final_loss": None if loss is None else round(float(loss), 6),
        "trained_batches": summary["trained_batches"],
        "kills": summary["kills"],
        "timed_out": bool(summary.get("timed_out")),
        "dense_leaves": {
            name: list(np.shape(arr))
            for name, arr in sorted(leaves.items())
        },
    }


def render_report(report: dict) -> str:
    return json.dumps(report, sort_keys=True, indent=2) + "\n"


def write_report(report: dict, path: str):
    with open(path, "w") as fh:
        fh.write(render_report(report))
    logger.info("chaos report written to %s", path)


# ---- CLI ----------------------------------------------------------------


def _force_cpu_if_requested():
    """Mirror tests/conftest.py: the container's sitecustomize may pin
    a TPU plugin via jax.config, which overrides JAX_PLATFORMS — when
    the caller asked for cpu (make chaos-smoke), force it back."""
    if os.environ.get("JAX_PLATFORMS", "") == "cpu":
        import jax

        jax.config.update("jax_platforms", "cpu")


def main(argv=None) -> int:
    """``elasticdl_tpu chaos {run|soak} <flags>``."""
    import argparse
    import shutil
    import tempfile

    parser = argparse.ArgumentParser("elasticdl_tpu-chaos")
    parser.add_argument("command", choices=["run", "soak"])
    parser.add_argument("--seed", type=int, default=0)
    parser.add_argument("--plan", default="",
                        help="JSON fault-plan file; default: the "
                             "canonical seed-derived plan")
    parser.add_argument("--master_kill", action="store_true",
                        help="run: use the master-crash acceptance "
                             "plan (two master kills recovered by "
                             "journal replay — docs/fault_tolerance"
                             ".md) instead of the canonical worker-"
                             "fault plan")
    parser.add_argument("--report", default=DEFAULT_REPORT)
    parser.add_argument("--workdir", default="",
                        help="Scratch dir (default: a fresh tempdir, "
                             "removed afterwards)")
    parser.add_argument("--model", choices=["sparse", "dense"],
                        default="sparse")
    parser.add_argument("--records", type=int, default=64)
    parser.add_argument("--minibatch_size", type=int, default=8)
    parser.add_argument("--num_minibatches_per_task", type=int, default=2)
    parser.add_argument("--num_row_service_shards", type=int, default=1)
    parser.add_argument("--in_process", action="store_true",
                        help="Drive the master via direct calls "
                             "instead of localhost gRPC")
    parser.add_argument("--no_twin", action="store_true",
                        help="Skip the fault-free twin (disables the "
                             "loss-equivalence invariant)")
    parser.add_argument("--timings", action="store_true",
                        help="Include wall-clock recovery latencies "
                             "(makes the report non-byte-reproducible)")
    parser.add_argument("--max_kills", type=int, default=8)
    parser.add_argument("--join_timeout", type=float, default=120.0)
    parser.add_argument("--rounds", type=int, default=3,
                        help="soak: randomized plans per invocation")
    args = parser.parse_args(argv)

    _force_cpu_if_requested()

    workdir = args.workdir
    cleanup = False
    if not workdir:
        workdir = tempfile.mkdtemp(prefix="edl_chaos_")
        cleanup = True

    def runner_for(plan: FaultPlan, subdir: str) -> ChaosRunner:
        return ChaosRunner(
            plan,
            workdir=os.path.join(workdir, subdir),
            model=args.model,
            records=args.records,
            minibatch_size=args.minibatch_size,
            num_minibatches_per_task=args.num_minibatches_per_task,
            num_row_service_shards=args.num_row_service_shards,
            use_rpc=not args.in_process,
            twin=not args.no_twin,
            max_kills=args.max_kills,
            join_timeout=args.join_timeout,
            include_timings=args.timings,
        )

    try:
        if args.command == "run":
            if args.plan:
                plan = FaultPlan.load(args.plan)
            elif args.master_kill:
                plan = master_kill_plan(
                    args.seed,
                    num_row_service_shards=args.num_row_service_shards,
                )
            else:
                plan = default_plan(
                    args.seed,
                    num_row_service_shards=args.num_row_service_shards,
                )
            report = runner_for(plan, "r0").run()
            write_report(report, args.report)
            print(f"chaos run seed={plan.seed} "
                  f"passed={report['passed']} "
                  f"faults={report['fault_counts']}")
            for verdict in report["invariants"]:
                mark = "PASS" if verdict["passed"] else "FAIL"
                print(f"  [{mark}] {verdict['name']}: "
                      f"{verdict['details']}")
            return 0 if report["passed"] else 1

        # soak: randomized plans; first failure wins and prints the
        # seed that replays it.
        rounds = []
        failed_seed = None
        for i in range(args.rounds):
            round_seed = args.seed * 1000 + i
            plan = randomized_plan(
                round_seed,
                num_row_service_shards=args.num_row_service_shards,
            )
            print(f"chaos soak round {i} seed={round_seed}: "
                  f"{len(plan.events)} event(s)")
            report = runner_for(plan, f"soak{i}").run()
            rounds.append({
                "seed": round_seed,
                "passed": report["passed"],
                "fault_counts": report["fault_counts"],
                "invariants": report["invariants"],
            })
            if not report["passed"]:
                failed_seed = round_seed
                break
        soak_report = {
            "chaos_report_version": REPORT_VERSION,
            "mode": "soak",
            "seed": int(args.seed),
            "rounds": rounds,
            "passed": failed_seed is None,
        }
        write_report(soak_report, args.report)
        if failed_seed is not None:
            # The failing plan is fully determined by its seed — dump
            # it so the failure replays with one command.
            plan_path = args.report.replace(
                ".json", ""
            ) + f"_failed_plan_seed{failed_seed}.json"
            randomized_plan(
                failed_seed,
                num_row_service_shards=args.num_row_service_shards,
            ).save(plan_path)
            print(
                f"chaos soak FAILED at seed {failed_seed}; reproduce "
                f"with:\n  python -m elasticdl_tpu chaos run "
                f"--plan {plan_path}"
            )
            return 1
        print(f"chaos soak passed ({len(rounds)} round(s))")
        return 0
    finally:
        if cleanup:
            shutil.rmtree(workdir, ignore_errors=True)


if __name__ == "__main__":
    import sys

    sys.exit(main())
