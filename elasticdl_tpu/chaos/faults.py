"""Fault plans: seed-deterministic schedules of injectable failures.

The paper's resilience claim — elasticity from task re-queuing and pod
relaunch, not checkpoint-restart — is only testable under *repeatable*
adversarial schedules (AMPS and the MPMD pipeline schedulers in
PAPERS.md both validate against scripted fault injection). A
``FaultPlan`` is that schedule: a list of ``FaultEvent``s whose
triggers are **call counts and save counts, never wall-clock**, so the
same plan against the same job replays the exact same fault sequence
(``chaos run --seed N`` twice is byte-identical). Randomized soak
plans are generated from a seed for the same reason: a soak failure
reproduces from the printed seed alone.

Event kinds (ISSUE 3 tentpole):

- ``kill_worker``    — simulate pod death (SIGKILL / exit 137) at a
                       worker's Nth ``get_task``; recovery is the
                       dispatcher re-queue + relaunch-with-new-id path.
- ``rpc_drop``       — fail a named RPC with a transport code
                       (UNAVAILABLE by default); exercises the stub's
                       jittered-backoff retry.
- ``rpc_error``      — fail a named RPC with a *permanent* code
                       (INTERNAL): must surface, never retry.
- ``rpc_delay``      — add latency to a named RPC.
- ``stall_shard``    — server-side stall of one row-service shard's
                       handlers (the slow-PS regime).
- ``blackhole``      — drop every matching call for a window of
                       ``duration_calls`` calls (a dead channel).
- ``corrupt_checkpoint`` — truncate/garbage/delete a shard file of the
                       version written by the Nth matching save.
- ``master_kill``    — simulate MASTER pod death at the Nth dispatch
                       RPC (ISSUE 5 tentpole): the harness's restart
                       seam rebuilds the master from its write-ahead
                       journal (master/journal.py) while the worker
                       rides the outage out on its RPC retry budget
                       and re-attaches under the bumped generation.
- ``fsync_stall``    — slow-disk brownout at a storage fsync seam
                       (ISSUE 20): ``target`` picks the seam —
                       ``"pushlog"`` stalls the WAL group commit that
                       durable-ack pushes wait on, ``"checkpoint"``
                       stalls the saver's shard-file fsyncs, ``""``
                       stalls both. The overload plane's deadline-
                       bounded durable waits are what keeps this from
                       wedging the push path.
"""

import dataclasses
import json
import random
from typing import Dict, List, Optional

KILL_WORKER = "kill_worker"
RPC_DROP = "rpc_drop"
RPC_ERROR = "rpc_error"
RPC_DELAY = "rpc_delay"
STALL_SHARD = "stall_shard"
BLACKHOLE = "blackhole"
CORRUPT_CHECKPOINT = "corrupt_checkpoint"
MASTER_KILL = "master_kill"
FSYNC_STALL = "fsync_stall"

KINDS = (
    KILL_WORKER, RPC_DROP, RPC_ERROR, RPC_DELAY, STALL_SHARD,
    BLACKHOLE, CORRUPT_CHECKPOINT, MASTER_KILL, FSYNC_STALL,
)

# Storage seams an fsync_stall can target ("" = every seam).
FSYNC_SEAMS = ("pushlog", "checkpoint")

# Site of an RPC fault: client = before the request leaves the stub
# (exercises stub retry/backoff), server = inside the handler wrap
# (exercises the caller's timeout/ride-out behavior).
SITES = ("client", "server")


@dataclasses.dataclass
class FaultEvent:
    """One scripted failure. Trigger semantics:

    - ``at_call`` (1-based): fire on the Nth call matching this
      event's (site, target, method) filter; with ``duration_calls``
      > 1 the event stays active for that many matching calls (a
      window). ``at_call=0`` means probabilistic: each matching call
      fires with ``probability`` drawn from the event's own seeded
      RNG — still replay-deterministic for a sequential caller.
    - ``max_fires`` caps total fires (0 = unlimited).
    - ``corrupt_checkpoint`` triggers on ``at_save``: the Nth save
      whose checkpoint dir contains ``target`` as a substring.
    """

    kind: str
    target: str = ""        # service name / server tag / ckpt-dir substring
    method: str = ""        # RPC method ("" = any)
    site: str = "client"    # where RPC faults inject (client|server)
    worker_id: int = -1     # kill victim (-1 = whichever worker matches)
    at_call: int = 0        # Nth matching call (1-based); 0 = probabilistic
    probability: float = 0.0
    delay_secs: float = 0.0
    duration_calls: int = 1  # window width for stall/blackhole
    code: str = "UNAVAILABLE"  # injected status code for drop/blackhole
    at_save: int = 0        # corrupt_checkpoint: Nth matching save
    corrupt_mode: str = "truncate"  # truncate | garbage | delete
    shard: int = 0          # stall_shard: which row-service shard
    max_fires: int = 1      # 0 = unlimited

    def __post_init__(self):
        if self.kind not in KINDS:
            raise ValueError(f"unknown fault kind {self.kind!r}")
        # A hand-written window event (blackhole/stall over N calls)
        # must not be silently neutered by the max_fires=1 default:
        # the window IS the intended fire count.
        if (self.at_call > 0 and self.duration_calls > 1
                and self.max_fires
                and self.max_fires < self.duration_calls):
            self.max_fires = self.duration_calls
        if self.site not in SITES:
            raise ValueError(f"unknown fault site {self.site!r}")
        if self.corrupt_mode not in ("truncate", "garbage", "delete"):
            raise ValueError(f"unknown corrupt_mode {self.corrupt_mode!r}")
        if self.kind == FSYNC_STALL and self.target not in (
            ("",) + FSYNC_SEAMS
        ):
            raise ValueError(
                f"fsync_stall target must be one of {FSYNC_SEAMS} "
                f"(or '' for any), got {self.target!r}"
            )
        if self.at_call == 0 and self.kind in (
            RPC_DROP, RPC_ERROR, RPC_DELAY
        ) and not (0.0 <= self.probability <= 1.0):
            raise ValueError("probability must be in [0, 1]")

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)

    @classmethod
    def from_dict(cls, d: dict) -> "FaultEvent":
        known = {f.name for f in dataclasses.fields(cls)}
        unknown = set(d) - known
        if unknown:
            raise ValueError(f"unknown FaultEvent fields {sorted(unknown)}")
        return cls(**d)


@dataclasses.dataclass
class FaultPlan:
    """An ordered event list + the seed that (re)generates any
    probabilistic decisions. Serializes to stable JSON (sorted keys)
    so two runs of the same seed write byte-identical schedules."""

    events: List[FaultEvent] = dataclasses.field(default_factory=list)
    seed: int = 0

    def to_dict(self) -> dict:
        return {
            "seed": int(self.seed),
            "events": [e.to_dict() for e in self.events],
        }

    def to_json(self) -> str:
        return json.dumps(self.to_dict(), sort_keys=True, indent=2) + "\n"

    @classmethod
    def from_dict(cls, d: dict) -> "FaultPlan":
        return cls(
            events=[FaultEvent.from_dict(e) for e in d.get("events", [])],
            seed=int(d.get("seed", 0)),
        )

    @classmethod
    def from_json(cls, text: str) -> "FaultPlan":
        return cls.from_dict(json.loads(text))

    def save(self, path: str):
        with open(path, "w") as fh:
            fh.write(self.to_json())

    @classmethod
    def load(cls, path: str) -> "FaultPlan":
        with open(path) as fh:
            return cls.from_json(fh.read())


def default_plan(seed: int = 0,
                 master_service: str = "elasticdl_tpu.Master",
                 num_row_service_shards: int = 1) -> FaultPlan:
    """The canonical acceptance schedule (ISSUE 3): one worker kill,
    one row-shard stall window, one checkpoint corruption, and one
    transient RPC drop to exercise the stub retry — all positioned so
    recovery restores the newest *valid* checkpoint and the faulted
    run stays loss-equivalent to its fault-free twin at equal data
    order. Trigger positions wobble with the seed (same seed, same
    plan, byte for byte)."""
    rng = random.Random(int(seed))
    kill_call = 3 + rng.randint(0, 1)  # after 2-3 completed tasks
    events = [
        # Transient blip on the control plane: the stub's backoff retry
        # must ride it out with no schedule change.
        FaultEvent(
            kind=RPC_DROP, site="client", target=master_service,
            method="get_task", at_call=2, code="UNAVAILABLE",
        ),
        # Slow-shard regime: the worker's pulls/pushes just get slower,
        # nothing times out, order is unchanged.
        FaultEvent(
            kind=STALL_SHARD, site="server",
            shard=rng.randrange(max(1, num_row_service_shards)),
            at_call=4 + rng.randint(0, 2), duration_calls=3,
            delay_secs=0.05, max_fires=3,
        ),
        # Corrupt the FIRST worker-state checkpoint: later saves
        # supersede it, so recovery restores the newest valid version
        # and no completed task's training is lost (the corrupt-latest
        # case is the loss-equivalence checker's job to catch — see
        # tests/test_chaos.py).
        FaultEvent(
            kind=CORRUPT_CHECKPOINT, target="state", at_save=1,
            corrupt_mode="truncate",
        ),
        # Hard pod death at a task boundary; recovery = re-queue +
        # relaunch under a new worker id + restore from checkpoint.
        FaultEvent(
            kind=KILL_WORKER, site="client", target=master_service,
            method="get_task", at_call=kill_call,
        ),
    ]
    return FaultPlan(events=events, seed=int(seed))


def master_kill_plan(seed: int = 0,
                     master_service: str = "elasticdl_tpu.Master",
                     num_row_service_shards: int = 1) -> FaultPlan:
    """The master-crash acceptance schedule (ISSUE 5): kill the master
    twice — once at a clean task boundary (a ``get_task``, nothing
    leased by the reporting path) and once mid-lease (the worker's
    ``report_task_result`` arrives at a master that just lost its
    memory) — plus one transient RPC drop so the ordinary stub-retry
    path is exercised alongside the restart ride-out. Both kills must
    leave accounting exactly-once and the loss trajectory equal to the
    fault-free twin: the first proves the journal replays the queue
    state, the second proves a surviving lease + retried report
    resolves without re-training. Trigger positions wobble with the
    seed (same seed, same plan, byte for byte)."""
    rng = random.Random(int(seed))
    # Trigger positions assume the canonical job shape (>= 4 tasks:
    # the default 64 records at 8x2 records/task). Kills are listed
    # BEFORE the drop so their call counters see every attempt — an
    # event only stops counting the call on which an earlier-listed
    # event fired.
    events = [
        # Kill #1: at a dispatch boundary — the recovered master must
        # hand out the exact task the dead one would have.
        FaultEvent(
            kind=MASTER_KILL, site="client", target=master_service,
            method="get_task", at_call=3 + rng.randint(0, 1),
        ),
        # Kill #2: mid-lease — the worker trained the task, the report
        # hits the fresh incarnation, which must accept it against the
        # replayed lease (NOT re-queue it: re-training would diverge
        # from the twin).
        FaultEvent(
            kind=MASTER_KILL, site="client", target=master_service,
            method="report_task_result", at_call=3 + rng.randint(0, 1),
        ),
        # Transient blip alongside the restarts: the plain stub-retry
        # path must coexist with generation fencing.
        FaultEvent(
            kind=RPC_DROP, site="client", target=master_service,
            method="get_task", at_call=2, code="UNAVAILABLE",
        ),
    ]
    return FaultPlan(events=events, seed=int(seed))


def randomized_plan(seed: int,
                    master_service: str = "elasticdl_tpu.Master",
                    num_row_service_shards: int = 1,
                    max_kills: int = 2) -> FaultPlan:
    """Soak-mode generator: a survivable random schedule fully
    determined by ``seed`` (print the seed, replay the failure)."""
    rng = random.Random(int(seed))
    events: List[FaultEvent] = []
    for _ in range(rng.randint(1, max_kills)):
        events.append(FaultEvent(
            kind=KILL_WORKER, site="client", target=master_service,
            method="get_task", at_call=rng.randint(2, 6),
        ))
    if rng.random() < 0.8:
        events.append(FaultEvent(
            kind=RPC_DROP, site="client", target=master_service,
            method=rng.choice(["get_task", "report_task_result"]),
            at_call=0, probability=rng.uniform(0.02, 0.15),
            max_fires=rng.randint(1, 3),
        ))
    if rng.random() < 0.6:
        events.append(FaultEvent(
            kind=STALL_SHARD, site="server",
            shard=rng.randrange(max(1, num_row_service_shards)),
            at_call=rng.randint(2, 8),
            duration_calls=rng.randint(1, 4),
            delay_secs=rng.uniform(0.01, 0.1),
            max_fires=rng.randint(1, 4),
        ))
    if rng.random() < 0.5:
        events.append(FaultEvent(
            kind=CORRUPT_CHECKPOINT, target="state",
            at_save=1,  # never the latest-at-kill version: soak plans
            # must stay loss-equivalent (see default_plan rationale)
            corrupt_mode=rng.choice(["truncate", "garbage", "delete"]),
        ))
    if rng.random() < 0.4:
        events.append(FaultEvent(
            kind=BLACKHOLE, site="client", target=master_service,
            method="report_version", at_call=rng.randint(2, 6),
            duration_calls=rng.randint(1, 3), max_fires=3,
        ))
    return FaultPlan(events=events, seed=int(seed))


def describe(plan: FaultPlan) -> str:
    """One line per event, for logs and the soak console."""
    lines = []
    for i, e in enumerate(plan.events):
        bits = [f"[{i}] {e.kind}"]
        if e.kind == KILL_WORKER:
            bits.append(f"victim={'any' if e.worker_id < 0 else e.worker_id}"
                        f" at get_task #{e.at_call}")
        elif e.kind == MASTER_KILL:
            bits.append(
                f"at {e.method or 'get_task'} #{e.at_call} "
                "(journal-replay restart)"
            )
        elif e.kind == CORRUPT_CHECKPOINT:
            bits.append(f"dir~{e.target!r} save #{e.at_save}"
                        f" mode={e.corrupt_mode}")
        elif e.kind == STALL_SHARD:
            bits.append(f"shard={e.shard} +{e.delay_secs}s"
                        f" x{e.duration_calls} from call #{e.at_call}")
        elif e.kind == FSYNC_STALL:
            bits.append(f"seam={e.target or 'any'} +{e.delay_secs}s"
                        f" x{e.duration_calls} from call #{e.at_call}")
        else:
            trig = (f"call #{e.at_call}" if e.at_call
                    else f"p={e.probability}")
            bits.append(f"{e.site} {e.target}/{e.method or '*'} {trig}"
                        f" code={e.code}")
        lines.append(" ".join(bits))
    return "\n".join(lines)
