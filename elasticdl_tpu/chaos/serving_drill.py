"""Serving-fleet chaos drill: kill a replica mid-load, availability
holds (ISSUE 6; ``make serve-fleet-smoke``).

The training-side chaos plane (runner.py) adjudicates recovery with
invariant checkers over a faulted run; this is the serving-tier
equivalent, fully in-process: a router + 2 ``InferenceServer``
replicas (each with a hot-row LRU) over ONE live ``HostRowService``,
driven by seeded mixed-priority closed-loop clients. After a fixed
number of completed requests one replica is hard-killed; the router
must hedge/route around it. Mid-run row pushes exercise the cache's
version-based invalidation under fire.

Invariants checked (exit nonzero on failure):
- availability: non-shed requests answer 200 at >= the threshold
  across the kill (sheds are counted separately — a 429 is the system
  WORKING, not failing);
- cache effectiveness: the replicas' hot-row caches served a nonzero
  share of resolved rows;
- the router noticed: the killed replica is marked unhealthy by the
  end of the run.

Deterministic per seed on the REQUEST side (ids, priorities, kill
trigger); wall-clock effects (which exact request straddles the kill,
hedge timing) vary — the invariants are thresholds, not byte
equality, mirroring the soak mode's contract.
"""

import json
import os
import tempfile
import threading
import time

import numpy as np

from elasticdl_tpu.common.log_utils import get_logger

logger = get_logger("serving_drill")

ID_SPACE = 200  # small id universe -> the LRU warms inside the drill


def export_sparse_bundle(tmpdir: str, seed: int):
    """DeepFM host-tier bundle (row-service export mode) — the sparse
    serving shape the hot-row cache exists for. Returns (bundle dir,
    the deepfm_host zoo module). The row plane is the caller's:
    in-process here, a real ``row_service`` subprocess in
    bench_serving's fleet mode."""
    import optax

    from elasticdl_tpu.core.model_spec import get_model_spec
    from elasticdl_tpu.core.train_state import init_train_state
    from elasticdl_tpu.serving.export import export_serving_bundle
    from elasticdl_tpu.testing.data import model_zoo_dir
    from model_zoo.deepfm import deepfm_host

    spec = get_model_spec(
        model_zoo_dir(), "deepfm.deepfm_host.custom_model"
    )
    rng = np.random.RandomState(seed)
    ids = rng.randint(0, ID_SPACE, (4, 10)).astype(np.int32)
    batch = {
        "features": {deepfm_host.FEATURE_KEY: ids},
        "labels": np.zeros((4,), np.int32),
        "mask": np.ones((4,), np.float32),
    }
    state = init_train_state(
        spec.model, optax.adam(1e-3), batch, seed=seed
    )
    bundle = os.path.join(tmpdir, "bundle")
    export_serving_bundle(
        bundle, spec.model, state, batch_example=batch,
        model_def="deepfm.deepfm_host.custom_model",
        host_id_keys={deepfm_host.TABLE_NAME: deepfm_host.FEATURE_KEY},
    )
    return bundle, deepfm_host


def _export_sparse_bundle(tmpdir: str, seed: int):
    """Bundle + a live in-process row service (the drill's shape)."""
    from elasticdl_tpu.embedding.optimizer import (
        SGD,
        HostOptimizerWrapper,
    )
    from elasticdl_tpu.embedding.row_service import HostRowService
    from elasticdl_tpu.embedding.table import EmbeddingTable
    from elasticdl_tpu.observability import MetricsRegistry

    bundle, deepfm_host = export_sparse_bundle(tmpdir, seed)
    service = HostRowService(
        {deepfm_host.TABLE_NAME:
            EmbeddingTable(deepfm_host.TABLE_NAME,
                           deepfm_host.EMBEDDING_DIM)},
        HostOptimizerWrapper(SGD(lr=0.5)),
        metrics_registry=MetricsRegistry(),
    ).start()
    return bundle, service, deepfm_host


def run_drill(seed: int = 7, requests_per_client: int = 40,
              clients: int = 4, kill_after: int = 30,
              availability_threshold: float = 0.98,
              row_cache: int = 4096,
              report_path: str = "") -> dict:
    """Run the fleet drill; returns the report dict (["passed"])."""
    from elasticdl_tpu.common import tensor_utils
    from elasticdl_tpu.observability import MetricsRegistry
    from elasticdl_tpu.serving.model_store import ModelStore
    from elasticdl_tpu.serving.router import RouterServer
    from elasticdl_tpu.serving.server import InferenceServer

    tmpdir = tempfile.mkdtemp(prefix="serving_drill_")
    bundle, service, deepfm_host = _export_sparse_bundle(tmpdir, seed)
    feature_key = deepfm_host.FEATURE_KEY
    table_name = deepfm_host.TABLE_NAME

    replica_registries = [MetricsRegistry(), MetricsRegistry()]
    replicas = []
    stores = []
    for registry in replica_registries:
        store = ModelStore(
            bundle,
            row_service_addr=f"localhost:{service.port}",
            poll_seconds=3600,
            row_cache_capacity=row_cache,
            row_cache_version_check_secs=0.02,
            metrics_registry=registry,
        )
        store.load_initial()
        stores.append(store)
        replicas.append(InferenceServer(
            store, max_batch_size=8, batch_deadline_ms=2.0, port=0,
            metrics_registry=registry,
        ).start())
    router_registry = MetricsRegistry()
    router = RouterServer(
        [f"localhost:{r.port}" for r in replicas], port=0,
        metrics_registry=router_registry,
        hedge_min_ms=10, hedge_max_ms=200, replica_timeout=10.0,
        probe_secs=0.2,
    ).start()

    # Warm every replica's buckets + the hedge window so the measured
    # phase never pays a first-compile.
    rng = np.random.RandomState(seed)

    def payload(client_rng):
        ids = client_rng.randint(0, ID_SPACE, (4, 10)).astype(np.int32)
        return tensor_utils.dumps({"features": {feature_key: ids}})

    import http.client

    def predict(conn, body, priority):
        conn.request(
            "POST", "/v1/predict", body=body,
            headers={"Content-Type": "application/x-msgpack",
                     "X-Priority": priority},
        )
        resp = conn.getresponse()
        resp.read()
        return resp.status

    warm_conn = http.client.HTTPConnection(
        "localhost", router.port, timeout=30
    )
    for _ in range(8):
        status = predict(warm_conn, payload(rng), "normal")
        assert status == 200, f"warmup failed with {status}"
    warm_conn.close()

    completed = [0]
    statuses = []
    lock = threading.Lock()
    killed = threading.Event()
    priorities = ("high", "normal", "low")

    def client(worker: int):
        client_rng = np.random.RandomState(seed * 1000 + worker)
        conn = http.client.HTTPConnection(
            "localhost", router.port, timeout=30
        )
        try:
            for i in range(requests_per_client):
                priority = priorities[
                    int(client_rng.randint(0, len(priorities)))
                ]
                try:
                    status = predict(conn, payload(client_rng),
                                     priority)
                except Exception:
                    # Transport error surfaces as a failed request —
                    # counted against availability, and the keep-alive
                    # conn is replaced.
                    status = -1
                    conn.close()
                    conn = http.client.HTTPConnection(
                        "localhost", router.port, timeout=30
                    )
                with lock:
                    statuses.append((priority, status))
                    completed[0] += 1
                    fire_kill = (
                        completed[0] >= kill_after
                        and not killed.is_set()
                    )
                    if fire_kill:
                        killed.set()  # claim before dropping the lock
                if fire_kill:
                    logger.info(
                        "DRILL: kill trigger at request %d",
                        completed[0],
                    )
                    replicas[0].stop()
                if i > 0 and i % 10 == 0:
                    # Row pushes under fire: bump the table version so
                    # the replicas' caches must invalidate + re-pull.
                    service._push_row_grads({
                        "table": table_name,
                        "ids": client_rng.randint(
                            0, ID_SPACE, (4,)
                        ).astype(np.int64),
                        "grads": np.full(
                            (4, deepfm_host.EMBEDDING_DIM), 0.1,
                            np.float32,
                        ),
                    })
        finally:
            conn.close()

    threads = [
        threading.Thread(target=client, args=(w,))
        for w in range(clients)
    ]
    t0 = time.monotonic()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    elapsed = time.monotonic() - t0
    drained = router.drain(grace=10.0)
    for replica in replicas[1:]:
        replica.stop()
    for store in stores:
        store.stop()
    service.stop(0)

    # ---- adjudicate ----------------------------------------------------

    counts = {}
    for _, status in statuses:
        counts[str(status)] = counts.get(str(status), 0) + 1
    ok = counts.get("200", 0)
    shed = counts.get("429", 0)
    total = len(statuses)
    answered = total - shed
    availability = ok / answered if answered else 0.0

    def cache_stats():
        hits = misses = 0.0
        for registry in replica_registries:
            for family in registry.snapshot()["families"]:
                if family["name"] == \
                        "edl_tpu_serving_row_cache_hits_total":
                    hits += sum(
                        s["value"] for s in family["series"]
                    )
                if family["name"] == \
                        "edl_tpu_serving_row_cache_misses_total":
                    misses += sum(
                        s["value"] for s in family["series"]
                    )
        rate = hits / (hits + misses) if hits + misses else 0.0
        return {"hits": hits, "misses": misses,
                "hit_rate": round(rate, 4)}

    cache = cache_stats()
    router_snap = {
        f["name"]: f for f in router_registry.snapshot()["families"]
    }
    hedges = {
        s["labels"][0]: s["value"]
        for s in router_snap.get(
            "edl_tpu_router_hedges_total", {"series": []}
        )["series"]
    }
    unhealthy = sum(
        s["value"] for s in router_snap.get(
            "edl_tpu_router_replica_unhealthy_total", {"series": []}
        )["series"]
    )

    invariants = [
        {
            "name": "availability_across_replica_kill",
            "passed": availability >= availability_threshold,
            "detail": f"{ok}/{answered} non-shed requests answered "
                      f"200 ({availability:.4f} >= "
                      f"{availability_threshold})",
        },
        {
            "name": "hot_row_cache_effective",
            "passed": cache["hits"] > 0,
            "detail": f"cache hit rate {cache['hit_rate']} "
                      f"({int(cache['hits'])} hits / "
                      f"{int(cache['misses'])} misses)",
        },
        {
            "name": "router_detected_dead_replica",
            "passed": unhealthy >= 1,
            "detail": f"{int(unhealthy)} unhealthy transition(s)",
        },
        {
            "name": "router_drained_clean",
            "passed": bool(drained),
            "detail": "in-flight hedged requests settled in grace",
        },
    ]
    report = {
        "config": {
            "seed": seed,
            "clients": clients,
            "requests_per_client": requests_per_client,
            "kill_after_requests": kill_after,
            "row_cache": row_cache,
            "availability_threshold": availability_threshold,
        },
        "elapsed_s": round(elapsed, 3),
        "statuses": counts,
        "shed": shed,
        "availability": round(availability, 4),
        "cache": cache,
        "hedges": hedges,
        "replica_unhealthy_transitions": int(unhealthy),
        "invariants": invariants,
        "passed": all(inv["passed"] for inv in invariants),
    }
    if report_path:
        with open(report_path, "w") as f:
            json.dump(report, f, indent=1)
            f.write("\n")
    for inv in invariants:
        logger.info(
            "DRILL invariant %-34s %s  (%s)", inv["name"],
            "PASS" if inv["passed"] else "FAIL", inv["detail"],
        )
    return report


def main(argv=None) -> int:
    import argparse

    parser = argparse.ArgumentParser("serving-fleet-drill")
    parser.add_argument("--seed", type=int, default=7)
    parser.add_argument("--clients", type=int, default=4)
    parser.add_argument("--requests_per_client", type=int, default=40)
    parser.add_argument("--kill_after", type=int, default=30)
    parser.add_argument("--availability_threshold", type=float,
                        default=0.98)
    parser.add_argument("--report", default="")
    args = parser.parse_args(argv)

    report = run_drill(
        seed=args.seed, clients=args.clients,
        requests_per_client=args.requests_per_client,
        kill_after=args.kill_after,
        availability_threshold=args.availability_threshold,
        report_path=args.report,
    )
    print(json.dumps({
        k: report[k] for k in (
            "availability", "shed", "cache", "hedges", "passed"
        )
    }))
    return 0 if report["passed"] else 1


if __name__ == "__main__":
    import sys

    sys.exit(main())
