"""Continuous-profiling drill: an injected hot function must dominate
the flame table and appear in the alert-triggered incident bundle.

``make profile-smoke`` (docs/observability.md "Continuous profiling &
exemplars") — a REAL two-process run:

1. **Overhead pin** — the sampling profiler's per-pass cost × the
   default rate must stay ≤ 1% of one core (the PR 4 span-guard
   discipline; the fast-lane twin lives in
   tests/test_profile_plane.py).
2. **Two-process flame capture** — a real
   ``python -m elasticdl_tpu.embedding.row_service`` subprocess runs a
   drill model-zoo module whose optimizer calls a named busy-spin
   (``_drill_hot_spin``) on every push, with ``--profile_hz 67``,
   ``--flight_recorder`` and ``--master_addr`` pointing at this
   process's master-servicer stand-in. The drill pushes gradients over
   real gRPC; the shard's flame windows, spans, and exemplar-stamped
   push histogram piggyback back on ``report_metrics``. Gates:

   - the hot function DOMINATES the shard's flame table (heaviest
     handler-class leaf, ≥ ``DOMINANCE_GATE`` of handler samples);
   - a threshold SLO rule over ``edl_tpu_row_service_push_seconds``
     fires, and its incident bundle passes ``tools/check_incident.py
     --require-profile --require-exemplars``: a valid profile
     snapshot (``tools/check_profile.py`` accepts it) carrying the hot
     function, plus ≥ 1 exemplar trace id that resolves to a span in
     the bundle's ``trace.json``.

Exits nonzero unless every gate holds; writes PROFILE_DRILL.json.
"""

import argparse
import json
import os
import subprocess
import sys
import threading
import time

import numpy as np

from elasticdl_tpu.common.log_utils import get_logger

logger = get_logger("profile_drill")

OVERHEAD_GATE = 0.01        # profiler <= 1% of a busy loop at 67 Hz
DOMINANCE_GATE = 0.30       # hot fn share of handler-class samples
HOT_FN = "_drill_hot_spin"
PUSH_LATENCY_GATE = 0.005   # rule: p99 push > 5ms (hot spin is ~25ms)

ZOO_MODULE = '''\
"""Drill-owned model zoo: a row service whose optimizer burns a named
hot function on every push (written by chaos/profile_drill.py)."""

import time

from elasticdl_tpu.embedding.optimizer import SGD, HostOptimizerWrapper
from elasticdl_tpu.embedding.row_service import HostRowService
from elasticdl_tpu.embedding.table import EmbeddingTable

HOT_MS = 25.0


def _drill_hot_spin(budget_ms=HOT_MS):
    deadline = time.perf_counter() + budget_ms / 1e3
    acc = 0
    while time.perf_counter() < deadline:
        acc += 1
    return acc


class _HotOptimizer(HostOptimizerWrapper):
    def apply_gradients(self, table, ids, grads):
        _drill_hot_spin()
        return super().apply_gradients(table, ids, grads)


def make_row_service():
    table = EmbeddingTable("drill", 8)
    return HostRowService({"drill": table}, _HotOptimizer(SGD(0.1)))
'''


def _force_cpu_if_requested():
    if os.environ.get("JAX_PLATFORMS", "") == "cpu":
        import jax

        jax.config.update("jax_platforms", "cpu")


def _free_port() -> int:
    import socket

    with socket.socket() as sock:
        sock.bind(("localhost", 0))
        return sock.getsockname()[1]


def measure_overhead(passes: int = 300,
                     resident_threads: int = 6) -> dict:
    """Phase 1: per-pass sampling cost, projected to the default rate.

    Measured against RESIDENT threads parked in waits (deep stacks to
    walk, no GIL contention): a pass's true cost is its walk time —
    time a sampler spends waiting for a busy worker thread to release
    the GIL is time the worker spends doing its own work, not profiler
    overhead. Best-of-3 rounds damp scheduler noise."""
    from elasticdl_tpu.observability.profiler import (
        DEFAULT_HZ,
        SamplingProfiler,
    )

    stop = threading.Event()

    def parked(depth=12):
        if depth:
            return parked(depth - 1)
        stop.wait()

    threads = [
        threading.Thread(target=parked, daemon=True)
        for _ in range(resident_threads)
    ]
    for t in threads:
        t.start()
    prof = SamplingProfiler(hz=DEFAULT_HZ, window_secs=3600.0)
    try:
        for _ in range(20):  # warm the frame-name cache
            prof.sample()
        per_pass = float("inf")
        for _round in range(3):
            t0 = time.perf_counter()
            for _ in range(passes):
                prof.sample()
            per_pass = min(
                per_pass, (time.perf_counter() - t0) / passes
            )
    finally:
        stop.set()
        for t in threads:
            t.join(timeout=2.0)
    return {
        "passes": passes,
        "resident_threads": resident_threads,
        "per_pass_secs": per_pass,
        "hz": DEFAULT_HZ,
        "overhead_fraction": per_pass * DEFAULT_HZ,
        "gate": OVERHEAD_GATE,
        "ok": per_pass * DEFAULT_HZ <= OVERHEAD_GATE,
    }


def drill_rule():
    from elasticdl_tpu.observability.slo import SLORule

    return SLORule(
        name="row-push-slow",
        kind="threshold",
        series="edl_tpu_row_service_push_seconds",
        source="rowservice-0",
        aggregation="p99",
        op=">",
        value=PUSH_LATENCY_GATE,
        window_secs=60.0,
        min_count=5,
        description="push handler p99 above 5ms — the injected hot "
                    "function must trip this",
    )


def _hot_share(samples: dict) -> dict:
    """Hot-function dominance over the handler (pool) thread class:
    share of pool samples whose stack contains the hot function, and
    whether it is the heaviest pool leaf."""
    pool_total = 0
    hot_total = 0
    leaf_counts = {}
    for stack, count in samples.items():
        if not stack.startswith("pool;"):
            continue
        pool_total += count
        if HOT_FN in stack:
            hot_total += count
        leaf = stack.rsplit(";", 1)[-1]
        leaf_counts[leaf] = leaf_counts.get(leaf, 0) + count
    heaviest_leaf = max(
        leaf_counts.items(), key=lambda kv: kv[1]
    )[0] if leaf_counts else ""
    share = hot_total / pool_total if pool_total else 0.0
    return {
        "pool_samples": pool_total,
        "hot_samples": hot_total,
        "share": round(share, 4),
        "heaviest_pool_leaf": heaviest_leaf,
        "gate": DOMINANCE_GATE,
        "ok": bool(
            share >= DOMINANCE_GATE and HOT_FN in heaviest_leaf
        ),
    }


def run_two_process(workdir: str, timeout_secs: float = 120.0) -> dict:
    """Phase 2: the real two-process capture + alert loop."""
    from elasticdl_tpu.comm.rpc import (
        RpcServer,
        RpcStub,
        wait_for_channel_ready,
    )
    from elasticdl_tpu.observability import MetricsPlane
    from elasticdl_tpu.observability.slo import IncidentRecorder

    try:
        from tools.check_incident import check_incident
    except ImportError:
        sys.path.insert(
            0, os.path.dirname(os.path.dirname(os.path.dirname(
                os.path.abspath(__file__)
            )))
        )
        from tools.check_incident import check_incident

    zoo_dir = os.path.join(workdir, "zoo")
    os.makedirs(zoo_dir, exist_ok=True)
    with open(
        os.path.join(zoo_dir, "profile_drill_zoo.py"), "w"
    ) as fh:
        fh.write(ZOO_MODULE)
    incidents_dir = os.path.join(workdir, "incidents")

    # The master-servicer stand-in: exactly the report_metrics fold-in
    # a real master does (servicer.py), minus the job plumbing the
    # drill doesn't need.
    plane = MetricsPlane(ttl_secs=120.0)
    plane.enable_timeseries(cadence_secs=0.5)

    def report_metrics(request: dict) -> dict:
        component = str(request.get("component", "") or "component")
        component_id = int(request.get("component_id", 0))
        snapshot = request.get("metrics")
        if snapshot:
            plane.ingest(f"{component}-{component_id}", snapshot)
        return {"accepted": True}

    master = RpcServer(
        "localhost:0",
        {"elasticdl_tpu.Master": {"report_metrics": report_metrics}},
    ).start()

    row_port = _free_port()
    row_addr = f"localhost:{row_port}"
    child_env = dict(os.environ)
    child_env.setdefault("JAX_PLATFORMS", "cpu")
    child = subprocess.Popen(
        [
            sys.executable, "-m", "elasticdl_tpu.embedding.row_service",
            "--model_zoo", zoo_dir,
            "--model_def", "profile_drill_zoo.make_row_service",
            "--addr", row_addr,
            "--profile_hz", "67",
            "--profile_window_secs", "2",
            "--flight_recorder", "8192",
            "--master_addr", f"localhost:{master.port}",
            "--metrics_report_secs", "1",
        ],
        env=child_env,
    )
    verdict = {
        "row_addr": row_addr,
        "pushes": 0,
        "fired": False,
        "bundle": None,
        "bundle_errors": None,
        "dominance": None,
        "exemplar_resolved": False,
        "hot_in_bundle_profile": False,
        "ok": False,
    }
    stub = None
    try:
        channel = wait_for_channel_ready(row_addr, timeout=90.0)
        stub = RpcStub(channel, "RowService")
        ids = np.arange(16, dtype=np.int64)
        grads = np.full((16, 8), 0.01, np.float32)
        deadline = time.monotonic() + timeout_secs
        seq = 0

        def push():
            nonlocal seq
            stub.call(
                "push_row_grads", table="drill", ids=ids,
                grads=grads, client="profile-drill", seq=seq,
                timeout=30.0,
            )
            seq += 1

        # Warm-up: pump pushes until the shard's profile windows,
        # spans, AND exemplar-carrying histogram snapshot have all
        # ridden report_metrics back — only then arm the SLO engine,
        # so the bundle captured at the firing transition is complete
        # (a real master is armed from minute zero and simply fires
        # later; the drill compresses that timeline).
        def shard_telemetry_ready() -> bool:
            merged = plane.profiles.merged(
                "rowservice-0", window_secs=300.0
            )
            if merged is None or merged["sample_count"] < 100:
                return False
            # The windows that arrived must already SHOW the hot work
            # (the shard's first window closes during idle startup —
            # gating on mere sample counts would arm the rule against
            # a pre-push flame table).
            hot = sum(
                count for stack, count in merged["samples"].items()
                if HOT_FN in stack
            )
            if hot < 50:
                return False
            if len(plane.traces) == 0:
                return False
            for snap in plane.cluster.snapshots().values():
                for family in snap.get("families", []):
                    if family.get(
                        "name"
                    ) == "edl_tpu_row_service_push_seconds" and any(
                        s.get("exemplars")
                        for s in family.get("series", [])
                    ):
                        return True
            return False

        while time.monotonic() < deadline:
            push()
            plane.slo_tick()
            if shard_telemetry_ready():
                break
        else:
            raise RuntimeError(
                "shard telemetry (profiles/spans/exemplars) never "
                "reached the master stand-in"
            )
        verdict["pushes"] = seq

        recorder = IncidentRecorder(
            incidents_dir,
            metrics_plane=plane,
            store=plane.timeseries,
            background=False,
        )
        plane.enable_slo(
            rules=[drill_rule()], incident_recorder=recorder
        )
        while time.monotonic() < deadline:
            push()
            plane.slo_tick()
            if plane.slo.firing():
                break
        verdict["pushes"] = seq
        verdict["fired"] = bool(plane.slo and plane.slo.firing())
        if not verdict["fired"]:
            raise RuntimeError("SLO rule never fired")
        if not recorder.bundles:
            raise RuntimeError("rule fired but no bundle captured")
        bundle = recorder.bundles[-1]
        verdict["bundle"] = bundle

        # Gate: the bundle is the full black box — valid profile
        # snapshot AND >=1 exemplar trace id resolving in trace.json.
        errors = check_incident(
            bundle, require_profile=True, require_exemplars=True
        )
        verdict["bundle_errors"] = errors

        # Gate: the hot function dominates the shard's flame table.
        body = plane.profiles.render(
            "rowservice-0", window_secs=300.0
        )
        samples = (body.get("window") or {}).get("samples") or {}
        verdict["dominance"] = _hot_share(samples)

        # And appears in the bundle's captured profile too.
        with open(os.path.join(bundle, "profile.json")) as fh:
            bundle_profile = json.load(fh)
        shard_entry = (
            bundle_profile.get("components", {}).get("rowservice-0")
        )
        verdict["hot_in_bundle_profile"] = bool(
            shard_entry and HOT_FN in shard_entry.get("folded", "")
        )
        with open(os.path.join(bundle, "exemplars.json")) as fh:
            verdict["exemplar_count"] = len(
                json.load(fh).get("exemplars", [])
            )
        verdict["exemplar_resolved"] = not any(
            "exemplars.json" in e for e in errors
        )
        verdict["ok"] = bool(
            not errors
            and verdict["dominance"]["ok"]
            and verdict["hot_in_bundle_profile"]
        )
        return verdict
    finally:
        if stub is not None:
            try:
                stub.close()
            except Exception:
                pass
        child.terminate()
        try:
            child.wait(timeout=15.0)
        except subprocess.TimeoutExpired:
            child.kill()
            child.wait(timeout=15.0)
        master.stop(0)
        plane.stop()


def main(argv=None) -> int:
    _force_cpu_if_requested()
    parser = argparse.ArgumentParser("elasticdl_tpu-profile-drill")
    parser.add_argument("--workdir", default="",
                        help="Scratch dir (default: a tempdir)")
    parser.add_argument("--report", default="PROFILE_DRILL.json")
    parser.add_argument("--timeout", type=float, default=120.0)
    args = parser.parse_args(argv)

    workdir = args.workdir
    if not workdir:
        import tempfile

        workdir = tempfile.mkdtemp(prefix="edl_profile_drill_")

    logger.info("phase 1: profiler overhead pin")
    overhead = measure_overhead()
    logger.info(
        "profiler overhead: %.3f%% of one core at %g Hz (gate %.0f%%)",
        100.0 * overhead["overhead_fraction"], overhead["hz"],
        100.0 * OVERHEAD_GATE,
    )

    logger.info("phase 2: two-process hot-function capture")
    try:
        capture = run_two_process(workdir, timeout_secs=args.timeout)
    except Exception as exc:
        logger.exception("two-process capture failed")
        capture = {"ok": False, "error": f"{type(exc).__name__}: {exc}"}

    report = {
        "overhead": overhead,
        "capture": capture,
        "ok": bool(overhead["ok"] and capture.get("ok")),
    }
    with open(args.report, "w") as fh:
        json.dump(report, fh, indent=2, sort_keys=True, default=str)
        fh.write("\n")
    if report["ok"]:
        dom = capture.get("dominance") or {}
        logger.info(
            "PROFILE DRILL PASS: hot fn %.0f%% of handler samples "
            "(heaviest leaf %s), bundle %s valid with %d exemplars",
            100.0 * dom.get("share", 0.0),
            dom.get("heaviest_pool_leaf"),
            capture.get("bundle"), capture.get("exemplar_count", 0),
        )
        return 0
    logger.error("PROFILE DRILL FAIL: %s",
                 json.dumps(report, indent=2, default=str))
    return 1


if __name__ == "__main__":
    sys.exit(main())
