"""Tiered-storage chaos drill: kills mid-eviction and mid-compaction
must restore to a consistent table.

``make tiered-smoke`` (docs/sparse_path.md "Tiered storage"):

1. **Kill mid-eviction** — a tiered ``HostRowService`` (hot budget a
   fraction of the driven id space, slots tiering in lockstep via the
   native optimizer) is killed by ``ChaosKill`` raised from the tier's
   pre-erase chaos hook: demoted rows' bytes are already appended to
   the cold store but the hot arena still holds them — the duplicate-
   record window. The relaunch restores from the checkpoint chain into
   a FRESH cold dir (the cold tier is a spill cache; a dead
   incarnation's spill is never resurrected), the *driver* re-pushes
   the schedule suffix past the restored version, and the end state
   must land **byte-equal** to a fault-free twin driven by the same
   seeded schedule — rows, optimizer slots, and Adam step counters
   included.

   Contract note: this drill's service runs checkpoints WITHOUT the
   write-ahead push log, so the kill legitimately loses applied
   pushes back to the restored version and the driver models a
   trainer retrying the *unacked* suffix. Once ``--push_log_dir`` is
   configured, that external re-drive is FORBIDDEN — acked pushes
   survive kills on their own (restore-chain → WAL-tail replay), and
   ``chaos/quake_drill.py`` (``make quake-smoke``) pins exactly that:
   byte-equality with no re-driven pushes (docs/fault_tolerance.md
   "Zero-RPO row plane", docs/chaos.md "Relaunch contract").
2. **Kill mid-compaction** — same service shape, killed from the cold
   store's mid-compact hook: the victim segment's live rows are
   re-appended to the tail but the victim file still exists. Same
   relaunch + replay + byte-equality bar.
3. **Store-level crash recovery** — a raw ``ColdRowStore`` crashed
   mid-compaction is reopened with ``fresh=False``: the rebuilt
   later-record-wins index must serve every row byte-equal to the
   pre-crash oracle, proving segments are self-describing.

Every dead incarnation's cold dir is left in the workdir and audited
by ``tools/check_store.py`` (the drill runs it in-process; ``make
tiered-smoke``/``chaos-smoke`` run it again on the tree). The row-
conservation invariant (chaos/invariants.py) snapshots at each kill
over ``to_arrays`` — which spans BOTH tiers, so a row demoted to disk
counts exactly like a hot one. Exits nonzero unless every scenario
holds. Fast-lane equivalent:
``tests/test_tiered_store.py::test_tiered_drill_passes``.
"""

import argparse
import json
import os
import sys

import numpy as np

from elasticdl_tpu.common.log_utils import get_logger

logger = get_logger("tiered_drill")

TABLE = "drill_rows"
DIM = 8
VOCAB = 480
HOT_BUDGET = 48
PUSHES = 60
CHECKPOINT_STEPS = 10
SEGMENT_BYTES = 4096


def _schedule(seed: int):
    """The seeded push schedule: (ids, grads) per seq, identical for
    twin, faulted, and replay runs."""
    rng = np.random.RandomState(seed)
    out = []
    for _ in range(PUSHES):
        ids = np.unique(rng.randint(0, VOCAB, 96)).astype(np.int64)
        grads = rng.rand(ids.size, DIM).astype(np.float32)
        out.append((ids, grads))
    return out


def _build_service(ckpt_dir, cold_dir=None):
    from elasticdl_tpu.embedding.optimizer import Adam
    from elasticdl_tpu.embedding.row_service import HostRowService
    from elasticdl_tpu.native.row_store import (
        make_host_optimizer,
        make_host_table,
    )

    svc = HostRowService(
        {TABLE: make_host_table(TABLE, DIM)},
        make_host_optimizer(Adam(lr=0.01)),
    )
    if cold_dir is not None:
        svc.configure_tiering(
            cold_dir, HOT_BUDGET, segment_max_bytes=SEGMENT_BYTES,
            compact_live_fraction=0.6, background_compact=False,
        )
    svc.configure_checkpoint(
        ckpt_dir, checkpoint_steps=CHECKPOINT_STEPS,
        delta_chain_max=3, async_write=False,
    )
    return svc


def _drive(svc, schedule, start_seq: int, client: str):
    """Push seqs ``start_seq..len(schedule)`` through the real
    handler; a ChaosKill propagates to the caller (the simulated pod
    death)."""
    for seq in range(start_seq, len(schedule) + 1):
        ids, grads = schedule[seq - 1]
        svc._push_row_grads({
            "table": TABLE, "ids": ids, "grads": grads,
            "client": client, "seq": seq,
        })


def _row_views(svc):
    """The checkpoint views that hold ROWS (tables + slots + step
    counters) — the push-dedup seq map is client-id bookkeeping, keyed
    by which incarnation pushed, so equality/conservation over it
    would compare client ids, not state."""
    return {
        name: view for name, view in svc.host_tables.items()
        if name != "__row_service_seqs__"
    }


def _capture(svc):
    """Every row view's (ids, rows), across both tiers."""
    return {
        name: view.to_arrays() for name, view in _row_views(svc).items()
    }


def _tables_equal(a, b):
    problems = []
    for name in sorted(a):
        ids_a, rows_a = a[name]
        ids_b, rows_b = b[name]
        if not np.array_equal(np.asarray(ids_a), np.asarray(ids_b)):
            problems.append(f"{name}: id sets differ "
                            f"({len(ids_a)} vs {len(ids_b)})")
        elif not np.array_equal(
            np.asarray(rows_a, np.float32), np.asarray(rows_b, np.float32)
        ):
            problems.append(f"{name}: row bytes differ")
    return problems


def _kill_drill(workdir, schedule, twin_state, scenario: str, seed: int):
    """One service-level kill scenario: fault hook raises ChaosKill,
    relaunch restores + replays, final state must equal the twin's."""
    from elasticdl_tpu.chaos.interceptors import ChaosKill
    from elasticdl_tpu.chaos.invariants import RowConservation
    from elasticdl_tpu.storage import cold_store, tiered

    ckpt_dir = os.path.join(workdir, scenario, "ckpt")
    cold_a = os.path.join(workdir, "cold", f"{scenario}_dead")
    cold_b = os.path.join(workdir, "cold", f"{scenario}_relaunch")
    result = {"scenario": scenario, "passed": False, "problems": []}
    conservation = RowConservation()

    svc = _build_service(ckpt_dir, cold_a)
    fired = {"n": 0}

    def _boom(*_args):
        # Arm on the SECOND event so the first eviction/compaction
        # exercises the healthy path in the same run.
        fired["n"] += 1
        if fired["n"] == 2:
            raise ChaosKill(worker_id=0, event_index=fired["n"])

    if scenario == "kill_mid_eviction":
        tiered.set_chaos_hooks(pre_erase=_boom)
    else:
        cold_store.set_chaos_hooks(mid_compact=_boom)
    killed_at = None
    try:
        _drive(svc, schedule, 1, f"drill-{scenario}")
    except ChaosKill:
        killed_at = svc._push_count
        conservation.snapshot(f"{scenario}@push{killed_at}",
                              _row_views(svc))
    finally:
        tiered.set_chaos_hooks(pre_erase=None)
        cold_store.set_chaos_hooks(mid_compact=None)
    if killed_at is None:
        result["problems"].append(
            "fault hook never fired (no eviction/compaction happened "
            "— workload too small for the budget?)"
        )
        return result
    result["killed_at_push"] = int(killed_at)

    # Relaunch: fresh cold dir (spill is not durable state), restore
    # from the chain, replay the pushes the kill lost. The dead
    # incarnation's cold dir stays on disk for fsck.
    svc2 = _build_service(ckpt_dir, cold_b)
    restored = svc2._push_count
    result["restored_version"] = int(restored)
    _drive(svc2, schedule, restored + 1, f"drill-{scenario}-relaunch")
    assert svc2.checkpoint_now()

    check = conservation.check(_row_views(svc2))
    result["row_conservation"] = check.to_dict()
    if not check.passed:
        result["problems"].append(check.details)
    result["problems"].extend(
        _tables_equal(twin_state, _capture(svc2))
    )
    stats = svc2.tier_stats()[TABLE]
    result["tier_stats"] = {
        "hot_rows": stats["hot_rows"], "cold_rows": stats["cold_rows"],
        "budget": stats["budget"],
    }
    if stats["hot_rows"] > HOT_BUDGET:
        result["problems"].append(
            f"hot tier over budget after relaunch: "
            f"{stats['hot_rows']} > {HOT_BUDGET}"
        )
    svc2.stop()
    result["passed"] = not result["problems"]
    return result


def _store_recovery_drill(workdir, seed: int):
    """Raw ColdRowStore crashed mid-compaction, reopened fresh=False:
    the rebuilt index must serve pre-crash bytes exactly."""
    from elasticdl_tpu.chaos.interceptors import ChaosKill
    from elasticdl_tpu.storage import ColdRowStore, cold_store

    path = os.path.join(workdir, "cold", "store_recovery")
    result = {"scenario": "store_crash_recovery", "passed": False,
              "problems": []}
    rng = np.random.RandomState(seed)
    store = ColdRowStore(path, dim=DIM, segment_max_bytes=2048,
                         compact_live_fraction=0.6,
                         background_compact=False)
    ids = np.arange(128, dtype=np.int64)
    oracle = {}

    def _boom(_seg):
        raise ChaosKill(worker_id=0, event_index=1)

    try:
        rows = rng.rand(ids.size, DIM).astype(np.float32)
        store.put_rows(ids, rows)
        for i, row in zip(ids.tolist(), rows):
            oracle[i] = row
        cold_store.set_chaos_hooks(mid_compact=_boom)
        # Overwrites drop segment live fractions below threshold; the
        # inline compactor then dies between re-append and delete.
        # rows2 go into the oracle FIRST: put_rows commits them to the
        # index before _maybe_compact runs, so the kill lands after
        # they are durable.
        rows2 = rng.rand(64, DIM).astype(np.float32)
        for i, row in zip(ids[:64].tolist(), rows2):
            oracle[i] = row
        store.put_rows(ids[:64], rows2)
        result["problems"].append("mid-compact hook never fired")
    except ChaosKill:
        pass
    finally:
        cold_store.set_chaos_hooks(mid_compact=None)
    if result["problems"]:
        return result

    reopened = ColdRowStore(path, fresh=False, background_compact=False)
    want_ids = np.array(sorted(oracle), np.int64)
    have_ids = reopened.live_ids()
    if not np.array_equal(want_ids, have_ids):
        result["problems"].append(
            f"recovered id set differs: {want_ids.size} expected, "
            f"{have_ids.size} recovered"
        )
    else:
        got = reopened.get_rows(want_ids)
        want = np.stack([oracle[i] for i in want_ids.tolist()])
        if not np.array_equal(got, want):
            result["problems"].append(
                "recovered rows differ from pre-crash bytes"
            )
    result["recovered_rows"] = int(have_ids.size)
    reopened.close()
    result["passed"] = not result["problems"]
    return result


def run_drill(workdir: str, seed: int) -> dict:
    schedule = _schedule(seed)

    # Fault-free twin: same schedule, no tiering — the byte-equality
    # oracle (tiering must be invisible to training semantics).
    twin = _build_service(os.path.join(workdir, "twin", "ckpt"))
    _drive(twin, schedule, 1, "drill-twin")
    assert twin.checkpoint_now()
    twin_state = _capture(twin)
    twin.stop()

    scenarios = [
        _kill_drill(workdir, schedule, twin_state,
                    "kill_mid_eviction", seed),
        _kill_drill(workdir, schedule, twin_state,
                    "kill_mid_compaction", seed),
        _store_recovery_drill(workdir, seed),
    ]

    # Fsck every cold dir the drill left behind — dead incarnations
    # included (their crash states must still parse clean).
    sys.path.insert(0, os.path.join(
        os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__)))), "tools",
    ))
    from check_store import check_store

    fsck_errors, fsck_report = check_store(os.path.join(workdir, "cold"))
    return {
        "drill": "tiered_storage",
        "seed": seed,
        "config": {
            "table": TABLE, "dim": DIM, "vocab": VOCAB,
            "hot_budget_rows": HOT_BUDGET, "pushes": PUSHES,
            "checkpoint_steps": CHECKPOINT_STEPS,
            "segment_max_bytes": SEGMENT_BYTES,
        },
        "scenarios": scenarios,
        "fsck": {
            "errors": fsck_errors,
            "stores": len(fsck_report["stores"]),
            "live_rows": fsck_report["live_rows"],
            "garbage_bytes": fsck_report["garbage_bytes"],
        },
        "passed": (
            all(s["passed"] for s in scenarios) and not fsck_errors
        ),
    }


def main(argv=None) -> int:
    parser = argparse.ArgumentParser("elasticdl_tpu-tiered-drill")
    parser.add_argument("--seed", type=int, default=7)
    parser.add_argument("--workdir", required=True,
                        help="Scratch dir; cold dirs (dead incarnations "
                             "included) are left here for fsck")
    parser.add_argument("--report", default="TIERED_DRILL.json")
    args = parser.parse_args(argv)

    report = run_drill(args.workdir, args.seed)
    with open(args.report, "w") as fh:
        json.dump(report, fh, indent=2, sort_keys=True, default=str)
        fh.write("\n")
    for scenario in report["scenarios"]:
        logger.info(
            "tiered drill %s: %s%s", scenario["scenario"],
            "PASS" if scenario["passed"] else "FAIL",
            "" if scenario["passed"]
            else f" ({'; '.join(scenario['problems'])})",
        )
    logger.info(
        "tiered drill: %s (fsck %d store(s), %d error(s)); report %s",
        "PASS" if report["passed"] else "FAIL",
        report["fsck"]["stores"], len(report["fsck"]["errors"]),
        args.report,
    )
    return 0 if report["passed"] else 1


if __name__ == "__main__":
    sys.exit(main())
