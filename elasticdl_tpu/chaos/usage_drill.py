"""Workload-attribution drill: principal tags must survive a live
reshard, and metering must stay effectively free.

``make usage-smoke`` (docs/observability.md "Workload attribution"):

Two byte-identical runs of the same seeded push schedule against a
2-shard row fleet that splits live onto a third shard mid-run (with
hot-row replica designation, so pushes fan out replica refreshes):

1. **Baseline** — attribution disabled via the
   ``principal.set_enabled(False)`` kill-switch: no ``_principal``
   piggyback on the wire, no usage metering server-side. Every push
   is timed.
2. **Attributed** — attribution on, the driver process tagged via
   ``principal.set_process_principal(job="drill",
   component="worker", purpose="training")`` (the remote engine
   fans pushes out on worker threads, so the process default — not
   a thread-local push — is what reaches the wire, exactly as in
   ``worker/main.py``). Same pushes, same pulls, same split.

Gates (all three must hold, else exit nonzero):

- **Purity** — internal fan-outs re-tag themselves, so in the
  process-wide registry every ``usage_bytes_total`` series for the
  ``ingest_rows`` method carries ``purpose="migration"`` and every
  ``replica_refresh`` series carries ``purpose="replica_refresh"``
  — training traffic NEVER pays for migration or replica bytes.
  Both purposes must also actually appear with nonzero bytes (the
  drill really exercised a split and refreshes).
- **Coverage** — ``summarize_usage`` reports at least
  ``SHARE_GATE`` (95%) of handler wall-time attributed to a
  non-``unknown`` purpose.
- **Overhead** — p99 push latency with attribution on is at most
  ``P99_GATE`` (1.05x) the attribution-off baseline. The pair of
  runs is re-measured once before failing, damping scheduler noise
  the way ``profile_drill.measure_overhead`` does with best-of-3.

The drill's shards share one process registry, so the purity and
coverage gates are process-wide; per-shard top-K attribution (the
``/usage`` endpoint's ``shards`` block) is covered by unit tests
over ``MetricsPlane`` ingest. Report is validated by
``tools/check_usage.py`` and fsck'd under the ``usage`` kind.
Fast-lane equivalent: ``tests/test_usage.py::test_usage_drill_passes``.
"""

import argparse
import json
import os
import sys
import time

import numpy as np

from elasticdl_tpu.common.log_utils import get_logger

logger = get_logger("usage_drill")

TABLE = "drill_rows"
DIM = 8
PUSHES = 240
PUSH_IDS = 48
ID_SPACE = 1_000_000
HOT_IDS = 6
SPLIT_AT = 120        # push index before the 2 -> 3 split
WARMUP = 20           # pushes excluded from latency samples
P99_GATE = 1.05       # attributed p99 <= 1.05x baseline p99
SHARE_GATE = 0.95     # >= 95% of handler time non-unknown
LATENCY_ATTEMPTS = 2  # re-measure the pair once before failing


def _schedule(seed: int):
    """Seeded (ids, grads) per push — uniform ids plus a pinned hot
    set so replica designation has a signal. Identical across the
    baseline and attributed runs."""
    rng = np.random.RandomState(seed)
    hot = rng.choice(ID_SPACE, HOT_IDS, replace=False).astype(np.int64)
    out = []
    for _ in range(PUSHES):
        ids = np.unique(np.concatenate([
            rng.randint(0, ID_SPACE, PUSH_IDS).astype(np.int64), hot,
        ]))
        grads = rng.rand(ids.size, DIM).astype(np.float32)
        out.append((ids, grads))
    return hot, out


def _build_shard(port: int = 0):
    from elasticdl_tpu.embedding.optimizer import (
        Adam,
        HostOptimizerWrapper,
    )
    from elasticdl_tpu.embedding.row_service import HostRowService
    from elasticdl_tpu.embedding.table import EmbeddingTable

    svc = HostRowService(
        {TABLE: EmbeddingTable(TABLE, DIM)},
        HostOptimizerWrapper(Adam(lr=0.01)),
    )
    # No checkpoint/WAL: this drill measures attribution overhead on
    # the pure push path; durability planes have their own drills.
    return svc.start(f"localhost:{port}")


class _Fleet:
    """One run's shards + reshard authority + client."""

    def __init__(self, workdir: str, run: str):
        from elasticdl_tpu.master.row_reshard import (
            ReshardPolicy,
            ShardMapController,
        )

        self.shards = [_build_shard() for _ in range(2)]
        self.state_path = os.path.join(workdir, run, "shard_map.json")
        os.makedirs(os.path.dirname(self.state_path), exist_ok=True)
        self.controller = ShardMapController(
            self.state_path,
            policy=ReshardPolicy(replica_min_pulls=2,
                                 replica_top_k=HOT_IDS,
                                 replica_count=1),
        )
        self.controller.bootstrap(
            [f"localhost:{s.port}" for s in self.shards]
        )
        self.engine = None

    def client(self):
        from elasticdl_tpu.embedding.row_service import (
            make_remote_engine,
        )

        if self.engine is None:
            self.engine = make_remote_engine(
                ",".join(f"localhost:{s.port}" for s in self.shards),
                id_keys={TABLE: "ids"}, retries=6, backoff_secs=0.1,
            )
        return self.engine

    def push(self, ids, grads):
        engine = self.client()
        engine.optimizer.apply_gradients(
            engine.tables[TABLE], ids, grads
        )

    def pull(self, ids):
        return self.client().tables[TABLE].get(ids)

    def add_shard(self) -> str:
        svc = _build_shard()
        self.shards.append(svc)
        return f"localhost:{svc.port}"

    def stop(self):
        self.controller.close()
        if self.engine is not None:
            self.engine.close()
        for svc in self.shards:
            try:
                svc.stop(0)
            except Exception:
                pass


def _run_once(workdir: str, run: str, hot, schedule):
    """Drive the full scripted run (pushes, hot pulls, replica
    designation, live 2 -> 3 split, more pushes) and return per-push
    latencies past the warmup."""
    fleet = _Fleet(workdir, run)
    samples = []
    try:
        for seq in range(SPLIT_AT):
            ids, grads = schedule[seq]
            t0 = time.monotonic()
            fleet.push(ids, grads)
            if seq >= WARMUP:
                samples.append(time.monotonic() - t0)
        for _ in range(4):
            fleet.pull(hot)  # hot signal for replica designation
        fleet.controller.update_replicas()
        fleet.controller.split(0, new_addr=fleet.add_shard())
        for seq in range(SPLIT_AT, PUSHES):
            ids, grads = schedule[seq]
            t0 = time.monotonic()
            fleet.push(ids, grads)
            samples.append(time.monotonic() - t0)
    finally:
        fleet.stop()
    return samples


def _measure_pair(workdir: str, attempt: int, hot, schedule):
    """One baseline run (attribution off) + one attributed run, same
    schedule. Returns (p99_off, p99_on, usage snapshot gates' raw
    registry snapshot is taken by the caller)."""
    from elasticdl_tpu.observability import principal

    prev = principal.set_enabled(False)
    try:
        off = _run_once(workdir, f"baseline{attempt}", hot, schedule)
    finally:
        principal.set_enabled(prev)

    principal.set_enabled(True)
    # Process-wide default, not a thread-local push: the remote
    # engine fans pushes out on worker threads, and only the process
    # default reaches them — the same mechanism real workers use
    # (ELASTICDL_JOB_NAME in worker/main.py).
    principal.set_process_principal(job="drill", component="worker",
                                    purpose="training")
    try:
        on = _run_once(workdir, f"attributed{attempt}", hot, schedule)
    finally:
        principal.set_process_principal()
    return (float(np.percentile(off, 99)),
            float(np.percentile(on, 99)))


def _series_by_method(snapshot: dict, family: str):
    """{method: sorted purposes seen}, plus total value per method."""
    purposes = {}
    totals = {}
    for fam in snapshot.get("families", []):
        if fam.get("name") != family:
            continue
        names = fam.get("labelnames", [])
        for series in fam.get("series", []):
            labels = dict(zip(names, series.get("labels", [])))
            method = labels.get("method", "")
            purposes.setdefault(method, set()).add(
                labels.get("purpose", "")
            )
            totals[method] = totals.get(method, 0.0) + float(
                series.get("value", 0.0)
            )
    return (
        {m: sorted(v) for m, v in purposes.items()},
        totals,
    )


def _purity_gate(snapshot: dict) -> dict:
    """Migration and replica-refresh bytes live ONLY under their own
    purposes — and both actually flowed."""
    purposes, totals = _series_by_method(
        snapshot, "edl_tpu_usage_bytes_total"
    )
    problems = []
    for method, want in (("ingest_rows", "migration"),
                         ("replica_refresh", "replica_refresh")):
        seen = purposes.get(method, [])
        if seen != [want]:
            problems.append(
                f"{method} bytes metered under purposes {seen}, "
                f"want only ['{want}']"
            )
        if totals.get(method, 0.0) <= 0:
            problems.append(f"no {method} bytes flowed — the drill "
                            "did not exercise that path")
    return {
        "purposes_by_method": purposes,
        "bytes_by_method": totals,
        "problems": problems,
        "ok": not problems,
    }


def run_drill(workdir: str, seed: int) -> dict:
    from elasticdl_tpu.observability.registry import default_registry
    from elasticdl_tpu.observability.usage import summarize_usage

    hot, schedule = _schedule(seed)
    report = {
        "drill": "workload_attribution",
        "seed": seed,
        "config": {
            "table": TABLE, "dim": DIM, "pushes": PUSHES,
            "push_ids": PUSH_IDS, "id_space": ID_SPACE,
            "split_at": SPLIT_AT, "hot_ids": HOT_IDS,
            "warmup": WARMUP,
        },
        "problems": [],
    }

    # Latency gate: re-measure the whole pair once before failing —
    # a single noisy p99 on a shared box must not flunk the drill.
    attempts = []
    ok = False
    for attempt in range(LATENCY_ATTEMPTS):
        p99_off, p99_on = _measure_pair(workdir, attempt, hot,
                                        schedule)
        ratio = p99_on / p99_off if p99_off > 0 else float("inf")
        attempts.append({
            "p99_baseline_s": p99_off,
            "p99_attributed_s": p99_on,
            "ratio": ratio,
        })
        logger.info(
            "attempt %d: p99 off %.3fms on %.3fms ratio %.3f "
            "(gate %.2f)", attempt, 1e3 * p99_off, 1e3 * p99_on,
            ratio, P99_GATE,
        )
        if ratio <= P99_GATE:
            ok = True
            break
    report["latency"] = {
        "attempts": attempts, "gate": P99_GATE, "ok": ok,
    }
    if not ok:
        report["problems"].append(
            f"attributed p99 exceeded {P99_GATE}x baseline in all "
            f"{LATENCY_ATTEMPTS} attempts: "
            f"{[round(a['ratio'], 3) for a in attempts]}"
        )

    # Purity + coverage gates over the process-wide registry (all
    # this drill's shards share it; counters are cumulative across
    # attempts, which only adds more of the same traffic).
    snapshot = default_registry().snapshot()
    purity = _purity_gate(snapshot)
    report["purity"] = purity
    report["problems"].extend(purity["problems"])

    usage = summarize_usage({"proc": snapshot}, top_k=5)
    share = float(usage.get("attributed_handler_share", 0.0))
    report["attribution"] = {
        "attributed_handler_share": share,
        "gate": SHARE_GATE,
        "ok": share >= SHARE_GATE,
    }
    if share < SHARE_GATE:
        report["problems"].append(
            f"only {share:.3f} of handler time attributed "
            f"(gate {SHARE_GATE})"
        )
    report["usage"] = usage
    report["passed"] = not report["problems"]
    return report


def main(argv=None) -> int:
    parser = argparse.ArgumentParser("elasticdl_tpu-usage-drill")
    parser.add_argument("--seed", type=int, default=7)
    parser.add_argument("--workdir", required=True)
    parser.add_argument("--report", default="USAGE_DRILL.json")
    args = parser.parse_args(argv)

    report = run_drill(args.workdir, args.seed)
    with open(args.report, "w") as fh:
        json.dump(report, fh, indent=2, sort_keys=True, default=str)
        fh.write("\n")
    logger.info(
        "usage drill: %s (share %.3f, p99 ratio %.3f); report %s",
        "PASS" if report["passed"] else "FAIL",
        report["attribution"]["attributed_handler_share"],
        report["latency"]["attempts"][-1]["ratio"],
        args.report,
    )
    return 0 if report["passed"] else 1


if __name__ == "__main__":
    sys.exit(main())
