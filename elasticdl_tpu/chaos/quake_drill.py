"""Zero-RPO quake drill: SIGKILL real row-service processes mid-push-
storm and require that no acked push is ever lost.

``make quake-smoke`` (docs/fault_tolerance.md "Zero-RPO row plane"):

1. **Shard quake** — a REAL 2-shard row-service fleet (subprocesses
   over localhost gRPC) with checkpoints + the write-ahead push log
   (``storage/pushlog.py``, durable acks) takes a seeded push storm;
   one shard is SIGKILLed mid-storm and relaunched. The client simply
   keeps pushing (bounded retries) — **no external replay of acked
   pushes** — and the final fleet state must be **byte-equal** (rows,
   optimizer slots, Adam step counters) to a fault-free twin driven by
   the same schedule: acked-push RPO = 0, recovered from
   restore-chain + WAL-tail replay alone. The dead incarnation's log
   is fsck'd (``tools/check_pushlog.py``) before the relaunch touches
   it.
2. **Durable-ack overhead** — the price of zero RPO, measured: the
   same storm against a no-log shard vs a durable-ack shard at the
   default group-commit window, interleaved windows, gate
   **p99 push ≤ 1.5x** the no-log baseline.
3. **Composed quake** — the multi-plane kill: a journaled master
   (primary + warm standby, the failover drill's real processes) runs
   a task schedule while the row fleet live-splits 2→3; the migration
   SOURCE self-SIGKILLs mid-copy (chunk-hook) and the drill SIGKILLs
   the PRIMARY MASTER in the same window. Recovery is three
   independent mechanisms converging at once: the standby fences and
   takes over (worker rides out, exactly-once accounting holds), the
   relaunched source restores chain + replays its WAL, and a fresh
   authority ``resume()``s the migration from its state file. Gates:
   the job drains with exactly the scheduled records trained, every
   shard converges to ONE map epoch, no row lost or double-homed, and
   the row fleet lands byte-equal to a kill-free twin that ran the
   same storm + split.

Contract note (docs/chaos.md): pre-WAL drills re-drove lost pushes
externally after a kill — modeling a trainer retrying *unacked* work.
This drill is the stronger claim and never re-drives: once the push
log acks a write, only the dead process's own recovery may produce
it again.
"""

import argparse
import json
import os
import signal
import socket
import subprocess
import sys
import threading
import time
from typing import Dict, List, Optional

import numpy as np

from elasticdl_tpu.common.log_utils import get_logger

logger = get_logger("quake_drill")

TABLE = "quake_rows"
DIM = 16
# Spans the full 8192-bucket shard-map space (id % NUM_BUCKETS), so a
# bootstrap 2-shard map actually splits the storm across the fleet.
VOCAB = 120_000
PUSH_IDS = 48
SEED = 11
STORM_PUSHES = 240
KILL_AT_ACK = 90
CHECKPOINT_STEPS = 40
COMPOSED_PUSHES = 160
COMPOSED_SPLIT_AT = 80
BENCH_PUSHES = 480       # per window per mode (p99 needs samples)
BENCH_THREADS = 4
BENCH_WINDOWS = 3        # window 0 is warmup, gates on the rest
MAX_DURABLE_P99_RATIO = 1.5


def _schedule(seed: int, pushes: int):
    rng = np.random.RandomState(seed)
    out = []
    for _ in range(pushes):
        ids = np.unique(
            rng.randint(0, VOCAB, PUSH_IDS)
        ).astype(np.int64)
        out.append((ids, rng.rand(ids.size, DIM).astype(np.float32)))
    return out


def _pkg_root() -> str:
    return os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__)
    )))


def _free_ports(n: int) -> List[int]:
    ports, socks = [], []
    for _ in range(n):
        s = socket.socket()
        s.bind(("localhost", 0))
        ports.append(s.getsockname()[1])
        socks.append(s)
    for s in socks:
        s.close()
    return ports


# ---- `serve` subcommand: one real row-service shard ----------------------


def _serve(args) -> int:
    from elasticdl_tpu.comm.rpc import RpcServer
    from elasticdl_tpu.embedding import row_service as rs_mod
    from elasticdl_tpu.embedding.optimizer import SGD, Adam
    from elasticdl_tpu.embedding.row_service import (
        SERVICE_NAME,
        HostRowService,
    )
    from elasticdl_tpu.native.row_store import (
        make_host_optimizer,
        make_host_table,
    )

    # SGD is for drills whose byte-equality gate compares runs with
    # DIFFERENT apply interleavings (stream_drill.py): Adam's per-table
    # step counter makes row state order-dependent even when every row
    # sees exactly one update.
    opt = (SGD(lr=0.01) if getattr(args, "optimizer", "adam") == "sgd"
           else Adam(lr=0.01))
    svc = HostRowService(
        {TABLE: make_host_table(TABLE, DIM)},
        make_host_optimizer(opt),
    )
    if args.checkpoint_dir:
        svc.configure_checkpoint(
            args.checkpoint_dir, checkpoint_steps=args.checkpoint_steps,
            delta_chain_max=3,
        )
    if args.push_log_dir:
        svc.configure_push_log(
            args.push_log_dir, group_ms=args.push_log_group_ms,
            ack=args.push_log_ack,
        )
    if args.die_after_migrate_chunks > 0:
        # The composed scenario's deterministic kill point: the REAL
        # process SIGKILLs itself after N migrated chunks landed on
        # the target — mid-copy, rows in flight, WAL mid-truncation
        # cycle.
        state = {"n": 0}

        def _die(_svc, _mig, _view, _chunk):
            state["n"] += 1
            if state["n"] >= args.die_after_migrate_chunks:
                os.kill(os.getpid(), signal.SIGKILL)

        rs_mod.set_reshard_chaos_hooks(mid_migrate=_die)

    def _capture(_request: dict) -> dict:
        out = {}
        for name, view in svc.host_tables.items():
            if name == rs_mod.SEQS_TABLE_NAME:
                # Client-id bookkeeping, keyed by which incarnation
                # pushed — not comparable row state.
                continue
            ids, rows = view.to_arrays()
            out[name] = {
                "ids": np.asarray(ids, np.int64),
                "rows": np.asarray(rows),
            }
        return {"tables": out, "push_count": svc._push_count}

    handlers = dict(svc.handlers())
    handlers["drill_capture"] = _capture
    handlers["ping"] = lambda _req: {"ok": True, "pid": os.getpid()}
    server = RpcServer(
        f"localhost:{args.port}", {SERVICE_NAME: handlers},
        tag=f"rowservice/{args.shard_id}",
    ).start()
    svc._server = server
    logger.info("quake shard %d serving on %d (pid %d)",
                args.shard_id, server.port, os.getpid())
    server.wait()
    return 0


# ---- driver: shard fleet management ---------------------------------------


class RowFleet:
    """Spawn/kill/relaunch the drill's real row-service processes."""

    def __init__(self, workdir: str):
        self.workdir = workdir
        os.makedirs(workdir, exist_ok=True)
        self.procs: Dict[int, subprocess.Popen] = {}
        self.cmds: Dict[int, List[str]] = {}
        self._logs = []

    def spawn(self, shard: int, port: int, checkpoint_dir: str = "",
              push_log_dir: str = "", ack: str = "durable",
              group_ms: float = 2.0,
              die_after_migrate_chunks: int = 0,
              checkpoint_steps: int = CHECKPOINT_STEPS,
              optimizer: str = "adam",
              ) -> subprocess.Popen:
        cmd = [
            sys.executable, "-m", "elasticdl_tpu.chaos.quake_drill",
            "serve", "--port", str(port), "--shard_id", str(shard),
            "--checkpoint_steps", str(checkpoint_steps),
            "--push_log_group_ms", str(group_ms),
            "--push_log_ack", ack,
            "--optimizer", optimizer,
        ]
        if checkpoint_dir:
            cmd += ["--checkpoint_dir", checkpoint_dir]
        if push_log_dir:
            cmd += ["--push_log_dir", push_log_dir]
        # The relaunch re-runs the identical command MINUS the death
        # hook — a pod restart does not inherit the fault injector —
        # so snapshot the command BEFORE appending the flag pair.
        self.cmds[shard] = list(cmd)
        if die_after_migrate_chunks:
            cmd += ["--die_after_migrate_chunks",
                    str(die_after_migrate_chunks)]
        log = open(os.path.join(
            self.workdir, f"shard{shard}-{port}-{len(self._logs)}.log"
        ), "w")
        self._logs.append(log)
        proc = subprocess.Popen(
            cmd, env=dict(os.environ, JAX_PLATFORMS="cpu"),
            cwd=_pkg_root(), stdout=log, stderr=subprocess.STDOUT,
        )
        self.procs[shard] = proc
        return proc

    def relaunch(self, shard: int) -> subprocess.Popen:
        log = open(os.path.join(
            self.workdir, f"shard{shard}-relaunch-{len(self._logs)}.log"
        ), "w")
        self._logs.append(log)
        proc = subprocess.Popen(
            self.cmds[shard], env=dict(os.environ, JAX_PLATFORMS="cpu"),
            cwd=_pkg_root(), stdout=log, stderr=subprocess.STDOUT,
        )
        self.procs[shard] = proc
        return proc

    def sigkill(self, shard: int):
        proc = self.procs[shard]
        os.kill(proc.pid, signal.SIGKILL)
        proc.wait(timeout=30)

    def stop_all(self):
        for proc in self.procs.values():
            if proc.poll() is None:
                try:
                    os.kill(proc.pid, signal.SIGKILL)
                except OSError:
                    pass
        for proc in self.procs.values():
            try:
                proc.wait(timeout=10)
            except Exception:
                pass
        for log in self._logs:
            log.close()


def _call_shard(port: int, method: str, timeout: float = 10.0,
                **fields) -> dict:
    from elasticdl_tpu.comm.rpc import RpcStub
    from elasticdl_tpu.embedding.row_service import SERVICE_NAME

    stub = RpcStub(f"localhost:{port}", SERVICE_NAME, max_retries=0)
    try:
        return stub.call(method, timeout=timeout, **fields)
    finally:
        stub.close()


def _wait_shard(port: int, deadline_secs: float = 90.0):
    t0 = time.monotonic()
    last = None
    while time.monotonic() - t0 < deadline_secs:
        try:
            return _call_shard(port, "ping", timeout=2.0)
        except Exception as exc:
            last = exc
            time.sleep(0.1)
    raise TimeoutError(f"shard on port {port} never served: {last}")


def _capture_shard(port: int) -> dict:
    resp = _call_shard(port, "drill_capture", timeout=60.0)
    return resp


def _make_engine(ports: List[int]):
    from elasticdl_tpu.embedding.row_service import make_remote_engine

    return make_remote_engine(
        ",".join(f"localhost:{p}" for p in ports), {},
        retries=20, backoff_secs=0.25,
    )


def _tables_equal(a: dict, b: dict, where: str) -> List[str]:
    problems = []
    for name in sorted(set(a) | set(b)):
        if name not in a or name not in b:
            problems.append(f"{where}: view {name} present on one "
                            "side only")
            continue
        ids_a = np.asarray(a[name]["ids"], np.int64)
        ids_b = np.asarray(b[name]["ids"], np.int64)
        order_a, order_b = np.argsort(ids_a), np.argsort(ids_b)
        if not np.array_equal(ids_a[order_a], ids_b[order_b]):
            problems.append(
                f"{where}: {name} id sets differ "
                f"({ids_a.size} vs {ids_b.size})"
            )
            continue
        rows_a = np.asarray(a[name]["rows"])[order_a]
        rows_b = np.asarray(b[name]["rows"])[order_b]
        if not np.array_equal(
            rows_a.astype(np.float64), rows_b.astype(np.float64)
        ):
            problems.append(f"{where}: {name} row bytes differ")
    return problems


def _fsck_log(log_dir: str, checkpoint_dir: Optional[str] = None
              ) -> dict:
    sys.path.insert(0, os.path.join(_pkg_root(), "tools"))
    from check_pushlog import check_one_log

    errors, report = check_one_log(log_dir, checkpoint_dir)
    return {"errors": errors, "records": report["records"],
            "torn_tail": report["torn_tail"]}


# ---- scenario 1: shard quake ----------------------------------------------


def _run_quake_fleet(workdir: str, schedule, kill: bool) -> dict:
    fleet = RowFleet(workdir)
    ports = _free_ports(2)
    dirs = {}
    result = {"problems": [], "dead_log_fsck": None}
    for shard, port in enumerate(ports):
        ckpt = os.path.join(workdir, f"s{shard}", "ckpt")
        wal = os.path.join(workdir, f"s{shard}", "pushlog")
        dirs[shard] = (ckpt, wal)
        fleet.spawn(shard, port, checkpoint_dir=ckpt,
                    push_log_dir=wal, ack="durable")
    try:
        for port in ports:
            _wait_shard(port)
        engine = _make_engine(ports)
        table = engine.tables[TABLE]
        acked = 0
        for ids, grads in schedule:
            engine.optimizer.apply_gradients(table, ids, grads)
            acked += 1
            if kill and acked == KILL_AT_ACK:
                # SIGKILL shard 0 mid-storm: queued group commits die
                # with it; every *acked* push is already on disk
                # (durable ack). The dead incarnation's log must fsck
                # clean BEFORE the relaunch appends to it.
                fleet.sigkill(0)
                result["dead_log_fsck"] = _fsck_log(
                    dirs[0][1], dirs[0][0]
                )
                result["killed_at_ack"] = acked
                fleet.relaunch(0)
                # No waiting, no external replay: the next push's
                # bounded retries ride out the relaunch.
        result["acked"] = acked
        states = {
            shard: _capture_shard(port)
            for shard, port in enumerate(ports)
        }
        result["states"] = states
        result["push_counts"] = {
            s: int(st["push_count"]) for s, st in states.items()
        }
    finally:
        fleet.stop_all()
    return result


def scenario_shard_quake(workdir: str) -> dict:
    schedule = _schedule(SEED, STORM_PUSHES)
    result = {"scenario": "shard_quake", "passed": False,
              "problems": [], "config": {
                  "pushes": STORM_PUSHES, "kill_at_ack": KILL_AT_ACK,
                  "checkpoint_steps": CHECKPOINT_STEPS,
                  "ack": "durable",
              }}
    twin = _run_quake_fleet(
        os.path.join(workdir, "quake", "twin"), schedule, kill=False
    )
    result["problems"] += [f"twin: {p}" for p in twin["problems"]]
    faulted = _run_quake_fleet(
        os.path.join(workdir, "quake", "faulted"), schedule, kill=True
    )
    result["problems"] += [f"faulted: {p}" for p in faulted["problems"]]
    fsck = faulted.get("dead_log_fsck")
    result["dead_log_fsck"] = fsck
    if fsck is None:
        result["problems"].append("shard 0 was never killed")
    elif fsck["errors"]:
        result["problems"] += [
            f"dead incarnation log fsck: {e}" for e in fsck["errors"]
        ]
    for shard in (0, 1):
        result["problems"] += _tables_equal(
            twin["states"][shard]["tables"],
            faulted["states"][shard]["tables"],
            f"shard {shard} vs twin",
        )
    result["push_counts"] = {
        "twin": twin.get("push_counts"),
        "faulted": faulted.get("push_counts"),
    }
    if twin.get("push_counts") != faulted.get("push_counts"):
        result["problems"].append(
            "per-shard push counts diverged from the twin "
            f"({faulted.get('push_counts')} vs "
            f"{twin.get('push_counts')}) — lost or duplicated applies"
        )
    result["rpo_zero"] = not any(
        p for p in result["problems"] if "vs twin" in p
        or "push counts" in p
    )
    result["passed"] = not result["problems"]
    return result


# ---- scenario 2: durable-ack overhead -------------------------------------


def _bench_storm(engine, seed: int) -> List[float]:
    """One window of concurrent pushes; returns per-push seconds.
    The engine is reused across windows — fresh gRPC channels per
    window would charge connection setup to whichever mode ran
    first."""
    table = engine.tables[TABLE]
    latencies: List[float] = []
    lock = threading.Lock()
    errors: List[BaseException] = []

    def pusher(tid: int):
        rng = np.random.RandomState(seed * 97 + tid)
        mine = []
        try:
            for _ in range(BENCH_PUSHES // BENCH_THREADS):
                ids = np.unique(
                    rng.randint(0, VOCAB, PUSH_IDS)
                ).astype(np.int64)
                grads = rng.rand(ids.size, DIM).astype(np.float32)
                t0 = time.monotonic()
                engine.optimizer.apply_gradients(table, ids, grads)
                mine.append(time.monotonic() - t0)
        except BaseException as exc:
            errors.append(exc)
        with lock:
            latencies.extend(mine)

    threads = [
        threading.Thread(target=pusher, args=(tid,), daemon=True)
        for tid in range(BENCH_THREADS)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=120.0)
    if errors:
        raise errors[0]
    return latencies


def _fsync_profile(where: str, n: int = 120) -> dict:
    """The medium's raw fsync distribution — what a durable ack
    fundamentally pays per group commit."""
    import tempfile

    os.makedirs(where, exist_ok=True)
    fd, path = tempfile.mkstemp(dir=where)
    lats = []
    try:
        with os.fdopen(fd, "wb") as fh:
            for _ in range(n):
                fh.write(b"x" * 4096)
                fh.flush()
                t0 = time.monotonic()
                os.fsync(fh.fileno())
                lats.append(time.monotonic() - t0)
    finally:
        try:
            os.remove(path)
        except OSError:
            pass
    lats.sort()
    return {
        "p50_ms": round(1e3 * lats[len(lats) // 2], 3),
        "p99_ms": round(
            1e3 * lats[min(len(lats) - 1, int(0.99 * len(lats)))], 3
        ),
        "max_ms": round(1e3 * lats[-1], 3),
    }


# A medium whose own fsync p99 exceeds this is pathological (CI
# overlayfs: p50 ~2ms, p99 >50ms, max >400ms measured) — the bench
# would gate the disk, not the group-commit mechanism. Real NVMe
# deployment media fsync sub-millisecond.
FSYNC_SANE_P99_MS = 10.0


def scenario_durable_overhead(workdir: str) -> dict:
    """p99 push with the WAL in durable-ack mode vs a no-log shard at
    the default group window — interleaved measurement windows so the
    box's slow drift charges both modes equally; window 0 is warmup.

    The gate prices the MECHANISM (framing + enqueue + group wait +
    write/sync syscalls + the handler's durability rendezvous), so
    when the bench medium's own fsync tail is pathological (container
    overlayfs) the WAL moves to tmpfs and the report says so — the
    shard-quake scenario still proves recovery against real disk."""
    result = {"scenario": "durable_overhead", "passed": False,
              "problems": [], "config": {
                  "threads": BENCH_THREADS,
                  "pushes_per_window": BENCH_PUSHES,
                  "windows": BENCH_WINDOWS,
                  "group_ms": 2.0,
                  "max_p99_ratio": MAX_DURABLE_P99_RATIO,
              }}
    wal_dir = os.path.join(workdir, "bench", "wal")
    profile = _fsync_profile(os.path.join(workdir, "bench"))
    result["fsync_medium"] = {"workdir": profile}
    medium = "workdir"
    if profile["p99_ms"] > FSYNC_SANE_P99_MS and os.path.isdir(
        "/dev/shm"
    ):
        wal_dir = os.path.join(
            "/dev/shm", f"edl_quake_wal_{os.getpid()}"
        )
        result["fsync_medium"]["tmpfs"] = _fsync_profile(wal_dir)
        medium = "tmpfs"
    result["wal_medium"] = medium
    fleet = RowFleet(os.path.join(workdir, "bench"))
    ports = _free_ports(2)
    fleet.spawn(0, ports[0])  # no log, no checkpoint: the baseline
    fleet.spawn(1, ports[1], push_log_dir=wal_dir, ack="durable")
    p99s = {"nolog": [], "durable": []}
    try:
        for port in ports:
            _wait_shard(port)
        engines = {
            "nolog": _make_engine([ports[0]]),
            "durable": _make_engine([ports[1]]),
        }
        for window in range(BENCH_WINDOWS + 1):
            for mode in ("nolog", "durable"):
                lats = _bench_storm(engines[mode], seed=SEED + window)
                if window == 0:
                    continue  # warmup: first pushes pay lazy init
                lats.sort()
                p99s[mode].append(
                    lats[min(len(lats) - 1,
                             int(0.99 * len(lats)))]
                )
    finally:
        fleet.stop_all()
        if medium == "tmpfs":
            import shutil

            shutil.rmtree(wal_dir, ignore_errors=True)
    med = {
        mode: sorted(vals)[len(vals) // 2]
        for mode, vals in p99s.items()
    }
    ratio = med["durable"] / med["nolog"] if med["nolog"] else None
    result["p99_secs"] = {
        mode: [round(v, 5) for v in vals]
        for mode, vals in p99s.items()
    }
    result["p99_median_secs"] = {
        mode: round(v, 5) for mode, v in med.items()
    }
    result["p99_ratio"] = round(ratio, 3) if ratio else None
    if ratio is None or ratio > MAX_DURABLE_P99_RATIO:
        result["problems"].append(
            f"durable-ack p99 {med['durable'] * 1e3:.2f}ms is "
            f"{ratio:.2f}x the no-log baseline "
            f"{med['nolog'] * 1e3:.2f}ms "
            f"(gate <= {MAX_DURABLE_P99_RATIO}x)"
        )
    result["passed"] = not result["problems"]
    return result


# ---- scenario 3: composed master + shard + migration kill -----------------


def _run_composed_row_side(workdir: str, schedule, kill: bool,
                           result: dict) -> Optional[dict]:
    """The row half of the composed scenario (the master half rides
    the failover drill's real processes in the caller): 2-shard fleet,
    storm phase 1, live 2→3 split (source self-SIGKILLs mid-copy when
    ``kill``), relaunch + fresh-authority resume, storm phase 2.
    Returns captures keyed by shard."""
    from elasticdl_tpu.comm.rpc import RpcStub
    from elasticdl_tpu.embedding.row_service import SERVICE_NAME
    from elasticdl_tpu.master.row_reshard import ShardMapController

    fleet = RowFleet(workdir)
    ports = _free_ports(3)
    addrs = [f"localhost:{p}" for p in ports]
    dirs = {}
    for shard in range(3):
        dirs[shard] = (
            os.path.join(workdir, f"s{shard}", "ckpt"),
            os.path.join(workdir, f"s{shard}", "pushlog"),
        )
    state_path = os.path.join(workdir, "shard_map.json")
    # Non-retrying transports: the drill wants the source's death to
    # surface immediately (the production RideOutTransport would mask
    # it for ~64s before the authority restart path engages).
    transport_factory = lambda addr: RpcStub(  # noqa: E731
        addr, SERVICE_NAME, max_retries=1
    )
    try:
        fleet.spawn(0, ports[0], checkpoint_dir=dirs[0][0],
                    push_log_dir=dirs[0][1],
                    die_after_migrate_chunks=2 if kill else 0)
        fleet.spawn(1, ports[1], checkpoint_dir=dirs[1][0],
                    push_log_dir=dirs[1][1])
        _wait_shard(ports[0])
        _wait_shard(ports[1])
        controller = ShardMapController(
            state_path, transport_factory=transport_factory
        )
        controller.bootstrap(addrs[:2])
        engine = _make_engine(ports[:2])
        table = engine.tables[TABLE]
        for ids, grads in schedule[:COMPOSED_SPLIT_AT]:
            engine.optimizer.apply_gradients(table, ids, grads)
        # The split target comes up fresh (its own checkpoint + WAL).
        fleet.spawn(2, ports[2], checkpoint_dir=dirs[2][0],
                    push_log_dir=dirs[2][1])
        _wait_shard(ports[2])
        if not kill:
            controller.split(0, new_addr=addrs[2])
            controller.close()
        else:
            # The caller boots the master plane NOW (primary +
            # standby + a worker holding a live lease), so the
            # composed kill window opens with the task job mid-
            # flight — not drained minutes earlier while the row
            # fleet was still importing.
            before = result.pop("_before_split", None)
            if before is not None:
                before()
            split_exc: List[BaseException] = []

            def _split():
                try:
                    controller.split(0, new_addr=addrs[2])
                except BaseException as exc:
                    split_exc.append(exc)

            splitter = threading.Thread(target=_split, daemon=True)
            splitter.start()
            # The source self-SIGKILLs after 2 migrated chunks — wait
            # for the REAL death, then the caller kills the master in
            # the same window.
            deadline = time.monotonic() + 60.0
            while (fleet.procs[0].poll() is None
                   and time.monotonic() < deadline):
                time.sleep(0.05)
            if fleet.procs[0].poll() is None:
                result["problems"].append(
                    "composed: source never self-killed mid-copy"
                )
                return None
            kill_cb = result.pop("_on_source_dead", None)
            if kill_cb is not None:
                kill_cb()  # SIGKILL the primary master NOW
            splitter.join(timeout=90.0)
            result["split_interrupted"] = bool(split_exc)
            controller.close()
            # Dead incarnation's WAL must fsck clean before the
            # relaunch appends to it.
            result["dead_log_fsck"] = _fsck_log(
                dirs[0][1], dirs[0][0]
            )
            fleet.relaunch(0)
            _wait_shard(ports[0])
            # A FRESH authority incarnation finishes the move from
            # the persisted state file — the restarted-master path.
            controller2 = ShardMapController(
                state_path, transport_factory=transport_factory
            )
            resumed = controller2.resume()
            result["migration_resumed"] = resumed is not None
            controller2.close()
        for ids, grads in schedule[COMPOSED_SPLIT_AT:]:
            engine.optimizer.apply_gradients(table, ids, grads)
        # Convergence: every shard on ONE epoch.
        epochs = {}
        for shard, port in enumerate(ports):
            resp = _call_shard(port, "get_shard_map")
            m = resp.get("map") or {}
            epochs[shard] = int(m.get("version", -1))
        result.setdefault("epochs", {})[
            "kill" if kill else "twin"
        ] = epochs
        if len(set(epochs.values())) != 1:
            result["problems"].append(
                f"composed ({'kill' if kill else 'twin'}): shards "
                f"did not converge to one epoch: {epochs}"
            )
        return {
            shard: _capture_shard(port)
            for shard, port in enumerate(ports)
        }
    finally:
        fleet.stop_all()


def scenario_composed(workdir: str) -> dict:
    from elasticdl_tpu.chaos.failover_drill import (
        RECORDS,
        Fleet,
        ScriptedWorker,
        _call,
        _wait_serving,
    )

    result = {"scenario": "composed_quake", "passed": False,
              "problems": [], "config": {
                  "pushes": COMPOSED_PUSHES,
                  "split_at": COMPOSED_SPLIT_AT,
                  "task_records": RECORDS,
              }}
    schedule = _schedule(SEED + 1, COMPOSED_PUSHES)

    # Fault-free twin of the ROW side (the master side's twin
    # equivalence is pinned by FAILOVER_DRILL; here the master gates
    # are exactly-once accounting + takeover + fsck).
    twin_states = _run_composed_row_side(
        os.path.join(workdir, "composed", "twin"), schedule,
        kill=False, result=result,
    )
    if twin_states is None:
        return result

    mdir = os.path.join(workdir, "composed", "master")
    os.makedirs(mdir, exist_ok=True)
    mfleet = Fleet(mdir, heartbeat_secs=0.05, miss_threshold=2,
                   poll_secs=0.05)
    mports = _free_ports(2)
    pauses = {"holding_lease": threading.Event()}
    worker = ScriptedWorker(
        ",".join(f"localhost:{p}" for p in mports), pauses
    )
    try:
        def _boot_master_plane():
            # Runs from the row side's pre-split hook: the primary,
            # its warm standby, and a worker HOLDING a live lease all
            # exist the instant the migration starts — so the kill
            # window has every plane mid-flight.
            mfleet.spawn_primary(mports[0])
            _wait_serving(mports[0])
            standby = mfleet.spawn_standby(mports[1], mports[0])
            Fleet.wait_attached(standby)
            worker.start()
            if not worker.reached["holding_lease"].wait(60.0):
                raise TimeoutError(
                    "composed: worker never held a lease"
                )

        def _kill_master():
            # The composed window: the master dies while the row
            # migration's source is ALSO freshly dead and a worker
            # holds a live lease.
            Fleet.sigkill(mfleet.procs[0])
            result["master_killed"] = True
            pauses["holding_lease"].set()

        result["_before_split"] = _boot_master_plane
        result["_on_source_dead"] = _kill_master
        faulted_states = _run_composed_row_side(
            os.path.join(workdir, "composed", "faulted"), schedule,
            kill=True, result=result,
        )
        if faulted_states is None:
            return result
        if not result.get("master_killed"):
            result["problems"].append(
                "composed: master kill callback never fired"
            )
        for shard in range(3):
            result["problems"] += _tables_equal(
                twin_states[shard]["tables"],
                faulted_states[shard]["tables"],
                f"composed shard {shard} vs twin",
            )
        # Row conservation: the primary table's ids must partition
        # across the fleet — no loss, no double-homing.
        def _owned(states):
            per = [
                set(np.asarray(
                    states[s]["tables"][TABLE]["ids"], np.int64
                ).tolist())
                for s in range(3)
            ]
            return per

        twin_owned = _owned(twin_states)
        fault_owned = _owned(faulted_states)
        for a in range(3):
            for b in range(a + 1, 3):
                dup = fault_owned[a] & fault_owned[b]
                if dup:
                    result["problems"].append(
                        f"composed: {len(dup)} row id(s) double-"
                        f"homed on shards {a} and {b}"
                    )
        if set().union(*fault_owned) != set().union(*twin_owned):
            result["problems"].append(
                "composed: surviving row id set differs from twin "
                "(rows lost across the multi-plane kill)"
            )
        # Master-plane gates: the job drained exactly once under the
        # promoted standby.
        worker.join(timeout=240.0)
        if worker.is_alive():
            result["problems"].append(
                "composed: worker never drained the task job after "
                "the takeover"
            )
        elif worker.error is not None:
            result["problems"].append(
                f"composed: worker error: {worker.error!r}"
            )
        else:
            result["trained_records"] = int(worker.trained_records)
            if worker.trained_records != RECORDS:
                result["problems"].append(
                    f"composed: trained {worker.trained_records} "
                    f"records, expected exactly {RECORDS} (task "
                    "loss or duplication across the takeover)"
                )
            final = _call(mports[1], "drill_export")
            result["promoted_generation"] = int(
                final.get("generation", -1)
            )
            if result["promoted_generation"] < 1:
                result["problems"].append(
                    "composed: standby never opened a new generation"
                )
        sys.path.insert(0, os.path.join(_pkg_root(), "tools"))
        from check_journal import check_journal

        journal_errors = check_journal(mfleet.journal_dir)
        result["journal_fsck"] = journal_errors
        result["problems"] += [
            f"composed journal fsck: {e}" for e in journal_errors
        ]
        fsck = result.get("dead_log_fsck")
        if fsck and fsck["errors"]:
            result["problems"] += [
                f"composed dead-source log fsck: {e}"
                for e in fsck["errors"]
            ]
    finally:
        result.pop("_on_source_dead", None)
        result.pop("_before_split", None)
        mfleet.stop_all()
    result["passed"] = not result["problems"]
    return result


# ---- report + gates --------------------------------------------------------


def run_drill(workdir: str) -> dict:
    os.makedirs(workdir, exist_ok=True)
    scenarios = []
    logger.info("quake drill: shard quake (real processes)")
    scenarios.append(scenario_shard_quake(workdir))
    logger.info("quake drill: durable-ack overhead bench")
    scenarios.append(scenario_durable_overhead(workdir))
    logger.info("quake drill: composed master+shard+migration kill")
    scenarios.append(scenario_composed(workdir))
    for s in scenarios:
        # Captured table payloads are for comparison, not the report.
        s.pop("states", None)
    return {
        "drill": "zero_rpo_quake",
        "seed": SEED,
        "config": {
            "table": TABLE, "dim": DIM, "vocab": VOCAB,
            "push_ids": PUSH_IDS,
        },
        "scenarios": scenarios,
        "passed": all(s["passed"] for s in scenarios),
    }


def main(argv=None) -> int:
    parser = argparse.ArgumentParser("elasticdl_tpu-quake-drill")
    sub = parser.add_subparsers(dest="command", required=True)
    serve = sub.add_parser("serve")
    serve.add_argument("--port", type=int, required=True)
    serve.add_argument("--shard_id", type=int, default=0)
    serve.add_argument("--checkpoint_dir", default="")
    serve.add_argument("--checkpoint_steps", type=int,
                       default=CHECKPOINT_STEPS)
    serve.add_argument("--push_log_dir", default="")
    serve.add_argument("--push_log_group_ms", type=float, default=2.0)
    serve.add_argument("--push_log_ack", default="durable",
                       choices=["durable", "applied"])
    serve.add_argument("--die_after_migrate_chunks", type=int,
                       default=0)
    serve.add_argument("--optimizer", default="adam",
                       choices=["adam", "sgd"])

    run = sub.add_parser("run")
    run.add_argument("--workdir", required=True)
    run.add_argument("--report", default="QUAKE_DRILL.json")
    args = parser.parse_args(argv)

    if args.command == "serve":
        return _serve(args)

    report = run_drill(args.workdir)
    with open(args.report, "w") as fh:
        json.dump(report, fh, indent=2, sort_keys=True, default=str)
        fh.write("\n")
    for scenario in report["scenarios"]:
        logger.info(
            "quake drill %s: %s%s", scenario["scenario"],
            "PASS" if scenario["passed"] else "FAIL",
            "" if scenario["passed"]
            else f" ({'; '.join(map(str, scenario['problems']))})",
        )
    logger.info(
        "quake drill: %s; report %s",
        "PASS" if report["passed"] else "FAIL", args.report,
    )
    return 0 if report["passed"] else 1


if __name__ == "__main__":
    sys.exit(main())
