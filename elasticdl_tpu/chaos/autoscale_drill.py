"""Autoscale chaos drill: grow + shrink + a worker kill mid-barrier.

The adversarial proof behind the closed-loop autoscaler
(master/autoscaler.py + the live-reshard barrier in
master/servicer.py / parallel/reshard.py): a job that scales DOWN
mid-training (dp4 → dp2, checkpointless live reshard), scales back UP
(dp2 → dp4), and loses its worker to a hard kill while the grow
barrier is pending — adjudicated against a **checkpoint-restart
control twin** that walks the IDENTICAL mesh schedule (same shrink
point, same trained-but-unreported kill, same restore version) through
the old save → teardown → restore path:

- **loss-trajectory equivalence vs the control**: final version,
  final loss, and every dense leaf (params, optimizer state,
  batch_stats) match. Both runs execute the same step programs on the
  same meshes in the same order, so this is a near-bit comparison —
  live reshard must leave exactly the trace checkpoint-restart leaves,
  minus the disk. (A never-resized twin is NOT a usable control: this
  model trains in bfloat16, and the different gradient-reduction
  orders of dp4 vs dp2 amplify chaotically — the same reason the
  checkpoint-restart resize tests compare value preservation, not
  cross-mesh trajectories.)
- **exactly-once accounting**: every record counted complete exactly
  once — the killed worker's in-flight task re-queues once, the
  resharded state neither loses nor repeats a step;
- **barrier liveness**: both resize barriers complete; the one the
  kill interrupted completes through the replacement worker (which
  sees the still-pending directive on its FIRST get_task, applies it
  pre-init, and acks under its own id while the drill's tick drops the
  dead worker from the membership — exactly what the master run-loop
  tick does in production).

The kill lands where it hurts: AFTER the grow directive is issued,
BEFORE the worker can see or ack it, with a trained-but-unreported
task in `doing` and the newest checkpoint deliberately one task
boundary behind (checkpoint cadence = 2 tasks), so recovery must
combine checkpoint restore + task re-queue + barrier re-offer.

Deterministic by construction (single worker, sync checkpoint writes,
in-process master, fixed kill/resize report counts); wall-clock
timings are excluded from the default report.

``make autoscale-smoke`` runs this; the fast-lane equivalent lives in
tests/test_autoscale.py.
"""

import json
import os
from typing import List, Optional

import numpy as np

from elasticdl_tpu.chaos.interceptors import ChaosKill
from elasticdl_tpu.common.constants import TaskType
from elasticdl_tpu.common.log_utils import get_logger

logger = get_logger("autoscale_drill")

REPORT_VERSION = 1
DEFAULT_REPORT = "AUTOSCALE_DRILL.json"
MODEL_DEF = "mnist.mnist_functional.custom_model"

# Cross-mesh tolerance: dp4 and dp2 reduce gradients in different
# orders — the same rtol the checkpoint-restart resize equivalence
# tests use (tests/test_elastic_mesh_resize.py).
RTOL = 1e-4
ATOL = 1e-5


class DrillError(RuntimeError):
    pass


def _final_summary(worker) -> dict:
    import jax

    from elasticdl_tpu.checkpoint import named_leaves_from_state

    leaves = {}
    if worker.state is not None:
        leaves = jax.device_get(named_leaves_from_state(worker.state))
    return {
        "final_version": (
            int(worker.state.step) if worker.state is not None else 0
        ),
        "final_loss": (
            float(worker.last_metrics["loss"])
            if worker.last_metrics is not None else None
        ),
        "leaves": leaves,
    }


def _equivalence_verdict(control: dict, run: dict) -> dict:
    problems: List[str] = []
    if run["final_version"] != control["final_version"]:
        problems.append(
            f"final version {run['final_version']} != control "
            f"{control['final_version']} (training lost or repeated)"
        )
    t_loss, r_loss = control.get("final_loss"), run.get("final_loss")
    if (t_loss is None) != (r_loss is None):
        problems.append(
            f"final loss presence differs (control={t_loss}, "
            f"run={r_loss})"
        )
    elif t_loss is not None and not np.isclose(
        r_loss, t_loss, rtol=RTOL, atol=ATOL
    ):
        problems.append(f"final loss {r_loss!r} != control {t_loss!r}")
    t_leaves = control.get("leaves", {})
    r_leaves = run.get("leaves", {})
    if set(t_leaves) != set(r_leaves):
        problems.append("dense leaf sets differ")
    else:
        for name, arr in t_leaves.items():
            if not np.allclose(
                np.asarray(r_leaves[name], np.float64),
                np.asarray(arr, np.float64),
                rtol=RTOL, atol=ATOL,
            ):
                problems.append(f"dense leaves diverged at {name!r}")
                break
    return {
        "name": "loss_trajectory_equivalence",
        "passed": not problems,
        "details": (
            "; ".join(problems) if problems else
            f"version {run['final_version']} and {len(r_leaves)} dense "
            "leaves match the checkpoint-restart control"
        ),
    }


def run_drill(
    workdir: str,
    records: int = 256,
    minibatch_size: int = 8,
    num_minibatches_per_task: int = 2,
    shrink_at_report: int = 2,
    grow_kill_at_report: int = 5,
    join_timeout: float = 300.0,
) -> dict:
    """Twin run, then the autoscaled run with a kill mid-barrier."""
    import jax

    from elasticdl_tpu.chaos.invariants import ExactlyOnceTaskAccounting
    from elasticdl_tpu.checkpoint import CheckpointHook
    from elasticdl_tpu.core.model_spec import get_model_spec
    from elasticdl_tpu.parallel import reshard
    from elasticdl_tpu.parallel.mesh import make_mesh
    from elasticdl_tpu.parallel.mesh_runner import make_runner_for_spec
    from elasticdl_tpu.testing.cluster import MiniCluster
    from elasticdl_tpu.testing.data import (
        create_mnist_record_file,
        model_zoo_dir,
    )
    from elasticdl_tpu.worker.worker import Worker

    if len(jax.devices()) < 4:
        raise DrillError(
            "autoscale drill needs >=4 devices (run under "
            "xla_force_host_platform_device_count)"
        )
    os.makedirs(workdir, exist_ok=True)
    train = create_mnist_record_file(
        os.path.join(workdir, "train.rec"), records, seed=11
    )
    mesh4 = lambda: make_mesh(  # noqa: E731
        (4,), ("dp",), devices=jax.devices()[:4]
    )
    mesh2 = lambda: make_mesh(  # noqa: E731
        (2,), ("dp",), devices=jax.devices()[:2]
    )
    # Checkpoint every SECOND task on purpose: the kill must land with
    # the newest checkpoint strictly behind the killed worker's state,
    # so recovery genuinely re-trains the re-queued task instead of
    # resuming past it.
    checkpoint_steps = 2 * num_minibatches_per_task

    def build_cluster(subdir: str, callbacks=None,
                      with_checkpoint: bool = False) -> MiniCluster:
        return MiniCluster(
            model_zoo=model_zoo_dir(),
            model_def=MODEL_DEF,
            training_data=train,
            minibatch_size=minibatch_size,
            num_minibatches_per_task=num_minibatches_per_task,
            mesh=mesh4(),
            worker_callbacks=callbacks,
            checkpoint_dir=(
                os.path.join(workdir, subdir, "ckpt")
                if with_checkpoint else ""
            ),
            checkpoint_steps=checkpoint_steps if with_checkpoint else 0,
            checkpoint_async=False,
        )

    # ---- control: checkpoint-restart over the SAME mesh schedule -------
    # The proven old path: shrink = kill at a task boundary + fresh
    # dp2 worker restoring the v(2·mb/task) checkpoint; grow = the same
    # trained-but-unreported kill at report #grow_kill, fresh dp4
    # worker restoring the stale checkpoint and re-training the
    # re-queued task. Step programs, meshes, and data order match the
    # live run exactly — only the transition mechanism differs.
    logger.info("autoscale drill: checkpoint-restart control run")

    def make_phase_worker(cluster, worker_id, mesh, ckpt_dir,
                          callbacks=None):
        spec = get_model_spec(model_zoo_dir(), MODEL_DEF)
        spec.model = spec.make_model(mesh)
        return Worker(
            worker_id=worker_id,
            master_client=cluster.make_inprocess_client(
                worker_id, callbacks=callbacks
            ),
            model_spec=spec,
            data_reader=cluster.train_reader,
            minibatch_size=minibatch_size,
            step_runner=make_runner_for_spec(spec, mesh),
            checkpoint_hook=CheckpointHook(
                checkpoint_dir=ckpt_dir,
                checkpoint_steps=checkpoint_steps,
                async_save=False,
            ),
            checkpoint_dir_for_init=ckpt_dir,
            metrics_report_secs=0.0,
        )

    ctrl_counts = {"reports": 0}

    def ctrl_on_report(request):
        ctrl_counts["reports"] += 1
        if ctrl_counts["reports"] == grow_kill_at_report:
            # Same trained-but-unreported shape as the live run's kill.
            raise ChaosKill(1, event_index=ctrl_counts["reports"])

    def ctrl_on_get_task(request):
        # Shrink point: a clean task-boundary kill (nothing leased) —
        # the counterpart of the live run applying the shrink directive
        # between tasks without losing state.
        if ctrl_counts["reports"] >= shrink_at_report:
            raise ChaosKill(0, event_index=ctrl_counts["reports"])

    ctrl_cluster = build_cluster(
        "control",
        callbacks={"report_task_result": ctrl_on_report,
                   "get_task": ctrl_on_get_task},
        with_checkpoint=True,
    )
    ctrl_ckpt = os.path.join(workdir, "control", "ckpt")
    try:
        ctrl_cluster.workers[0].run()
        raise DrillError("control worker A was never killed")
    except ChaosKill:
        pass
    ctrl_cluster.dispatcher.recover_tasks(0)
    worker_b = make_phase_worker(
        ctrl_cluster, 1, mesh2(), ctrl_ckpt,
        callbacks={"report_task_result": ctrl_on_report},
    )
    try:
        worker_b.run()
        raise DrillError("control worker B was never killed")
    except ChaosKill:
        pass
    ctrl_cluster.dispatcher.recover_tasks(1)
    worker_c = make_phase_worker(ctrl_cluster, 2, mesh4(), ctrl_ckpt)
    worker_c.run()
    if not ctrl_cluster.finished:
        raise DrillError("control run did not drain")
    control = _final_summary(worker_c)
    ctrl_cluster.stop()

    # ---- autoscaled run ------------------------------------------------
    logger.info("autoscale drill: autoscaled run (shrink @%d, "
                "grow+kill @%d)", shrink_at_report, grow_kill_at_report)
    state = {"reports": 0, "killed": False, "worker_id": 0}
    box = {}
    resize_log: List[dict] = []

    def on_report(request):
        state["reports"] += 1
        cluster = box["cluster"]
        n = state["reports"]
        if n == shrink_at_report:
            rid = cluster.servicer.begin_resize(
                reshard.mesh_spec(mesh2()), direction="shrink"
            )
            resize_log.append({"resize_id": rid, "direction": "shrink",
                               "at_report": n})
        elif n == grow_kill_at_report and not state["killed"]:
            rid = cluster.servicer.begin_resize(
                reshard.mesh_spec(mesh4()), direction="grow"
            )
            resize_log.append({"resize_id": rid, "direction": "grow",
                               "at_report": n, "kill": True})
            state["killed"] = True
            # The callback runs BEFORE the servicer records the
            # report: this task dies trained-but-unreported, in
            # `doing` — and the grow directive dies unseen with us.
            raise ChaosKill(state["worker_id"], event_index=n)
        # The production master run-loop tick: refresh barrier
        # membership from the live fleet so a dead worker can't wedge
        # the barrier.
        cluster.servicer.maybe_complete_resize([state["worker_id"]])

    cluster = build_cluster(
        "autoscaled", callbacks={"report_task_result": on_report},
        with_checkpoint=True,
    )
    box["cluster"] = cluster
    ckpt_dir = os.path.join(workdir, "autoscaled", "ckpt")
    kills = 0
    worker = cluster.workers[0]
    while True:
        try:
            worker.run()
            break
        except ChaosKill:
            kills += 1
            if kills > 2:
                raise DrillError("kill budget exceeded")
            dead_id = state["worker_id"]
            cluster.dispatcher.recover_tasks(dead_id)
            cluster.servicer.remove_worker_metrics(dead_id)
            new_id = dead_id + 1
            state["worker_id"] = new_id
            logger.info(
                "drill: worker %d killed mid-barrier; relaunching as "
                "worker %d on the pre-grow mesh", dead_id, new_id,
            )
            # The relaunch comes up configured for the CURRENT (shrunk)
            # mesh — exactly what a pod relaunch would do — and meets
            # the still-pending grow directive on its first get_task.
            spec = get_model_spec(model_zoo_dir(), MODEL_DEF)
            spec.model = spec.make_model(mesh2())
            worker = Worker(
                worker_id=new_id,
                master_client=cluster.make_inprocess_client(
                    new_id,
                    callbacks={"report_task_result": on_report},
                ),
                model_spec=spec,
                data_reader=cluster.train_reader,
                minibatch_size=minibatch_size,
                step_runner=make_runner_for_spec(spec, mesh2()),
                checkpoint_hook=CheckpointHook(
                    checkpoint_dir=ckpt_dir,
                    checkpoint_steps=checkpoint_steps,
                    async_save=False,
                ),
                checkpoint_dir_for_init=ckpt_dir,
                metrics_report_secs=0.0,
            )

    # ---- verdicts -------------------------------------------------------
    verdicts = []
    drained = cluster.finished
    accounting = ExactlyOnceTaskAccounting(
        cluster.dispatcher, {TaskType.TRAINING: records}
    ).check()
    verdicts.append(accounting.to_dict())
    verdicts.append(
        _equivalence_verdict(control, _final_summary(worker))
    )

    barrier_problems = []
    if not drained:
        barrier_problems.append("job did not drain")
    if cluster.servicer.resize_status() is not None:
        barrier_problems.append(
            "a resize barrier is still pending after the job drained"
        )
    if len(resize_log) != 2:
        barrier_problems.append(
            f"expected 2 resizes (shrink, grow), saw {resize_log}"
        )
    if kills != 1:
        barrier_problems.append(f"expected exactly 1 kill, saw {kills}")
    final_mesh = None
    if worker.state is not None:
        import jax as _jax

        leaf = _jax.tree_util.tree_leaves(worker.state.params)[0]
        final_mesh = dict(leaf.sharding.mesh.shape)
        if final_mesh != {"dp": 4}:
            barrier_problems.append(
                f"final state not on the regrown dp4 mesh: {final_mesh}"
            )
    verdicts.append({
        "name": "resize_barrier_liveness",
        "passed": not barrier_problems,
        "details": (
            "; ".join(barrier_problems) if barrier_problems else
            f"shrink + grow barriers completed across {kills} "
            f"mid-barrier kill; final mesh {final_mesh}"
        ),
    })
    cluster.stop()

    passed = all(v["passed"] for v in verdicts)
    return {
        "autoscale_drill_version": REPORT_VERSION,
        "config": {
            "model_def": MODEL_DEF,
            "records": records,
            "minibatch_size": minibatch_size,
            "num_minibatches_per_task": num_minibatches_per_task,
            "checkpoint_steps": checkpoint_steps,
            "shrink_at_report": shrink_at_report,
            "grow_kill_at_report": grow_kill_at_report,
        },
        "resizes": resize_log,
        "kills": kills,
        "job": {
            "final_version": _final_summary(worker)["final_version"],
            "final_loss": (
                None if control["final_loss"] is None else round(
                    float(_final_summary(worker)["final_loss"]), 6
                )
            ),
            "final_mesh": final_mesh,
        },
        "invariants": verdicts,
        "passed": bool(passed),
    }


def main(argv=None) -> int:
    import argparse
    import shutil
    import tempfile

    parser = argparse.ArgumentParser("elasticdl_tpu-autoscale-drill")
    parser.add_argument("--report", default=DEFAULT_REPORT)
    parser.add_argument("--records", type=int, default=256)
    parser.add_argument("--workdir", default="",
                        help="Scratch dir (default: fresh tempdir, "
                             "removed afterwards)")
    args = parser.parse_args(argv)

    # Virtual multi-device CPU mesh, same forcing as the chaos CLI.
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8"
        ).strip()
    import jax

    if os.environ.get("JAX_PLATFORMS", "") == "cpu":
        jax.config.update("jax_platforms", "cpu")

    workdir = args.workdir
    cleanup = False
    if not workdir:
        workdir = tempfile.mkdtemp(prefix="edl_autoscale_")
        cleanup = True
    try:
        report = run_drill(workdir, records=args.records)
        with open(args.report, "w") as fh:
            fh.write(json.dumps(report, sort_keys=True, indent=2) + "\n")
        print(f"autoscale drill passed={report['passed']} "
              f"resizes={len(report['resizes'])} "
              f"kills={report['kills']}")
        for verdict in report["invariants"]:
            mark = "PASS" if verdict["passed"] else "FAIL"
            print(f"  [{mark}] {verdict['name']}: {verdict['details']}")
        return 0 if report["passed"] else 1
    finally:
        if cleanup:
            shutil.rmtree(workdir, ignore_errors=True)


if __name__ == "__main__":
    import sys

    sys.exit(main())
