"""SLO-engine drill: an injected row-RPC stall must page, a healthy
run must not.

``make slo-smoke`` (docs/observability.md "SLOs & alerting"):

1. **Faulted run** — a MiniCluster deepfm-host job over a real
   localhost ``HostRowService`` with a chaos ``rpc_delay`` injected
   into every ``pull_rows`` handler (the slow-row-plane regime, server
   site so the client-observed ``edl_tpu_rpc_client_seconds`` attempt
   latency actually contains the stall). A burn-rate rule over that
   family must fire, and the ``IncidentRecorder`` must leave a
   black-box bundle that ``tools/check_incident.py`` accepts
   (Perfetto-loadable trace, non-empty series window around the
   breach, critical-path attribution, journal tail).
2. **Healthy twin** — the identical job without the fault: ZERO rules
   may fire (an alert that pages on a healthy system is as broken as
   one that misses a stall — no flapping).

The drill drives ``MetricsPlane.slo_tick`` from its own thread exactly
the way the master run loop does, just on a faster cadence so the
whole loop fits in a smoke-test budget. Exits nonzero unless both
halves hold.
"""

import argparse
import json
import os
import sys
import threading
import time

from elasticdl_tpu.common.log_utils import get_logger

logger = get_logger("slo_drill")

ROW_DELAY_SECS = 0.12
LATENCY_THRESHOLD = 0.05  # pull_rows bucket boundary: fast < 50ms < stalled


def _force_cpu_if_requested():
    """Same dance as chaos/runner.py: the container's sitecustomize may
    pin a TPU plugin over JAX_PLATFORMS=cpu."""
    if os.environ.get("JAX_PLATFORMS", "") == "cpu":
        import jax

        jax.config.update("jax_platforms", "cpu")


def drill_rule():
    """The burn-rate rule under test: 95% of row pulls must finish
    under LATENCY_THRESHOLD; windows shrunk so the smoke run breaches
    (and would clear) within seconds instead of SRE-scale minutes."""
    from elasticdl_tpu.observability.slo import SLORule

    return SLORule(
        name="row-pull-latency-burn",
        kind="burn_rate",
        series="edl_tpu_rpc_client_seconds",
        labels={"service": "RowService", "method": "pull_rows"},
        latency_threshold=LATENCY_THRESHOLD,
        objective=0.95,
        long_window_secs=15.0,
        short_window_secs=3.0,
        burn_rate_threshold=3.0,
        min_count=5,
        description="row pulls slower than 50ms burning >3x the 5% "
                    "budget (injected stall must trip this)",
    )


def run_half(workdir: str, faulted: bool, records: int = 96,
             tick_secs: float = 0.1, cadence_secs: float = 0.25) -> dict:
    """One drill half; returns its verdict dict."""
    from elasticdl_tpu.embedding import HostStepRunner
    from elasticdl_tpu.embedding.row_service import make_remote_engine
    from elasticdl_tpu.observability import default_registry, tracing
    from elasticdl_tpu.observability.slo import IncidentRecorder
    from elasticdl_tpu.testing.cluster import MiniCluster
    from elasticdl_tpu.testing.data import (
        create_frappe_record_file,
        model_zoo_dir,
    )
    from model_zoo.deepfm import deepfm_host

    label = "faulted" if faulted else "healthy"
    half_dir = os.path.join(workdir, label)
    os.makedirs(half_dir, exist_ok=True)
    data_path = os.path.join(half_dir, "train.rec")
    create_frappe_record_file(data_path, records, seed=11)

    # Process-global state must start clean per half: the two halves
    # share one python process, and the faulted half's counters leaking
    # into the healthy twin would fake a breach.
    default_registry().reset()
    recorder = tracing.FlightRecorder(capacity=8192)
    tracing.install_recorder(recorder)

    injector = None
    if faulted:
        from elasticdl_tpu.chaos.faults import FaultEvent, FaultPlan
        from elasticdl_tpu.chaos.interceptors import FaultInjector

        plan = FaultPlan(events=[FaultEvent(
            kind="rpc_delay", target="RowService", method="pull_rows",
            site="server", at_call=0, probability=1.0, max_fires=0,
            delay_secs=ROW_DELAY_SECS,
        )], seed=7)
        injector = FaultInjector(plan).install()

    svc = None
    cluster = None
    ticker_stop = threading.Event()
    try:
        svc = deepfm_host.make_row_service()
        svc.start(tag="rowservice/0")
        addr = f"localhost:{svc.port}"

        def runner_factory():
            # Synchronous applies: pulls stay on the worker thread, so
            # every stalled pull is a step-path stall (the regime the
            # alert exists for).
            return HostStepRunner(
                make_remote_engine(addr, id_keys={
                    deepfm_host.TABLE_NAME: deepfm_host.FEATURE_KEY,
                }),
                async_apply=False,
            )

        cluster = MiniCluster(
            model_zoo=model_zoo_dir(),
            model_def="deepfm.deepfm_host.custom_model",
            training_data=data_path,
            minibatch_size=8,
            num_minibatches_per_task=2,
            num_workers=1,
            step_runner_factory=runner_factory,
            metrics_report_secs=0.0,
            journal_dir=os.path.join(half_dir, "journal"),
        )
        plane = cluster.metrics_plane
        plane.enable_timeseries(cadence_secs=cadence_secs)
        incident_dir = os.path.join(workdir, "incidents")
        engine = plane.enable_slo(
            rules=[drill_rule()],
            incident_recorder=IncidentRecorder(
                incident_dir,
                metrics_plane=plane,
                store=plane.timeseries,
                journal_tail_fn=cluster._journal.tail,
                window_secs=60.0,
            ),
        )

        # The master run-loop tick, sped up for the smoke budget.
        def tick_loop():
            while not ticker_stop.wait(tick_secs):
                try:
                    plane.slo_tick()
                except Exception:
                    logger.exception("slo tick failed")

        ticker = threading.Thread(
            target=tick_loop, daemon=True, name="slo-drill-tick"
        )
        ticker.start()
        t0 = time.monotonic()
        cluster.run()
        ticker_stop.set()
        ticker.join(timeout=5)
        # One final evaluation on the drained run's window.
        plane.timeseries.sample({
            "": (default_registry().snapshot(), None)
        })
        states = engine.evaluate()
        elapsed = time.monotonic() - t0

        rule_state = engine.alert_state("row-pull-latency-burn")
        bundles = []
        if engine.incident_recorder is not None:
            # Captures write on a background thread; barrier before
            # the schema check reads the bundle.
            engine.incident_recorder.flush()
            bundles = engine.incident_recorder.bundles
        return {
            "label": label,
            "finished": cluster.finished,
            "elapsed_secs": round(elapsed, 3),
            "fired_count": rule_state["fired_count"],
            "final_states": states,
            "bundles": bundles,
            "samples": plane.timeseries.sample_count,
            "injected": len(injector.injected) if injector else 0,
        }
    finally:
        ticker_stop.set()
        tracing.uninstall_recorder()
        if injector is not None:
            injector.uninstall()
        if cluster is not None:
            if cluster._server is not None:
                cluster._server.stop(0)
            cluster.stop()
        if svc is not None:
            try:
                svc.stop(0)
            except Exception:
                pass


def main(argv=None) -> int:
    parser = argparse.ArgumentParser("elasticdl_tpu-slo-drill")
    parser.add_argument("--workdir", default="",
                        help="Scratch dir; the incident bundle lands "
                             "in <workdir>/incidents (default: fresh "
                             "tempdir, kept only on failure)")
    parser.add_argument("--report", default="SLO_DRILL.json")
    parser.add_argument("--records", type=int, default=96)
    args = parser.parse_args(argv)

    _force_cpu_if_requested()

    import shutil
    import tempfile

    workdir = args.workdir
    cleanup = False
    if not workdir:
        workdir = tempfile.mkdtemp(prefix="edl_slo_")
        cleanup = True

    failures = []
    faulted = run_half(workdir, faulted=True, records=args.records)
    if not faulted["finished"]:
        failures.append("faulted: job did not drain")
    if faulted["fired_count"] < 1:
        failures.append(
            "faulted: burn-rate rule never fired under the injected "
            f"stall ({faulted['injected']} delays injected)"
        )
    if not faulted["bundles"]:
        failures.append("faulted: no incident bundle written")
    else:
        from tools.check_incident import check_incident

        for err in check_incident(faulted["bundles"][0]):
            failures.append(f"faulted bundle: {err}")

    healthy = run_half(workdir, faulted=False, records=args.records)
    if not healthy["finished"]:
        failures.append("healthy: job did not drain")
    if healthy["fired_count"] != 0:
        failures.append(
            "healthy twin FIRED the burn-rate rule "
            f"({healthy['fired_count']}x) — flapping alert"
        )

    report = {
        "ok": not failures,
        "failures": failures,
        "faulted": faulted,
        "healthy": healthy,
        "workdir": workdir,
    }
    with open(args.report, "w") as fh:
        json.dump(report, fh, indent=2, sort_keys=True)
        fh.write("\n")
    for failure in failures:
        logger.error("SLO drill failure: %s", failure)
    logger.info(
        "SLO drill %s: faulted fired %dx (%d bundles), healthy fired "
        "%dx; report %s",
        "PASS" if not failures else "FAIL",
        faulted["fired_count"], len(faulted["bundles"]),
        healthy["fired_count"], args.report,
    )
    if cleanup and not failures:
        shutil.rmtree(workdir, ignore_errors=True)
    elif cleanup:
        logger.warning("keeping %s for inspection", workdir)
    return 0 if not failures else 1


if __name__ == "__main__":
    sys.exit(main())
