"""Chaos plane: deterministic fault injection + recovery invariants.

The subsystem that turns the paper's resilience claim into a testable
property: ``faults`` (seed-deterministic schedules), ``interceptors``
(injection hooks threaded through RPC, checkpointing, the instance
manager, and the in-process cluster), ``invariants`` (exactly-once
task accounting, row conservation, checkpoint monotonicity,
loss-trajectory equivalence), and ``runner`` (the harness + the
``elasticdl_tpu chaos`` CLI). See docs/chaos.md.
"""

from elasticdl_tpu.chaos.faults import (  # noqa: F401
    FaultEvent,
    FaultPlan,
    default_plan,
    master_kill_plan,
    randomized_plan,
)
from elasticdl_tpu.chaos.interceptors import (  # noqa: F401
    ChaosKill,
    FaultInjector,
)
from elasticdl_tpu.chaos.invariants import (  # noqa: F401
    CheckpointMonotonicity,
    CheckResult,
    ExactlyOnceTaskAccounting,
    LossTrajectoryEquivalence,
    MasterRestartEquivalence,
    RowConservation,
)
from elasticdl_tpu.chaos.runner import ChaosRunner  # noqa: F401
