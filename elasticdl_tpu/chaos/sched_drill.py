"""Gang-scheduler drill: two jobs on ONE elastic fleet, with a live
priority preemption — and nobody's state may smear.

``make sched-smoke`` (docs/scheduler.md "Smoke lane"):

One shared fleet (two worker slots + a 2-shard row service with the
write-ahead push log attached) runs two jobs through a real
``GangScheduler`` journaling onto a real ``MasterJournal``, leases
routed by a real ``MasterServicer`` in multi-job mode:

1. ``batch-lo`` (priority 1, gang 2) is submitted and starts running.
2. After ``PREEMPT_AFTER`` of its tasks land, ``prio-hi`` (priority
   10, gang 2) arrives via the ``submit_job`` RPC — the next
   scheduler tick preempts the batch job: its preempt callback
   checkpoints the dense model, ``preempt_leases`` hands the
   in-flight leases back (retry budgets untouched), and the drill
   kills the workers' pending applies the way a deleted pod would —
   side effects of a revoked lease never land.
3. ``prio-hi`` runs to completion on the whole fleet and journals
   ``done``; the next tick resumes ``batch-lo`` — its resume callback
   restores the dense model from the preemption checkpoint — and the
   batch job finishes on the slots it got back.

Each job owns a dense model vector plus its own embedding table on
the SHARED row service (plain SGD: per-row updates commute, and every
row id is pushed exactly once per job with exactly-representable
values — so any correct schedule is byte-identical to a solo run; a
lost or doubled task is not).

Gates (all must hold, else exit nonzero):

- **Isolation** — both jobs' final dense models AND row tables are
  byte-equal to solo control runs of the same job alone on a fresh
  fleet. A preemption that loses or double-applies work shows up
  here first.
- **Exactly-once** — every task of both jobs applied exactly once
  (the preempted in-flight leases were dropped un-applied and re-ran
  after resume; at least one such handback actually happened).
- **Lifecycle** — the journal's ``sched`` fold replays to both jobs
  ``done`` with exactly one recorded preemption of ``batch-lo``, and
  a cold fold over ``read_records`` agrees (the standby would wake
  with this exact table).
- **Fsck** — ``tools/check_journal.py`` over the master journal and
  ``tools/check_pushlog.py`` over every shard's WAL come back clean.

Report is validated by ``tools/check_sched.py`` and fsck'd under the
``sched`` kind. Fast-lane equivalent:
``tests/test_failover.py`` scheduler-replay tests.
"""

import argparse
import json
import os
import sys
import time

import numpy as np

from elasticdl_tpu.common.log_utils import get_logger

logger = get_logger("sched_drill")

DENSE_DIM = 16
ROW_DIM = 4
ROWS_PER_TASK = 8      # records per task == rows per task (1:1)
SLOTS = 2              # worker slots on the shared fleet
LR = 0.5               # exactly representable: updates stay exact

LO_JOB = "batch-lo"    # priority 1, the long batch job
HI_JOB = "prio-hi"     # priority 10, the preemptor
LO_TASKS = 12
HI_TASKS = 6
PREEMPT_AFTER = 4      # lo tasks applied before hi is submitted
MAX_STEPS = 400        # scheduler/worker loop iterations before giving up

_TABLES = {LO_JOB: "rows_batch_lo", HI_JOB: "rows_prio_hi"}
_NTASKS = {LO_JOB: LO_TASKS, HI_JOB: HI_TASKS}
_SALT = {LO_JOB: 3, HI_JOB: 11}


def _pkg_root() -> str:
    return os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))
    ))


def _job_spec(job: str) -> dict:
    """Spec the scheduler's default dispatcher factory understands:
    one shard, fixed-size tasks, no shuffle — task k covers rows
    ``[k*ROWS_PER_TASK, (k+1)*ROWS_PER_TASK)``."""
    return {
        "shards": {"data": [0, _NTASKS[job] * ROWS_PER_TASK]},
        "records_per_task": ROWS_PER_TASK,
        "num_epochs": 1,
        "seed": 0,
    }


def _row_ids(start: int, end: int) -> np.ndarray:
    """Row ids for record range [start, end): strided so the drill's
    small vocab spreads across the WHOLE bucket space (ids fold into
    buckets by ``id % NUM_BUCKETS``; consecutive small ints would all
    land on shard 0 and never exercise the second shard's WAL)."""
    from elasticdl_tpu.embedding.shard_map import NUM_BUCKETS

    stride = NUM_BUCKETS // (LO_TASKS * ROWS_PER_TASK)
    return np.arange(start, end, dtype=np.int64) * stride


def _task_grads(job: str, start: int, end: int):
    """Deterministic, exactly-representable push for one task: small
    integers, so SGD's ``row - lr*grad`` is exact and the final table
    depends only on WHICH pushes landed, never on their order."""
    rows = np.arange(start, end, dtype=np.int64)
    base = rows[:, None] * ROW_DIM + np.arange(ROW_DIM)[None, :]
    return _row_ids(start, end), ((base + _SALT[job]) % 64).astype(
        np.float32
    )


def _task_dense(job: str, start: int) -> np.ndarray:
    """The task's dense-model contribution — small integers again, so
    the (commutative) float32 sum is exact in any apply order."""
    return (
        (np.arange(DENSE_DIM) + start + _SALT[job]) % 32
    ).astype(np.float32)


class _Fleet:
    """One run's row-service shards (both jobs' tables on every
    shard, WAL attached) + remote engine."""

    def __init__(self, root: str):
        from elasticdl_tpu.embedding.optimizer import (
            SGD,
            HostOptimizerWrapper,
        )
        from elasticdl_tpu.embedding.row_service import HostRowService
        from elasticdl_tpu.embedding.table import EmbeddingTable

        self.root = root
        self.wal_dirs = []
        self.shards = []
        for i in range(2):
            svc = HostRowService(
                {t: EmbeddingTable(t, ROW_DIM)
                 for t in _TABLES.values()},
                HostOptimizerWrapper(SGD(lr=LR)),
            ).start("localhost:0")
            wal = os.path.join(root, "wal", f"shard{i}")
            svc.configure_push_log(wal, group_ms=1.0)
            self.wal_dirs.append(wal)
            self.shards.append(svc)
        self.engine = None

    def client(self):
        from elasticdl_tpu.embedding.row_service import (
            make_remote_engine,
        )

        if self.engine is None:
            self.engine = make_remote_engine(
                ",".join(f"localhost:{s.port}" for s in self.shards),
                id_keys={t: f"ids_{t}" for t in _TABLES.values()},
                retries=6, backoff_secs=0.1,
            )
        return self.engine

    def push(self, table: str, ids, grads):
        engine = self.client()
        engine.optimizer.apply_gradients(
            engine.tables[table], ids, grads
        )

    def pull_bytes(self, table: str, num_rows: int) -> bytes:
        rows = np.asarray(
            self.client().tables[table].get(_row_ids(0, num_rows)),
            dtype=np.float32,
        )
        return rows.tobytes()

    def stop(self):
        if self.engine is not None:
            self.engine.close()
        for svc in self.shards:
            try:
                svc.stop(0)
            except Exception:
                pass


def _solo_run(workdir: str, job: str):
    """Control: the job alone on a fresh fleet, tasks in order.
    Returns (dense_bytes, table_bytes)."""
    fleet = _Fleet(os.path.join(workdir, f"solo_{job}"))
    try:
        model = np.zeros(DENSE_DIM, np.float32)
        for k in range(_NTASKS[job]):
            start = k * ROWS_PER_TASK
            ids, grads = _task_grads(job, start, start + ROWS_PER_TASK)
            fleet.push(_TABLES[job], ids, grads)
            model = model + _task_dense(job, start)
        return model.tobytes(), fleet.pull_bytes(
            _TABLES[job], _NTASKS[job] * ROWS_PER_TASK
        )
    finally:
        fleet.stop()


def _shared_run(workdir: str) -> dict:
    """The real thing: GangScheduler + MasterJournal + MasterServicer
    over one fleet, two simulated worker slots, a live preemption."""
    from elasticdl_tpu.master.journal import MasterJournal
    from elasticdl_tpu.master.scheduler import GangScheduler
    from elasticdl_tpu.master.servicer import MasterServicer
    from elasticdl_tpu.master.task_dispatcher import TaskDispatcher

    root = os.path.join(workdir, "shared")
    fleet = _Fleet(root)
    journal_dir = os.path.join(root, "journal")
    journal = MasterJournal(journal_dir)
    generation = journal.open_generation()
    sched = GangScheduler(slots_fn=lambda: SLOTS, journal=journal)
    servicer = MasterServicer(
        TaskDispatcher({}, shuffle=False),  # single-job plane unused
        journal=journal, generation=generation, scheduler=sched,
    )

    out = {
        "events": [], "dropped_leases": 0, "preempt_checkpointed": 0,
        "resume_restored": 0, "steps": 0, "finished_seen": False,
        "applied": {LO_JOB: {}, HI_JOB: {}},
        "dense": {}, "rows": {}, "problems": [],
    }
    models = {LO_JOB: np.zeros(DENSE_DIM, np.float32)}
    ckpt_dir = os.path.join(root, "preempt_ckpt")
    os.makedirs(ckpt_dir, exist_ok=True)

    def _preempt_lo(job_id, entry):
        # Checkpoint-now on preemption: persist the dense model and
        # poison the in-memory copy — an apply that sneaks in while
        # the gang is revoked would crash the drill, not corrupt it.
        np.save(os.path.join(ckpt_dir, "batch_lo.npy"),
                models[LO_JOB])
        models[LO_JOB] = None
        out["preempt_checkpointed"] += 1

    def _resume_lo(job_id, entry):
        models[LO_JOB] = np.load(
            os.path.join(ckpt_dir, "batch_lo.npy")
        )
        out["resume_restored"] += 1

    sched.submit(LO_JOB, spec=_job_spec(LO_JOB), priority=1,
                 gang_size=2, preempt_cb=_preempt_lo,
                 resume_cb=_resume_lo)

    def _apply(job: str, task: dict):
        start, end = int(task["start"]), int(task["end"])
        ids, grads = _task_grads(job, start, end)
        fleet.push(_TABLES[job], ids, grads)
        models[job] = models[job] + _task_dense(job, start)
        tid = int(task["task_id"])
        out["applied"][job][tid] = out["applied"][job].get(tid, 0) + 1

    hi_submitted = False
    pending = {w: None for w in range(SLOTS)}  # worker -> (job, task)
    try:
        for step in range(1, MAX_STEPS + 1):
            out["steps"] = step
            # Fetch: idle workers lease before the tick, so the
            # preemption below lands on genuinely in-flight leases.
            for w in range(SLOTS):
                if pending[w] is not None:
                    continue
                resp = servicer.get_task({"worker_id": w})
                if resp.get("finished"):
                    out["finished_seen"] = True
                    continue
                task = resp.get("task")
                if task is None or int(task["task_id"]) < 0:
                    continue
                pending[w] = (str(resp.get("job", "")), task)
            if (not hi_submitted
                    and len(out["applied"][LO_JOB]) >= PREEMPT_AFTER):
                models[HI_JOB] = np.zeros(DENSE_DIM, np.float32)
                resp = servicer.submit_job({
                    "job": HI_JOB, "spec": _job_spec(HI_JOB),
                    "priority": 10, "gang_size": 2,
                })
                if not resp.get("accepted"):
                    out["problems"].append(
                        f"submit_job rejected: {resp}"
                    )
                hi_submitted = True
            out["events"].extend(sched.tick())
            # A preempted gang's pods are deleted: any lease a worker
            # was still holding dies with it, un-applied. The handed-
            # back task re-runs after resume — exactly once.
            states = {
                j: e["state"]
                for j, e in sched.render()["jobs"].items()
            }
            for w in range(SLOTS):
                if (pending[w] is not None
                        and states.get(pending[w][0]) == "preempted"):
                    pending[w] = None
                    out["dropped_leases"] += 1
            # Apply + report the surviving leases.
            for w in range(SLOTS):
                if pending[w] is None:
                    continue
                job, task = pending[w]
                _apply(job, task)
                servicer.report_task_result({
                    "task_id": int(task["task_id"]),
                    "worker_id": w, "job": job,
                    "generation": generation,
                })
                pending[w] = None
            if states and all(s == "done" for s in states.values()):
                break
        # One more lease round so the servicer's finished verdict
        # (scheduler idle + primary drained) is exercised.
        resp = servicer.get_task({"worker_id": 0})
        if resp.get("finished"):
            out["finished_seen"] = True
        for job in (LO_JOB, HI_JOB):
            out["dense"][job] = models[job].tobytes()
            out["rows"][job] = fleet.pull_bytes(
                _TABLES[job], _NTASKS[job] * ROWS_PER_TASK
            )
        out["render"] = sched.render()
        out["journal_dir"] = journal_dir
        out["wal_dirs"] = list(fleet.wal_dirs)
    finally:
        fleet.stop()
        journal.close()
    return out


def _replay_fold(journal_dir: str) -> dict:
    """Cold fold of the journal's sched records — exactly what a
    promoted standby (or a recovering master) would wake up with."""
    from elasticdl_tpu.master.journal import (
        JOURNAL_FILE,
        SCHED,
        SNAPSHOT,
        apply_sched_record,
        new_sched_state,
        read_records,
    )

    state = new_sched_state()
    for _offset, _end, record in read_records(
        os.path.join(journal_dir, JOURNAL_FILE)
    ):
        if record["t"] == SNAPSHOT and record.get("sched") is not None:
            state = record["sched"]
        elif record["t"] == SCHED:
            apply_sched_record(state, record)
    return state


def _fsck(journal_dir: str, wal_dirs) -> dict:
    sys.path.insert(0, os.path.join(_pkg_root(), "tools"))
    from check_journal import check_journal
    from check_pushlog import check_one_log

    result = {"journal_errors": check_journal(journal_dir),
              "wal": []}
    for wal in wal_dirs:
        errors, rep = check_one_log(wal)
        result["wal"].append({
            "dir": wal, "errors": errors,
            "records": rep.get("records", 0),
            "torn_tail": rep.get("torn_tail"),
        })
    return result


def run_drill(workdir: str, seed: int = 0) -> dict:
    report = {
        "drill": "gang_sched",
        "seed": seed,
        "config": {
            "slots": SLOTS, "dense_dim": DENSE_DIM,
            "row_dim": ROW_DIM, "rows_per_task": ROWS_PER_TASK,
            "jobs": {
                LO_JOB: {"priority": 1, "gang": 2,
                         "tasks": LO_TASKS},
                HI_JOB: {"priority": 10, "gang": 2,
                         "tasks": HI_TASKS},
            },
            "preempt_after": PREEMPT_AFTER,
        },
        "problems": [],
    }

    solo = {job: _solo_run(workdir, job) for job in (LO_JOB, HI_JOB)}
    shared = _shared_run(workdir)
    report["problems"].extend(shared["problems"])
    report["scheduler"] = {
        "events": shared["events"],
        "steps": shared["steps"],
        "dropped_leases": shared["dropped_leases"],
        "finished_seen": shared["finished_seen"],
    }

    # Isolation: byte-equality against the solo controls.
    byte_equal = {}
    for job in (LO_JOB, HI_JOB):
        dense_ok = solo[job][0] == shared["dense"][job]
        rows_ok = solo[job][1] == shared["rows"][job]
        byte_equal[job] = {"dense": dense_ok, "rows": rows_ok}
        if not dense_ok:
            report["problems"].append(
                f"{job}: dense model diverged from solo run"
            )
        if not rows_ok:
            report["problems"].append(
                f"{job}: row table diverged from solo run"
            )
    report["byte_equal"] = byte_equal

    # Exactly-once accounting (and the preemption really revoked
    # in-flight leases whose tasks then re-ran).
    accounting = {}
    for job in (LO_JOB, HI_JOB):
        counts = shared["applied"][job]
        dupes = {t: c for t, c in counts.items() if c != 1}
        accounting[job] = {"applied": len(counts), "dupes": dupes}
        if len(counts) != _NTASKS[job]:
            report["problems"].append(
                f"{job}: {len(counts)} tasks applied, "
                f"want {_NTASKS[job]}"
            )
        if dupes:
            report["problems"].append(
                f"{job}: tasks applied more than once: {dupes}"
            )
    report["accounting"] = accounting
    if shared["dropped_leases"] < 1:
        report["problems"].append(
            "no in-flight lease was revoked by the preemption — the "
            "drill did not exercise the handback path"
        )
    if shared["preempt_checkpointed"] != 1:
        report["problems"].append(
            f"preempt checkpoint ran {shared['preempt_checkpointed']} "
            "times, want exactly 1"
        )
    if shared["resume_restored"] != 1:
        report["problems"].append(
            f"resume restore ran {shared['resume_restored']} times, "
            "want exactly 1"
        )
    if not shared["finished_seen"]:
        report["problems"].append(
            "servicer never reported finished after both jobs done"
        )

    # Lifecycle: live table and the cold journal fold must both say
    # done+done with exactly one preemption of the batch job.
    fold = _replay_fold(shared["journal_dir"])
    live = shared["render"]["jobs"]
    report["replay"] = {
        "jobs": {j: e.get("state") for j, e in fold["jobs"].items()},
        "preemptions": fold.get("preemptions", 0),
    }
    for job in (LO_JOB, HI_JOB):
        for name, table in (("live", live), ("replayed", fold["jobs"])):
            got = (table.get(job) or {}).get("state")
            if got != "done":
                report["problems"].append(
                    f"{name} state for {job} is {got!r}, want 'done'"
                )
    lo_preempts = (fold["jobs"].get(LO_JOB) or {}).get("preemptions", 0)
    if lo_preempts != 1:
        report["problems"].append(
            f"journal fold shows {lo_preempts} preemptions of "
            f"{LO_JOB}, want exactly 1"
        )

    # Fsck: journal + every shard WAL.
    fsck = _fsck(shared["journal_dir"], shared["wal_dirs"])
    report["fsck"] = fsck
    report["problems"].extend(
        f"journal fsck: {e}" for e in fsck["journal_errors"]
    )
    for wal in fsck["wal"]:
        report["problems"].extend(
            f"wal fsck {wal['dir']}: {e}" for e in wal["errors"]
        )
        if wal["records"] <= 0:
            report["problems"].append(
                f"wal {wal['dir']}: no push records — the WAL was "
                "not exercised"
            )

    report["passed"] = not report["problems"]
    return report


def main(argv=None) -> int:
    parser = argparse.ArgumentParser("elasticdl_tpu-sched-drill")
    parser.add_argument("--seed", type=int, default=0)
    parser.add_argument("--workdir", required=True)
    parser.add_argument("--report", default="SCHED_DRILL.json")
    args = parser.parse_args(argv)

    report = run_drill(args.workdir, args.seed)
    with open(args.report, "w") as fh:
        json.dump(report, fh, indent=2, sort_keys=True, default=str)
        fh.write("\n")
    logger.info(
        "sched drill: %s (%d events, %d dropped leases); report %s",
        "PASS" if report["passed"] else "FAIL",
        len(report["scheduler"]["events"]),
        report["scheduler"]["dropped_leases"],
        args.report,
    )
    if report["problems"]:
        for problem in report["problems"]:
            logger.error("problem: %s", problem)
    return 0 if report["passed"] else 1


if __name__ == "__main__":
    sys.exit(main())
