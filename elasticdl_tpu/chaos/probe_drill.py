"""Synthetic-probe chaos drill: every fault reds the MATCHING
black-box probe within a bounded interval count, a kill-free twin
stays 100% green (``make probe-smoke``; committed PROBE_DRILL.json,
audited by ``tools/check_probe.py`` in the fsck umbrella).

White-box drills adjudicate recovery from inside the planes they
fault; this drill adjudicates the OBSERVER: the prober
(``observability/prober.py``) must detect each outage from outside,
fast, and must never cry wolf. One process hosts the full plane set
the five shipped probes exercise, all real surfaces:

- **row tier** — two ``quake_drill`` row-service subprocesses (durable
  WAL, ``--optimizer sgd`` so ``row_ryw``'s byte-equality expectation
  is order-free);
- **dispatch + stream** — a ``stream_drill._Master`` incarnation
  (real journal, streaming dispatcher, ingestor) whose ONLY job is the
  canary stream partition; a background canary worker (the dispatch
  probe body in ``resolve=True`` mode, running under the ``canary``
  principal) drains it so the committed watermark can advance;
- **serving** — an exported DeepFM host-tier bundle with an **int64
  feature signature** (the server coerces request ids onto the
  recorded signature; an int32 signature would truncate every
  canary-range id to garbage), served by a REAL replica subprocess
  behind an in-process router, rows from a dedicated serving row
  service (so the row-tier kill window cannot leak into the serving
  verdict).

Fault windows (the faulted run, after a green barrier):

1. ``row_shard_kill``  — SIGKILL the row shard owning the canary ids
                         → ``row_ryw`` reds; relaunch, re-green.
2. ``serving_stall``   — SIGSTOP the serving replica (the process is
                         alive but the path is wedged — exactly what
                         white-box metrics miss) →
                         ``serving_freshness`` reds; SIGCONT.
3. ``master_kill``     — crash the master incarnation →
                         ``dispatch_roundtrip`` reds; a fresh
                         incarnation journal-recovers on the same
                         port, re-green.

Gates: each window's matching probe turns red within
``DETECT_BOUND_TICKS`` probe intervals and the plane re-greens within
``GREEN_BOUND_TICKS`` after repair; the twin run's ticks are 100%
green (zero false positives); each red transition captured an
incident bundle whose rule is ``probe-<name>`` and whose alert carries
the failing run's trace id; and the master-side ``/usage`` metering
accounts every canary RPC under the ``canary`` purpose — and ONLY
under it. docs/observability.md "Synthetic probing".
"""

import argparse
import json
import os
import signal
import subprocess
import sys
import threading
import time
from typing import Dict, List, Optional

import numpy as np

from elasticdl_tpu.common.log_utils import get_logger

logger = get_logger("probe_drill")

SEED = 23
UNHEALTHY_AFTER = 2
DETECT_BOUND_TICKS = 5
GREEN_BOUND_TICKS = 40
TWIN_TICKS = 8
SETUP_BOUND_TICKS = 40
SERVING_DEADLINE_SECS = 3.0
STREAM_DEADLINE_SECS = 4.0
ROW_LR = 0.01          # quake_drill SGD shard: --optimizer sgd
SERVING_ROW_LR = 0.5   # the serving plane's own row service

PROBES = ("row_ryw", "serving_freshness", "reshard_convergence",
          "stream_watermark", "dispatch_roundtrip")

WINDOWS = (
    ("row_shard_kill", "row_ryw"),
    ("serving_stall", "serving_freshness"),
    ("master_kill", "dispatch_roundtrip"),
)


def _pkg_root() -> str:
    return os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__)
    )))


# ---- serving plane ---------------------------------------------------------


def export_probe_bundle(tmpdir: str, seed: int) -> str:
    """DeepFM host-tier bundle whose feature signature is **int64**.
    ``serving_drill.export_sparse_bundle`` traces with int32 ids; the
    server coerces every request onto the recorded signature
    (``server.py _coerce_signature``), which would truncate canary-
    range ids (>= 2^62) into the real vocabulary — the probe would
    then perturb row 0 of the REAL table and never see its own write.
    """
    import optax

    from elasticdl_tpu.core.model_spec import get_model_spec
    from elasticdl_tpu.core.train_state import init_train_state
    from elasticdl_tpu.serving.export import export_serving_bundle
    from elasticdl_tpu.testing.data import model_zoo_dir
    from model_zoo.deepfm import deepfm_host

    spec = get_model_spec(
        model_zoo_dir(), "deepfm.deepfm_host.custom_model"
    )
    rng = np.random.RandomState(seed)
    ids = rng.randint(0, 200, (4, 10)).astype(np.int64)
    batch = {
        "features": {deepfm_host.FEATURE_KEY: ids},
        "labels": np.zeros((4,), np.int32),
        "mask": np.ones((4,), np.float32),
    }
    state = init_train_state(
        spec.model, optax.adam(1e-3), batch, seed=seed
    )
    bundle = os.path.join(tmpdir, "bundle")
    export_serving_bundle(
        bundle, spec.model, state, batch_example=batch,
        model_def="deepfm.deepfm_host.custom_model",
        host_id_keys={deepfm_host.TABLE_NAME: deepfm_host.FEATURE_KEY},
    )
    return bundle


class _ServingPlane:
    """Replica SUBPROCESS (SIGSTOP-able) + in-process router + a
    dedicated row service for the serving tier's rows."""

    def __init__(self, workdir: str, bundle: str):
        from elasticdl_tpu.chaos.quake_drill import _free_ports
        from elasticdl_tpu.embedding.optimizer import (
            SGD,
            HostOptimizerWrapper,
        )
        from elasticdl_tpu.embedding.row_service import HostRowService
        from elasticdl_tpu.embedding.table import EmbeddingTable
        from elasticdl_tpu.observability import MetricsRegistry
        from elasticdl_tpu.serving.router import RouterServer
        from model_zoo.deepfm import deepfm_host

        os.makedirs(workdir, exist_ok=True)
        self.feature_key = deepfm_host.FEATURE_KEY
        self.row_service = HostRowService(
            {deepfm_host.TABLE_NAME: EmbeddingTable(
                deepfm_host.TABLE_NAME, deepfm_host.EMBEDDING_DIM
            )},
            HostOptimizerWrapper(SGD(lr=SERVING_ROW_LR)),
            metrics_registry=MetricsRegistry(),
        ).start()
        self._replica_port = _free_ports(1)[0]
        self._log = open(os.path.join(workdir, "replica.log"), "w")
        self.replica = subprocess.Popen(
            [sys.executable, "-m", "elasticdl_tpu.serving.server",
             "--model_dir", bundle,
             "--port", str(self._replica_port),
             "--row_service_addr",
             f"localhost:{self.row_service.port}",
             "--row_cache_capacity", "4096",
             "--row_cache_version_check_ms", "20"],
            env=dict(os.environ, JAX_PLATFORMS="cpu"),
            cwd=_pkg_root(), stdout=self._log,
            stderr=subprocess.STDOUT,
        )
        self.router = RouterServer(
            [f"localhost:{self._replica_port}"], port=0,
            metrics_registry=MetricsRegistry(),
            replica_timeout=2.0, probe_secs=0.2,
        ).start()

    def wait_ready(self, predict_fn, deadline_secs: float = 180.0):
        from elasticdl_tpu.observability.prober import ProbeFailure

        t0 = time.monotonic()
        while True:
            try:
                predict_fn()
                return
            except ProbeFailure as exc:
                if time.monotonic() - t0 > deadline_secs:
                    raise TimeoutError(
                        f"serving replica never answered: {exc}"
                    )
                time.sleep(0.5)

    def stall(self):
        os.kill(self.replica.pid, signal.SIGSTOP)

    def unstall(self):
        os.kill(self.replica.pid, signal.SIGCONT)

    def stop(self):
        try:
            self.router.drain(grace=2.0)
        except Exception:
            pass
        try:
            os.kill(self.replica.pid, signal.SIGCONT)
        except OSError:
            pass
        self.replica.terminate()
        try:
            self.replica.wait(timeout=15)
        except Exception:
            self.replica.kill()
        self.row_service.stop(0)
        self._log.close()


# ---- canary worker ---------------------------------------------------------


class _CanaryWorker(threading.Thread):
    """Drains the master's canary stream tasks so the committed
    watermark can advance. Reuses the dispatch probe body in
    ``resolve=True`` mode — the drill master's only job IS the canary
    partition — and runs under the canary principal so its RPCs meter
    as synthetic load, like all probe traffic."""

    def __init__(self, master_addr: str):
        super().__init__(name="canary-worker", daemon=True)
        from elasticdl_tpu.observability import prober

        self._resolve = prober.make_dispatch_roundtrip_probe(
            master_addr, worker_id=7, resolve=True,
        )
        # NOT `_stop`: threading.Thread.join() calls its private
        # `_stop()` internally; shadowing it with an Event breaks join.
        self._halt = threading.Event()

    def run(self):
        from elasticdl_tpu.observability import principal, prober

        with principal.pushed(job=prober.CANARY_JOB,
                              component="prober", purpose="canary"):
            while not self._halt.is_set():
                try:
                    self._resolve()
                except Exception:
                    # Master down (the kill window) — retry quietly.
                    self._halt.wait(0.2)
                self._halt.wait(0.02)

    def stop(self, timeout: float = 5.0):
        self._halt.set()
        self.join(timeout=timeout)


# ---- one plane-set run -----------------------------------------------------


class _Plane:
    """Everything one run probes: row fleet, master, serving,
    prober."""

    def __init__(self, workdir: str, bundle: str,
                 incident_dir: str = ""):
        from elasticdl_tpu.chaos.quake_drill import (
            RowFleet,
            _free_ports,
            _wait_shard,
        )
        from elasticdl_tpu.chaos.stream_drill import _Master
        from elasticdl_tpu.observability import MetricsRegistry, prober
        from elasticdl_tpu.observability.slo import IncidentRecorder

        self.workdir = workdir
        os.makedirs(workdir, exist_ok=True)
        self.row_ports = _free_ports(2)
        self.fleet = RowFleet(os.path.join(workdir, "rows"))
        for shard, port in enumerate(self.row_ports):
            self.fleet.spawn(
                shard, port,
                checkpoint_dir=os.path.join(
                    workdir, "rows", f"s{shard}", "ckpt"),
                push_log_dir=os.path.join(
                    workdir, "rows", f"s{shard}", "wal"),
                ack="durable", optimizer="sgd",
            )
        for port in self.row_ports:
            _wait_shard(port)

        self.journal_dir = os.path.join(workdir, "journal")
        self.stream_dir = os.path.join(workdir, "stream")
        os.makedirs(self.journal_dir, exist_ok=True)
        os.makedirs(self.stream_dir, exist_ok=True)
        self.master_port = _free_ports(1)[0]
        self.master = _Master(self.journal_dir, self.stream_dir,
                              self.master_port)
        self.worker = _CanaryWorker(f"localhost:{self.master_port}")
        self.worker.start()

        self.serving = _ServingPlane(
            os.path.join(workdir, "serving"), bundle
        )

        self.registry = MetricsRegistry()
        self.incidents = None
        if incident_dir:
            os.makedirs(incident_dir, exist_ok=True)
            self.incidents = IncidentRecorder(
                incident_dir, background=False
            )
        self.sched = prober.ProbeScheduler(
            registry=self.registry,
            incident_recorder=self.incidents,
            unhealthy_after=UNHEALTHY_AFTER,
        )
        self._register_probes()

    def _register_probes(self):
        from elasticdl_tpu.observability import prober

        row_addrs = ",".join(
            f"localhost:{p}" for p in self.row_ports
        )
        self.canary_client = prober.RowCanaryClient(row_addrs)
        # The quake shards run SGD(lr=ROW_LR): the deployment knows
        # its optimizer rule, so RYW gates BYTE equality, not just
        # visibility.
        expect = lambda before, grads: (  # noqa: E731
            before - np.float32(ROW_LR) * grads
        )
        self.sched.register(
            "row_ryw",
            prober.make_row_ryw_probe(self.canary_client,
                                      expect_fn=expect),
            interval_secs=0,
        )
        self.sched.register(
            "reshard_convergence",
            prober.make_reshard_convergence_probe(row_addrs),
            interval_secs=0,
        )

        cid = prober.canary_id(1)
        predict = prober.make_router_predictor(
            f"localhost:{self.serving.router.port}",
            self.serving.feature_key, [[cid] * 10], timeout=3.0,
        )
        self.serving.wait_ready(predict)
        push_client = prober.RowCanaryClient(
            f"localhost:{self.serving.row_service.port}"
        )

        def push_canary(sign):
            dim = push_client.dim()
            push_client.push(
                np.array([cid], np.int64),
                np.full((1, dim), sign * 1e-3, np.float32),
            )

        self.sched.register(
            "serving_freshness",
            prober.make_serving_freshness_probe(
                predict, push_canary,
                deadline_secs=SERVING_DEADLINE_SECS,
            ),
            interval_secs=0,
        )

        append = prober.make_stream_appender(self.stream_dir)
        plane = self

        def watermark():
            part = plane.master.ingestor.render()["partitions"].get(
                prober.CANARY_STREAM_PARTITION
            )
            return None if part is None else int(part["committed"])

        self.sched.register(
            "stream_watermark",
            prober.make_stream_watermark_probe(
                append, watermark,
                deadline_secs=STREAM_DEADLINE_SECS,
            ),
            interval_secs=0,
        )
        self.sched.register(
            "dispatch_roundtrip",
            prober.make_dispatch_roundtrip_probe(
                f"localhost:{self.master_port}"
            ),
            interval_secs=0,
        )

    # -- faults ----------------------------------------------------------

    def kill_row_shard(self):
        # Shard 0 owns the low shard-map buckets, and canary ids land
        # there (2^62 % 8192 == 0).
        self.fleet.sigkill(0)

    def relaunch_row_shard(self):
        from elasticdl_tpu.chaos.quake_drill import _wait_shard

        self.fleet.relaunch(0)
        _wait_shard(self.row_ports[0])

    def crash_master(self):
        self.master.crash()

    def relaunch_master(self):
        from elasticdl_tpu.chaos.stream_drill import _Master

        # Fresh incarnation, same port, journal recovery — the
        # watermark closure reads self.master so it follows along.
        self.master = _Master(self.journal_dir, self.stream_dir,
                              self.master_port)

    # -- ticks -----------------------------------------------------------

    def tick(self) -> Dict[str, str]:
        """Run every probe once; returns {probe: "ok" | reason}."""
        out = {}
        for name in PROBES:
            record = self.sched.run_once(name)
            out[name] = "ok" if record["ok"] else (
                record["reason"] or "exception"
            )
        return out

    def statuses(self) -> Dict[str, str]:
        return {
            name: ent["status"]
            for name, ent in self.sched.render()["probes"].items()
        }

    def stop(self):
        self.worker.stop()
        self.serving.stop()
        try:
            self.master.shutdown()
        except Exception:
            pass
        self.fleet.stop_all()
        if self.incidents is not None:
            self.incidents.flush()


def _green_barrier(plane: _Plane, timeline: List[dict],
                   bound: int) -> Optional[int]:
    """Tick until every probe is green; returns the tick count or
    None when the bound elapsed first."""
    for i in range(bound):
        results = plane.tick()
        timeline.append({"results": results})
        if all(s == "green" for s in plane.statuses().values()):
            return i + 1
    return None


def run_twin(workdir: str, bundle: str) -> dict:
    """Kill-free twin: after the setup barrier, every tick of every
    probe must be green — the zero-false-positive half of the gate."""
    out = {"role": "twin", "problems": [], "timeline": []}
    plane = _Plane(workdir, bundle)
    try:
        setup = _green_barrier(plane, [], SETUP_BOUND_TICKS)
        if setup is None:
            out["problems"].append(
                f"twin never reached all-green within "
                f"{SETUP_BOUND_TICKS} setup ticks: {plane.statuses()}"
            )
            return out
        out["setup_ticks"] = setup
        failures = 0
        for _ in range(TWIN_TICKS):
            results = plane.tick()
            out["timeline"].append({"results": results})
            failures += sum(1 for v in results.values() if v != "ok")
        out["ticks"] = TWIN_TICKS
        out["failures"] = failures
        if failures:
            out["problems"].append(
                f"twin saw {failures} probe failure(s) with no fault "
                "injected (false positives)"
            )
        out["probes"] = plane.sched.render()["probes"]
    finally:
        plane.stop()
    return out


def run_faulted(workdir: str, bundle: str) -> dict:
    """Three fault windows; each must red the MATCHING probe within
    the detection bound and re-green after repair."""
    out = {"role": "faulted", "problems": [], "windows": [],
           "timeline": []}
    incident_dir = os.path.join(workdir, "incidents")
    plane = _Plane(workdir, bundle, incident_dir=incident_dir)
    faults = {
        "row_shard_kill": (plane.kill_row_shard,
                           plane.relaunch_row_shard),
        "serving_stall": (plane.serving.stall,
                          plane.serving.unstall),
        "master_kill": (plane.crash_master, plane.relaunch_master),
    }
    try:
        setup = _green_barrier(plane, [], SETUP_BOUND_TICKS)
        if setup is None:
            out["problems"].append(
                f"faulted run never reached all-green within "
                f"{SETUP_BOUND_TICKS} setup ticks: {plane.statuses()}"
            )
            return out
        out["setup_ticks"] = setup
        for window, probe in WINDOWS:
            fault, repair = faults[window]
            entry = {"window": window, "probe": probe,
                     "detect_ticks": None, "within_bound": False,
                     "recover_ticks": None, "collateral": []}
            logger.info("probe drill window %s: faulting", window)
            fault()
            collateral = set()
            for i in range(DETECT_BOUND_TICKS):
                results = plane.tick()
                out["timeline"].append(
                    {"window": window, "results": results}
                )
                statuses = plane.statuses()
                collateral |= {
                    n for n, s in statuses.items()
                    if s == "red" and n != probe
                }
                if statuses[probe] == "red":
                    entry["detect_ticks"] = i + 1
                    entry["within_bound"] = True
                    entry["reason"] = (
                        plane.sched.render()["probes"][probe]
                        ["last_reason"]
                    )
                    break
            entry["collateral"] = sorted(collateral)
            if not entry["within_bound"]:
                out["problems"].append(
                    f"{window}: probe {probe} did not red within "
                    f"{DETECT_BOUND_TICKS} ticks "
                    f"(status {plane.statuses()[probe]})"
                )
            logger.info("probe drill window %s: repairing", window)
            repair()
            recover = _green_barrier(
                plane, out["timeline"], GREEN_BOUND_TICKS
            )
            entry["recover_ticks"] = recover
            if recover is None:
                out["problems"].append(
                    f"{window}: plane never re-greened within "
                    f"{GREEN_BOUND_TICKS} ticks after repair: "
                    f"{plane.statuses()}"
                )
                break
            out["windows"].append(entry)
        out["probes"] = plane.sched.render()["probes"]
        out["incidents"] = _audit_incidents(
            incident_dir, [probe for _, probe in WINDOWS],
            out["problems"],
        )
    finally:
        plane.stop()
    return out


def _audit_incidents(incident_dir: str, expected_probes: List[str],
                     problems: List[str]) -> dict:
    """Each red transition must have captured a bundle whose rule is
    ``probe-<name>`` and whose alert carries the failing run's trace
    id (resolvable against the trace the bundle itself snapshots)."""
    found: Dict[str, dict] = {}
    if os.path.isdir(incident_dir):
        for name in sorted(os.listdir(incident_dir)):
            alert_path = os.path.join(incident_dir, name, "alert.json")
            if not os.path.isfile(alert_path):
                continue
            try:
                with open(alert_path) as fh:
                    alert = json.load(fh).get("alert", {})
            except (OSError, ValueError):
                continue
            rule = str(alert.get("rule", ""))
            if rule.startswith("probe-"):
                found[rule[len("probe-"):]] = {
                    "bundle": name,
                    "trace_id": str(alert.get("trace_id", "")),
                    "reason": str(alert.get("reason", "")),
                }
    for probe in expected_probes:
        if probe not in found:
            problems.append(
                f"no incident bundle captured for probe {probe}"
            )
        elif not found[probe]["trace_id"]:
            problems.append(
                f"incident bundle for probe {probe} carries no "
                "trace id"
            )
    return found


def _usage_verdict(problems: List[str]) -> dict:
    """Master-side attribution gate: canary traffic meters under the
    ``canary`` purpose and ONLY under it (the drill's master, row
    services, and router live in this process, so their request
    metering lands on the default registry)."""
    from elasticdl_tpu.observability import default_registry
    from elasticdl_tpu.observability.prober import CANARY_JOB

    canary_series = 0
    canary_requests = 0
    violations = []
    snapshot = default_registry().snapshot()
    for family in snapshot.get("families", []):
        if not family["name"].startswith("edl_tpu_usage_"):
            continue
        names = family.get("labelnames", [])
        if "job" not in names:
            # usage_handler_seconds meters by (purpose, method) only
            # (bounded axes) — no job to cross-check.
            continue
        for series in family.get("series", []):
            labels = dict(zip(names, series.get("labels", [])))
            job = labels.get("job", "")
            purpose = labels.get("purpose", "")
            if job == CANARY_JOB:
                canary_series += 1
                if family["name"] == "edl_tpu_usage_requests_total":
                    canary_requests += int(series.get("value", 0))
                if purpose != "canary":
                    violations.append(
                        f"{family['name']}{labels} — canary job "
                        f"metered under purpose {purpose!r}"
                    )
            elif purpose == "canary":
                violations.append(
                    f"{family['name']}{labels} — purpose canary "
                    f"under foreign job {job!r}"
                )
    if canary_requests <= 0:
        problems.append(
            "no canary-principal requests metered in /usage"
        )
    problems.extend(violations)
    return {
        "canary_series": canary_series,
        "canary_requests": canary_requests,
        "violations": violations,
    }


def run_drill(workdir: str, seed: int = SEED) -> dict:
    from elasticdl_tpu.observability import prober, tracing

    os.makedirs(workdir, exist_ok=True)
    # Real trace ids for exemplars + incident bundles.
    from elasticdl_tpu.observability.tracing import FlightRecorder

    tracing.install_recorder(FlightRecorder(4096))
    bundle = export_probe_bundle(workdir, seed)
    try:
        logger.info("probe drill: kill-free twin")
        twin = run_twin(os.path.join(workdir, "twin"), bundle)
        logger.info("probe drill: faulted run (3 windows)")
        faulted = run_faulted(
            os.path.join(workdir, "faulted"), bundle
        )
    finally:
        tracing.uninstall_recorder()
    problems = (
        [f"twin: {p}" for p in twin["problems"]]
        + [f"faulted: {p}" for p in faulted["problems"]]
    )
    usage = _usage_verdict(problems)
    report = {
        "drill": "probe",
        "seed": seed,
        "config": {
            "probes": list(PROBES),
            "windows": [list(w) for w in WINDOWS],
            "unhealthy_after": UNHEALTHY_AFTER,
            "detect_bound_ticks": DETECT_BOUND_TICKS,
            "green_bound_ticks": GREEN_BOUND_TICKS,
            "twin_ticks": TWIN_TICKS,
            "canary_id_base": prober.CANARY_ID_BASE,
            "canary_id_span": prober.CANARY_ID_SPAN,
            "canary_partition": prober.CANARY_STREAM_PARTITION,
            "canary_job": prober.CANARY_JOB,
        },
        "twin": twin,
        "faulted": faulted,
        "usage": usage,
        "problems": problems,
        "passed": not problems,
    }
    return report


def main(argv=None) -> int:
    parser = argparse.ArgumentParser("elasticdl_tpu-probe-drill")
    parser.add_argument("--seed", type=int, default=SEED)
    parser.add_argument("--workdir", required=True)
    parser.add_argument("--report", default="PROBE_DRILL.json")
    args = parser.parse_args(argv)

    report = run_drill(args.workdir, seed=args.seed)
    with open(args.report, "w") as fh:
        json.dump(report, fh, indent=2, sort_keys=True, default=str)
        fh.write("\n")
    for entry in report["faulted"].get("windows", []):
        logger.info(
            "probe drill window %-16s -> %s red in %s tick(s) "
            "(reason %s), re-green in %s",
            entry["window"], entry["probe"], entry["detect_ticks"],
            entry.get("reason", "?"), entry["recover_ticks"],
        )
    logger.info(
        "probe drill: %s; twin %d tick(s) %d failure(s); report %s",
        "PASS" if report["passed"] else "FAIL",
        report["twin"].get("ticks", 0),
        report["twin"].get("failures", -1), args.report,
    )
    if not report["passed"]:
        for problem in report["problems"]:
            logger.error("probe drill problem: %s", problem)
    return 0 if report["passed"] else 1


if __name__ == "__main__":
    sys.exit(main())
