"""Hot-standby failover drill: SIGKILL real master processes and gate
the takeover.

``make failover-smoke`` / ``make failover-bench``
(docs/fault_tolerance.md "Hot standby & failover"):

The driver spawns a REAL primary master process (this module's
``serve`` subcommand: a journaled control plane — TaskDispatcher +
EvaluationService + MasterServicer over localhost gRPC, no model, no
JAX — the plane the failover protects) plus a warm standby process
tailing the same journal, then drives one scripted worker through a
deterministic task schedule and SIGKILLs the primary at the three
nastiest points:

1. **mid-lease** — the worker holds a leased training task; the lease
   must survive into the promoted standby and the late report must be
   accepted (exactly once, no re-train);
2. **mid-eval-round** — an open ``EvaluationJob`` with partially
   folded raw outputs; the promoted standby must resume the SAME
   round (journaled ``eval_round``/``eval_fold`` records) and close
   it with metrics equal to a never-killed twin's;
3. **mid-resize-barrier** — a pending resize directive with no acks
   yet; the promoted standby must re-offer it and the barrier must
   still complete.

A fourth scenario proves the fencing is structural, not probabilistic:
the primary is **partitioned** (its heartbeat endpoint wedged, the
process alive) so the standby fences and takes over while the old
incarnation still serves — the zombie's ``report_task_result`` and
``get_task`` must answer ``stale_master`` (its journal appends are
rejected under the fence flock), and the journal fsck must show no
post-fence records from the dead generation.

Downtime (last ack from the old master → first task dispatched by the
new one, measured at the worker) is compared against a
**restart-and-replay** baseline: the same schedule, same kill points,
but recovery = detect + spawn a fresh master process that replays the
journal cold — what the job paid before this PR. Gates
(FAILOVER_DRILL.json): standby mean downtime ≥5x lower, sub-second
worst case, zero task loss/duplication, the open eval round
surviving, final dispatcher state field-equal to the twin, and the
zombie provably fenced.
"""

import argparse
import json
import os
import signal
import socket
import subprocess
import sys
import threading
import time
from typing import Dict, List, Optional

import numpy as np

from elasticdl_tpu.common.log_utils import get_logger

logger = get_logger("failover_drill")

# ---- the drill job's fixed control-plane config -------------------------

RECORDS = 120
EVAL_RECORDS = 16
PER_TASK = 4
EVAL_STEPS = 40  # model versions between eval rounds
SEED = 5


def _dispatcher_factory():
    from elasticdl_tpu.master.task_dispatcher import TaskDispatcher

    return TaskDispatcher(
        training_shards={"train": (0, RECORDS)},
        evaluation_shards={"val": (0, EVAL_RECORDS)},
        records_per_task=PER_TASK,
        num_epochs=1,
        shuffle=False,
        seed=SEED,
    )


def _metrics_fns():
    return {
        "mean_out": lambda labels, outputs: float(
            np.mean(np.asarray(outputs, np.float64))
        )
    }


def _eval_state(eval_service) -> dict:
    """Comparable snapshot of the evaluation service (driver asserts
    round survival / twin equality through this)."""
    job = eval_service._eval_job
    open_round = None
    if job is not None:
        open_round = {
            "model_version": int(job.model_version),
            "total_tasks": int(job._total_tasks),
            "completed": int(job._completed_tasks),
            "folded": sorted(int(t) for t in job._folded_tasks),
        }
    return {
        "open": open_round,
        "last_eval_version": int(eval_service._last_eval_version),
        "completed_results": {
            str(v): dict(m)
            for v, m in sorted(eval_service.completed_results.items())
        },
    }


class _ControlPlane:
    """One master incarnation's assembly (shared by the primary role
    and the standby's promotion)."""

    def __init__(self, dispatcher, journal):
        from elasticdl_tpu.master.evaluation_service import (
            EvaluationService,
        )
        from elasticdl_tpu.master.servicer import MasterServicer

        self.dispatcher = dispatcher
        self.eval_service = EvaluationService(
            dispatcher, _metrics_fns(), eval_steps=EVAL_STEPS
        )
        self.servicer = MasterServicer(
            dispatcher, self.eval_service, journal=journal,
            generation=journal.generation if journal else 0,
        )
        self._paused = threading.Event()

    def handlers(self) -> dict:
        handlers = self.servicer.handlers()
        handlers["ping"] = self._ping
        handlers["drill_export"] = self._export
        handlers["drill_pause"] = self._pause
        handlers["drill_begin_resize"] = self._begin_resize
        return handlers

    # ping the standby's heartbeat can partition away (zombie
    # scenario): pausing makes ONLY the heartbeat fail while worker
    # RPCs keep flowing — the classic partial partition.
    def _ping(self, request: dict) -> dict:
        if self._paused.is_set():
            raise RuntimeError("drill partition: heartbeat wedged")
        return {"ok": True}

    def _pause(self, request: dict) -> dict:
        self._paused.set()
        return {"ok": True}

    def _export(self, request: dict) -> dict:
        return {
            "state": self.dispatcher.export_state(),
            "eval": _eval_state(self.eval_service),
            "resize": self.servicer.resize_status() is not None,
            "finished": self.dispatcher.finished(),
            "generation": self.servicer.generation,
            "pid": os.getpid(),
        }

    def _begin_resize(self, request: dict) -> dict:
        resize_id = self.servicer.begin_resize(
            dict(request.get("spec") or {"mesh": [1]}),
            direction="drill",
        )
        return {"resize_id": resize_id}

    def run_upkeep(self, poll_secs: float = 0.05):
        """The master run loop's barrier upkeep, minimized: complete
        pending resize barriers from the live worker set. Serves until
        killed (the drill's SIGKILL is the exit path)."""
        while True:
            time.sleep(poll_secs)
            if self.servicer.resize_status() is not None:
                live = list(self.servicer.worker_liveness())
                if live:
                    # Only once the fleet re-attached: right after a
                    # takeover the liveness map is empty, and an empty
                    # live set would complete the barrier with zero
                    # acks (the k8s path seeds membership from adopted
                    # pods instead).
                    self.servicer.maybe_complete_resize(live)


def _serve(args) -> int:
    """``serve`` subcommand: run one master process (primary or
    standby role) until SIGKILLed."""
    # The drill master stands in for the production entry point, so
    # it must pay the production BOOT cost: master/main.py pulls the
    # full framework (jax included) before it can recover anything.
    # A restart-and-replay replacement pays this import during the
    # outage; a standby paid it before the primary died — exactly the
    # asymmetry the drill measures.
    import elasticdl_tpu.master.main  # noqa: F401

    from elasticdl_tpu.comm.rpc import RpcServer
    from elasticdl_tpu.master.journal import (
        MasterJournal,
        recover_master_state,
    )
    from elasticdl_tpu.master.servicer import SERVICE_NAME

    if args.role == "primary":
        journal = MasterJournal(args.journal_dir)
        dispatcher = _dispatcher_factory()
        if journal.has_state():
            # Restart-and-replay path (the baseline the standby is
            # measured against): cold recovery through the same
            # sequence production uses.
            stats = recover_master_state(journal, dispatcher)
            plane = _ControlPlane(dispatcher, journal)
            plane.eval_service.restore_recovered(stats["eval"])
            plane.eval_service.attach_journal(journal)
            plane.servicer.model_version = stats["model_version"]
            plane.servicer.seed_task_start_times(
                list(dispatcher.doing_start_times())
            )
            if stats.get("resize"):
                plane.servicer.rearm_resize(stats["resize"])
        else:
            journal.open_generation()
            dispatcher.attach_journal(journal)
            plane = _ControlPlane(dispatcher, journal)
            plane.eval_service.attach_journal(journal)
        server = RpcServer(
            f"localhost:{args.port}",
            {SERVICE_NAME: plane.handlers()},
        ).start()
        logger.info("drill %s serving on %d (pid %d)",
                    args.role, server.port, os.getpid())
        plane.run_upkeep()
        return 0

    # standby role: tail + heartbeat, promote on missed beats.
    from elasticdl_tpu.master.standby import StandbyMaster

    plane_box: Dict[str, _ControlPlane] = {}

    def assemble(dispatcher, journal):
        plane = _ControlPlane(dispatcher, journal)
        plane_box["plane"] = plane
        return plane.eval_service, plane.servicer

    def handlers_factory(servicer):
        return plane_box["plane"].handlers()

    standby = StandbyMaster(
        args.journal_dir,
        _dispatcher_factory,
        assemble,
        primary_addr=args.primary_addr,
        serve_addr=f"localhost:{args.port}",
        heartbeat_secs=args.heartbeat_secs,
        miss_threshold=args.miss_threshold,
        poll_secs=args.poll_secs,
        handlers_factory=handlers_factory,
    )
    logger.info("drill standby tailing %s, heartbeating %s (pid %d)",
                args.journal_dir, args.primary_addr, os.getpid())
    if args.ready_file:
        # Attach handshake: the driver must not kill the primary while
        # this process is still booting (python + grpc imports dwarf
        # the takeover itself) — that would measure interpreter
        # startup, not failover. Ready = one confirmed heartbeat and
        # one journal poll.
        deadline = time.monotonic() + 60.0
        while time.monotonic() < deadline:
            if standby.heartbeat():
                break
            time.sleep(0.05)
        standby._misses = 0
        standby.poll_journal()
        with open(args.ready_file, "w") as fh:
            fh.write(str(os.getpid()))
    promoted = standby.run()
    if not promoted:
        return 1
    plane_box["plane"].run_upkeep()
    return 0


# ---- driver: scripted worker ---------------------------------------------


class ScriptedWorker(threading.Thread):
    """One deterministic worker driving the job over real gRPC, with
    driver-controlled pause points (so kills land mid-lease /
    mid-eval-round / mid-resize-barrier, not somewhere near them).
    Tracks per-outage downtime: last successful RPC before the streak
    → first get_task returning a REAL task after it."""

    def __init__(self, addrs: str, pauses: Dict[str, threading.Event]):
        super().__init__(daemon=True, name="drill-worker")
        self.addrs = addrs
        # pause name -> (reached event set by us, resume event set by
        # the driver). Pauses fire once each.
        self.pauses = pauses
        self.reached: Dict[str, threading.Event] = {
            name: threading.Event() for name in pauses
        }
        self.outages: List[dict] = []
        # Monotonic timestamps of every REAL task dispatch received —
        # the driver derives per-failover downtime as (first dispatch
        # after the kill) - (kill time).
        self.dispatch_times: List[float] = []
        self.error: Optional[BaseException] = None
        self.version = 0
        self.eval_folds = 0
        self.trained_records = 0
        self.acked_resizes: List[int] = []
        self._fired = set()

    def _pause(self, name: str):
        if name in self.pauses and name not in self._fired:
            self._fired.add(name)
            self.reached[name].set()
            self.pauses[name].wait(timeout=60.0)

    def run(self):
        try:
            self._run()
        except BaseException as exc:  # surfaced by the driver
            self.error = exc

    def _run(self):
        from elasticdl_tpu.comm.rpc import (
            RpcError,
            decorrelated_jitter,
        )
        from elasticdl_tpu.common.constants import TaskType
        from elasticdl_tpu.worker.master_client import MasterClient

        client = MasterClient(
            self.addrs, worker_id=0, connect_timeout=30, retries=3
        )
        state = {"last_ok": time.monotonic(), "outage": None,
                 "delay": 0.0}

        def note_ok():
            state["last_ok"] = time.monotonic()
            state["delay"] = 0.0

        def note_fail_and_wait():
            # Outage clock starts at the LAST ack the old master gave
            # — the drill's downtime definition.
            if state["outage"] is None:
                state["outage"] = state["last_ok"]
            state["delay"] = decorrelated_jitter(
                state["delay"], base=0.05, cap=0.3
            )
            time.sleep(state["delay"])
            client.reconnect()

        def rideout(fn):
            """Retry an RPC until a live master accepts it (the
            worker-side report ride-out: a lease must be re-reported,
            never abandoned)."""
            while True:
                try:
                    result = fn()
                    note_ok()
                    return result
                except RpcError:
                    note_fail_and_wait()

        while True:
            try:
                task, finished = client.get_task()
            except RpcError:
                note_fail_and_wait()
                continue
            note_ok()
            if task is not None and task.type != TaskType.WAIT:
                self.dispatch_times.append(time.monotonic())
            if state["outage"] is not None and task is not None and (
                task.type != TaskType.WAIT
            ):
                # First real dispatch from the new master closes the
                # outage window.
                now = time.monotonic()
                self.outages.append({
                    "last_ack": state["outage"],
                    "recovered": now,
                    "downtime_secs": now - state["outage"],
                })
                state["outage"] = None
            if client.pending_resize:
                resize_id = int(client.pending_resize["resize_id"])
                self._pause("resize_offered")
                if rideout(lambda: client.report_resize(resize_id)):
                    self.acked_resizes.append(resize_id)
            if finished:
                client.close()
                return
            if task is None or task.type == TaskType.WAIT:
                time.sleep(0.02)
                continue
            if task.type == TaskType.TRAINING:
                n = task.end - task.start
                self._pause("holding_lease")
                self.version += n
                version = self.version
                rideout(lambda: client.report_version(version))
                rideout(lambda: client.report_task_result(task.task_id))
                self.trained_records += n
            elif task.type == TaskType.EVALUATION:
                ids = np.arange(task.start, task.end,
                                dtype=np.float64)
                rideout(lambda: client.report_evaluation_metrics(
                    ids * 0.1, ids, task_id=task.task_id
                ))
                self.eval_folds += 1
                self._pause("eval_folded")
                rideout(lambda: client.report_task_result(task.task_id))
            else:  # TRAIN_END_CALLBACK
                rideout(lambda: client.report_task_result(task.task_id))


# ---- driver: process + measurement harness -------------------------------


def _free_ports(n: int) -> List[int]:
    ports, socks = [], []
    for _ in range(n):
        s = socket.socket()
        s.bind(("localhost", 0))
        ports.append(s.getsockname()[1])
        socks.append(s)
    for s in socks:
        s.close()
    return ports


class Fleet:
    """Spawn/kill the drill's real master processes."""

    def __init__(self, workdir: str, heartbeat_secs: float,
                 miss_threshold: int, poll_secs: float):
        self.workdir = workdir
        self.journal_dir = os.path.join(workdir, "journal")
        self.heartbeat_secs = heartbeat_secs
        self.miss_threshold = miss_threshold
        self.poll_secs = poll_secs
        self.procs: List[subprocess.Popen] = []

    def _spawn(self, role: str, port: int, primary_addr: str = "",
               ready_file: str = "") -> subprocess.Popen:
        cmd = [
            sys.executable, "-m",
            "elasticdl_tpu.chaos.failover_drill", "serve",
            "--role", role, "--port", str(port),
            "--journal_dir", self.journal_dir,
            "--heartbeat_secs", str(self.heartbeat_secs),
            "--miss_threshold", str(self.miss_threshold),
            "--poll_secs", str(self.poll_secs),
        ]
        if primary_addr:
            cmd += ["--primary_addr", primary_addr]
        if ready_file:
            cmd += ["--ready_file", ready_file]
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        log = open(
            os.path.join(self.workdir, f"{role}-{port}.log"), "w"
        )
        proc = subprocess.Popen(
            cmd, env=env,
            # The package root, not the driver's cwd: the drill must
            # run from anywhere (make failover-smoke uses a tempdir).
            cwd=os.path.dirname(os.path.dirname(os.path.dirname(
                os.path.abspath(__file__)
            ))),
            stdout=log, stderr=subprocess.STDOUT,
        )
        proc._drill_log = log
        self.procs.append(proc)
        return proc

    def spawn_primary(self, port: int) -> subprocess.Popen:
        return self._spawn("primary", port)

    def spawn_standby(self, port: int,
                      primary_port: int) -> subprocess.Popen:
        ready = os.path.join(self.workdir, f"standby-{port}.ready")
        proc = self._spawn(
            "standby", port, primary_addr=f"localhost:{primary_port}",
            ready_file=ready,
        )
        proc._drill_ready = ready
        return proc

    @staticmethod
    def wait_attached(proc: subprocess.Popen,
                      timeout_secs: float = 60.0):
        """Block until the standby confirmed its first heartbeat —
        killing the primary earlier would measure interpreter boot,
        not failover."""
        ready = getattr(proc, "_drill_ready", None)
        if ready is None:
            return
        deadline = time.monotonic() + timeout_secs
        while time.monotonic() < deadline:
            if os.path.exists(ready):
                return
            if proc.poll() is not None:
                raise RuntimeError(
                    "standby process died before attaching"
                )
            time.sleep(0.02)
        raise TimeoutError("standby never attached to the primary")

    @staticmethod
    def sigkill(proc: subprocess.Popen):
        os.kill(proc.pid, signal.SIGKILL)
        proc.wait(timeout=30)

    def stop_all(self):
        for proc in self.procs:
            if proc.poll() is None:
                try:
                    os.kill(proc.pid, signal.SIGKILL)
                except OSError:
                    pass
        for proc in self.procs:
            try:
                proc.wait(timeout=10)
            except Exception:
                pass
            log = getattr(proc, "_drill_log", None)
            if log is not None:
                log.close()


def _stub(port: int):
    from elasticdl_tpu.comm.rpc import RpcStub
    from elasticdl_tpu.master.servicer import SERVICE_NAME

    return RpcStub(f"localhost:{port}", SERVICE_NAME, max_retries=0)


def _call(port: int, method: str, timeout: float = 5.0, **fields):
    stub = _stub(port)
    try:
        return stub.call(method, timeout=timeout, **fields)
    finally:
        stub.close()


def _wait_serving(port: int, deadline_secs: float = 60.0,
                  method: str = "drill_export") -> dict:
    t0 = time.monotonic()
    last = None
    while time.monotonic() - t0 < deadline_secs:
        try:
            return _call(port, method, timeout=2.0)
        except Exception as exc:
            last = exc
            time.sleep(0.05)
    raise TimeoutError(f"port {port} never served: {last}")


def _normalized(state: dict) -> dict:
    """Dispatcher export with run-order-volatile fields normalized
    (same discipline as tests/test_journal.py): the resolved ledger
    compares as a sorted set, RNG state is config-determined (no
    shuffle in the drill)."""
    out = dict(state)
    out["resolved"] = sorted(
        [tid, task, wid, rq] for tid, task, wid, rq
        in state.get("resolved", [])
    )
    out.pop("rng", None)
    return out


def run_drill(workdir: str, mode: str, heartbeat_secs: float = 0.05,
              miss_threshold: int = 2, poll_secs: float = 0.05,
              zombie: bool = True) -> dict:
    """One full scripted schedule under ``mode``:

    - "standby": warm standbys pre-spawned; kills → hot takeover.
    - "restart": no standbys; the driver's monitor detects the death
      with the SAME heartbeat parameters, then spawns a replacement
      process that recovers cold (restart-and-replay baseline).
    - "twin": no kills at all — the fault-free oracle.
    """
    os.makedirs(workdir, exist_ok=True)
    fleet = Fleet(workdir, heartbeat_secs, miss_threshold, poll_secs)
    # Port plan: [0]=primary, [1..4]=successor masters, all of them in
    # the workers' re-resolve list up front.
    ports = _free_ports(6)
    result = {
        "mode": mode,
        "failovers": [],
        "problems": [],
        "zombie": None,
    }
    try:
        fleet.spawn_primary(ports[0])
        _wait_serving(ports[0])
        current = 0  # index into ports of the serving master

        def next_master(partition_only: bool = False) -> dict:
            """Kill (or partition) the current master and bring up its
            successor per ``mode``; returns timing info."""
            nonlocal current
            old_port = ports[current]
            old_proc = fleet.procs[-1] if mode == "restart" else None
            new_idx = current + 1
            if mode == "standby":
                # The standby must be ATTACHED before the kill, or the
                # measurement includes its interpreter boot.
                Fleet.wait_attached(standby_tracker["standby_proc"])
            t_kill = time.monotonic()
            if mode == "standby":
                # Standby already tailing (spawned below before the
                # kill); it promotes itself onto its own port.
                if partition_only:
                    _call(old_port, "drill_pause")
                else:
                    fleet.sigkill(standby_tracker["primary_proc"])
            else:
                if partition_only:
                    _call(old_port, "drill_pause")
                else:
                    fleet.sigkill(fleet.procs[-1])
                # Restart baseline: detect via the same heartbeat
                # budget, then cold-spawn the replacement.
                misses = 0
                while misses < miss_threshold:
                    try:
                        _call(old_port, "ping",
                              timeout=max(0.5, heartbeat_secs))
                        misses = 0
                    except Exception:
                        misses += 1
                    time.sleep(heartbeat_secs)
                fleet.spawn_primary(ports[new_idx])
            info = _wait_serving(ports[new_idx])
            current = new_idx
            if mode == "standby":
                standby_tracker["primary_proc"] = (
                    standby_tracker["standby_proc"]
                )
                # Pre-arm the NEXT standby against the new master.
                if new_idx + 1 < len(ports):
                    standby_tracker["standby_proc"] = (
                        fleet.spawn_standby(
                            ports[new_idx + 1], ports[new_idx]
                        )
                    )
            return {
                "old_port": old_port,
                "new_port": ports[new_idx],
                "t_kill": t_kill,
                "serving_at": time.monotonic(),
                "new_generation": int(info.get("generation", -1)),
            }

        standby_tracker = {}
        if mode == "standby":
            standby_tracker["primary_proc"] = fleet.procs[-1]
            standby_tracker["standby_proc"] = fleet.spawn_standby(
                ports[1], ports[0]
            )

        kills = mode in ("standby", "restart")
        pauses = {}
        if kills:
            pauses = {
                "holding_lease": threading.Event(),
                "eval_folded": threading.Event(),
                "resize_offered": threading.Event(),
            }
        worker = ScriptedWorker(
            ",".join(f"localhost:{p}" for p in ports[:5]), pauses
        )
        worker.start()

        if kills:
            # ---- failover 1: SIGKILL mid-lease -----------------------
            if not worker.reached["holding_lease"].wait(60.0):
                raise TimeoutError("worker never held a lease")
            pre = _call(ports[current], "drill_export")
            if not pre["state"]["doing"]:
                result["problems"].append(
                    "mid-lease kill: no task was leased"
                )
            info = next_master()
            info["scenario"] = "sigkill_mid_lease"
            result["failovers"].append(info)
            pauses["holding_lease"].set()

            # ---- failover 2: SIGKILL mid-eval-round ------------------
            if not worker.reached["eval_folded"].wait(120.0):
                raise TimeoutError("worker never folded eval outputs")
            pre = _call(ports[current], "drill_export")
            pre_round = pre["eval"]["open"]
            if pre_round is None:
                result["problems"].append(
                    "mid-eval kill: no round was open"
                )
            info = next_master()
            info["scenario"] = "sigkill_mid_eval_round"
            post = _call(ports[current], "drill_export")
            post_round = post["eval"]["open"]
            if pre_round is not None and (
                post_round is None
                or post_round["model_version"]
                != pre_round["model_version"]
                or post_round["folded"] != pre_round["folded"]
                or post_round["completed"] < pre_round["completed"]
            ):
                result["problems"].append(
                    "open eval round did not survive the failover: "
                    f"pre={pre_round} post={post_round}"
                )
            info["eval_round_survived"] = (
                pre_round is not None and post_round is not None
            )
            result["failovers"].append(info)
            pauses["eval_folded"].set()

            # ---- failover 3: SIGKILL mid-resize-barrier --------------
            _call(ports[current], "drill_begin_resize",
                  spec={"mesh": [1, 1]})
            if not worker.reached["resize_offered"].wait(120.0):
                raise TimeoutError("worker never saw the resize offer")
            info = next_master()
            info["scenario"] = "sigkill_mid_resize_barrier"
            post = _call(ports[current], "drill_export")
            if not post["resize"]:
                result["problems"].append(
                    "pending resize barrier was not re-armed after "
                    "the failover"
                )
            result["failovers"].append(info)
            pauses["resize_offered"].set()

            # ---- scenario 4: zombie primary (partition) --------------
            # Standby mode only: a cold restart spawned NEXT TO a
            # partitioned-but-alive primary is exactly the split
            # brain the fence exists to prevent — the baseline mode
            # has no fence publisher, so the scenario only proves
            # things about the standby path.
            if zombie and mode == "standby":
                zombie_port = ports[current]
                info = next_master(partition_only=True)
                info["scenario"] = "zombie_partition"
                result["failovers"].append(info)
                result["zombie"] = _probe_zombie(zombie_port)

        worker.join(timeout=240.0)
        if worker.is_alive():
            raise TimeoutError("scripted worker never drained the job")
        if worker.error is not None:
            raise worker.error

        final = _call(ports[current], "drill_export")
        result["final_state"] = _normalized(final["state"])
        result["final_eval"] = final["eval"]
        result["resize_pending_at_end"] = bool(final["resize"])
        result["trained_records"] = int(worker.trained_records)
        result["outages"] = worker.outages
        # Downtime per failover: the kill instant → the first real
        # task the fleet received from ANY master afterwards. (The
        # worker-side outage windows above are diagnostics; they
        # include driver choreography waits that are not recovery
        # cost.)
        downtimes = []
        for info in result["failovers"]:
            after = [
                t for t in worker.dispatch_times
                if t > info["t_kill"]
            ]
            if after:
                downtimes.append(round(after[0] - info["t_kill"], 4))
        result["downtimes_secs"] = downtimes
        # fsck the journal the run left behind (new record kinds +
        # fence monotonicity).
        sys.path.insert(0, os.path.join(
            os.path.dirname(os.path.dirname(os.path.dirname(
                os.path.abspath(__file__)))), "tools",
        ))
        from check_journal import check_journal

        result["fsck"] = check_journal(fleet.journal_dir)
        return result
    finally:
        fleet.stop_all()


def _probe_zombie(port: int) -> dict:
    """The fenced-but-alive old master must reject everything with
    ``stale_master`` — it can neither hand out work nor resolve it."""
    out = {"port": port}
    try:
        resp = _call(port, "report_task_result", task_id=1,
                     err_reason="", worker_id=0)
        out["report_rejected"] = bool(
            resp.get("stale_master") and not resp.get("accepted")
        )
    except Exception as exc:
        # A dead-on-arrival zombie also cannot resolve tasks, but the
        # drill wants the LIVE rejection proven.
        out["report_rejected"] = False
        out["report_error"] = str(exc)
    try:
        resp = _call(port, "get_task", worker_id=0)
        out["dispatch_rejected"] = bool(
            resp.get("stale_master") and resp.get("task") is None
        )
    except Exception as exc:
        out["dispatch_rejected"] = False
        out["dispatch_error"] = str(exc)
    out["fenced"] = bool(
        out.get("report_rejected") and out.get("dispatch_rejected")
    )
    return out


# ---- gates + report -------------------------------------------------------

MIN_SPEEDUP = 5.0
MAX_STANDBY_DOWNTIME_SECS = 1.0


def _gate(report: dict) -> List[str]:
    problems = []
    twin = report["twin"]
    standby = report["standby"]
    restart = report["restart"]
    for run in (twin, standby, restart):
        problems += [f"{run['mode']}: {p}" for p in run["problems"]]
        if run["fsck"]:
            problems += [f"{run['mode']} fsck: {e}"
                         for e in run["fsck"]]
        if run["trained_records"] != RECORDS:
            problems.append(
                f"{run['mode']}: trained {run['trained_records']} "
                f"records, expected exactly {RECORDS} "
                "(task loss or duplication)"
            )
        if run["resize_pending_at_end"]:
            problems.append(
                f"{run['mode']}: resize barrier never completed"
            )
    for run in (standby, restart):
        if run["final_state"] != twin["final_state"]:
            diff = [
                k for k in set(run["final_state"])
                | set(twin["final_state"])
                if run["final_state"].get(k)
                != twin["final_state"].get(k)
            ]
            problems.append(
                f"{run['mode']}: final dispatcher state diverged "
                f"from the fault-free twin on fields {sorted(diff)}"
            )
        if run["final_eval"] != twin["final_eval"]:
            problems.append(
                f"{run['mode']}: final eval results diverged from "
                f"the twin ({run['final_eval']} vs "
                f"{twin['final_eval']})"
            )
    zombie = standby.get("zombie")
    if not (zombie and zombie.get("fenced")):
        problems.append(
            f"zombie primary was not provably fenced: {zombie}"
        )
    # Compare the three SIGKILL failovers only (the standby run's
    # fourth outage is the zombie partition, whose clock starts at
    # the fence, not a death — different semantics).
    down_s = standby["downtimes_secs"][:3]
    down_r = restart["downtimes_secs"][:3]
    if len(down_s) < 3:
        problems.append(
            f"standby run saw {len(down_s)} outage(s), expected >=3"
        )
    if len(down_r) < 3:
        problems.append(
            f"restart run saw {len(down_r)} outage(s), expected >=3"
        )
    if down_s and down_r:
        # Gates run on the MEDIAN over the kill schedule: three
        # samples on a shared CI box see scheduler noise (a peer
        # process booting mid-takeover), and one hiccup must not
        # decide a 5x structural comparison. Mean and max stay in the
        # report.
        med_s = sorted(down_s)[len(down_s) // 2]
        med_r = sorted(down_r)[len(down_r) // 2]
        report["downtime"] = {
            "standby_median_secs": round(med_s, 4),
            "standby_mean_secs": round(sum(down_s) / len(down_s), 4),
            "standby_max_secs": round(max(down_s), 4),
            "restart_median_secs": round(med_r, 4),
            "restart_mean_secs": round(sum(down_r) / len(down_r), 4),
            "speedup": round(med_r / med_s, 2) if med_s else None,
            "min_speedup_gate": MIN_SPEEDUP,
            "max_standby_downtime_gate_secs":
                MAX_STANDBY_DOWNTIME_SECS,
        }
        if med_r < MIN_SPEEDUP * med_s:
            problems.append(
                f"takeover downtime not >={MIN_SPEEDUP}x better: "
                f"standby median {med_s:.3f}s vs restart-and-replay "
                f"median {med_r:.3f}s"
            )
        if med_s > MAX_STANDBY_DOWNTIME_SECS:
            problems.append(
                f"standby takeover not sub-second: median downtime "
                f"{med_s:.3f}s"
            )
    return problems


def main(argv=None) -> int:
    parser = argparse.ArgumentParser("elasticdl_tpu-failover-drill")
    sub = parser.add_subparsers(dest="command", required=True)
    serve = sub.add_parser("serve")
    serve.add_argument("--role", choices=["primary", "standby"],
                       required=True)
    serve.add_argument("--port", type=int, required=True)
    serve.add_argument("--journal_dir", required=True)
    serve.add_argument("--primary_addr", default="")
    serve.add_argument("--heartbeat_secs", type=float, default=0.05)
    serve.add_argument("--miss_threshold", type=int, default=2)
    serve.add_argument("--poll_secs", type=float, default=0.05)
    serve.add_argument("--ready_file", default="")

    run = sub.add_parser("run")
    run.add_argument("--workdir", required=True)
    run.add_argument("--report", default="FAILOVER_DRILL.json")
    run.add_argument("--heartbeat_secs", type=float, default=0.05)
    run.add_argument("--miss_threshold", type=int, default=2)
    args = parser.parse_args(argv)

    if args.command == "serve":
        return _serve(args)

    report = {"drill": "hot_standby_failover",
              "config": {
                  "records": RECORDS, "eval_records": EVAL_RECORDS,
                  "per_task": PER_TASK, "eval_steps": EVAL_STEPS,
                  "heartbeat_secs": args.heartbeat_secs,
                  "miss_threshold": args.miss_threshold,
              }}
    for mode in ("twin", "standby", "restart"):
        logger.info("failover drill: %s run", mode)
        report[mode] = run_drill(
            os.path.join(args.workdir, mode), mode,
            heartbeat_secs=args.heartbeat_secs,
            miss_threshold=args.miss_threshold,
        )
    problems = _gate(report)
    report["problems"] = problems
    report["passed"] = not problems
    with open(args.report, "w") as fh:
        json.dump(report, fh, indent=2, sort_keys=True, default=str)
        fh.write("\n")
    logger.info(
        "failover drill: %s%s; report %s",
        "PASS" if report["passed"] else "FAIL",
        "" if report["passed"]
        else f" problems: {'; '.join(map(str, problems))}",
        args.report,
    )
    return 0 if report["passed"] else 1


if __name__ == "__main__":
    sys.exit(main())
