"""Streaming ingestion drill: kill a worker AND a row shard in one
window and require the train→serve loop to close anyway.

``make stream-smoke`` (docs/online_learning.md "Chaos drill"):

1. **Kill drill** — a live ``data/stream.py`` file-tail stream (two
   partitions, appended throughout the run) feeds a streaming-mode
   master (real ``MasterJournal`` + ``TaskDispatcher(streaming=True)``
   + ``StreamIngestor`` + ``MasterServicer`` over localhost gRPC) whose
   tasks are trained by REAL worker subprocesses pushing row grads into
   the quake drill's REAL 2-shard row-service fleet (durable-ack WAL).
   Mid-run — in ONE window — the drill SIGKILLs a worker, SIGKILLs a
   row shard, and crashes the master. The dead shard's WAL is fsck'd,
   everything relaunches, and the recovered master must resume from
   the **journaled watermark** (never below what was committed, never
   re-acking an offset). Gates:

   - **read-your-writes** — every offset committed before the kills
     is visible to serving (non-zero rows on pull) right after the
     relaunch, before the pipeline finishes catching up;
   - **byte-equal** — the final row fleet (rows + optimizer slots)
     matches a kill-free twin that consumed the same stream: each
     stream offset maps to a unique row id pushed exactly once with a
     deterministic ``(client, seq)``, so a lost or double-applied push
     cannot hide (Adam's step counters diverge);
   - **watermarks** — final committed == appended end per partition,
     no pending (uncommitted) ranges, and a cold fold of the journal's
     STREAM/REPORT records agrees with the live dispatcher;
   - **fsck** — master journal + every WAL (including the dead
     incarnation's, checked BEFORE relaunch touches it) come back
     clean.

2. **Coexistence** — the streaming job enters the gang scheduler's
   job table like any tenant (``spec={"stream": True}`` through the
   default dispatcher factory): a higher-priority batch job arrives
   mid-stream, preempts the streaming gang, runs to completion, and
   yields back. The watermark must be monotone across the preemption,
   every stream offset applied exactly once, and the paused ingestor's
   backpressure meter must have ticked while the todo queue sat full.

Report: ``STREAM_DRILL.json``, validated by ``tools/check_stream.py``
(offset contiguity, watermark bounds, journal-vs-live coverage) in the
fsck lane.
"""

import argparse
import json
import os
import signal
import subprocess
import sys
import threading
import time
from typing import Dict, List

import numpy as np

from elasticdl_tpu.chaos.quake_drill import (
    TABLE,
    DIM,
    RowFleet,
    _capture_shard,
    _call_shard,
    _free_ports,
    _fsck_log,
    _pkg_root,
    _tables_equal,
    _wait_shard,
)
from elasticdl_tpu.common.log_utils import get_logger

logger = get_logger("stream_drill")

PARTITIONS = ("clicks", "views")
RECORDS_PER_PARTITION = 96
RECORDS_PER_TASK = 4
KILL_AT_COMMITTED = 48       # total committed records before the kills
MAX_TODO = 6                 # ingestor backpressure bound
NUM_WORKERS = 2
NUM_SHARDS = 2
ID_STRIDE = 40               # spreads ids across the 8192-bucket space
WORK_GRACE = 60.0            # worker ride-out for master/shard outages
DRILL_DEADLINE = 240.0

# Coexistence scenario sizing.
CO_STREAM_RECORDS = 64
CO_BATCH_TASKS = 6
CO_ROWS_PER_TASK = 4
CO_PREEMPT_AT = 16           # stream records committed before batch job
CO_MAX_STEPS = 2000


def _record_id(partition: str, offset: int) -> int:
    """One UNIQUE row id per stream offset: final table state is then
    order-independent even under Adam (each row sees exactly one
    update), so the kill run and its kill-free twin must land
    byte-equal."""
    p = PARTITIONS.index(partition)
    return (offset * len(PARTITIONS) + p) * ID_STRIDE + 7


def _grad_row(rid: int) -> List[float]:
    return [float((rid + j) % 23 + 1) for j in range(DIM)]


def _shard_of(rid: int, nshards: int) -> int:
    from elasticdl_tpu.embedding.shard_map import NUM_BUCKETS

    return (int(rid) % NUM_BUCKETS) * nshards // NUM_BUCKETS


# ---- `work` subcommand: one real streaming worker -------------------------


def _work(args) -> int:
    """Worker subprocess: lease stream tasks from the master, read the
    offset range from the SAME file tail, push each record's row grad
    to its home shard with a deterministic ``(client, seq)`` (a
    relaunched worker re-pushing a requeued task dedups server-side),
    then report. Rides out master/shard outages for ``--grace``."""
    from elasticdl_tpu.comm.rpc import RpcStub
    from elasticdl_tpu.data.stream import FileTailStream
    from elasticdl_tpu.embedding.row_service import (
        SERVICE_NAME as ROW_SERVICE,
    )
    from elasticdl_tpu.master.servicer import (
        SERVICE_NAME as MASTER_SERVICE,
    )

    source = FileTailStream(args.stream_dir)
    ports = [int(p) for p in args.shards.split(",")]
    master = RpcStub(args.master_addr, MASTER_SERVICE, max_retries=0)
    outage_deadline = [None]

    def call_master(method, **fields):
        while True:
            try:
                resp = master.call(method, timeout=5.0, **fields)
                outage_deadline[0] = None
                return resp
            except Exception as exc:
                now = time.monotonic()
                if outage_deadline[0] is None:
                    outage_deadline[0] = now + args.grace
                if now >= outage_deadline[0]:
                    raise TimeoutError(
                        f"master unreachable for {args.grace}s: {exc}"
                    )
                time.sleep(0.2)
                try:
                    master.reconnect()
                except Exception:
                    pass

    def push_shard(shard: int, ids, grads, client: str):
        stop_at = time.monotonic() + args.grace
        while True:
            stub = RpcStub(
                f"localhost:{ports[shard]}", ROW_SERVICE, max_retries=2
            )
            try:
                return stub.call(
                    "push_row_grads", timeout=10.0, table=TABLE,
                    ids=ids, grads=grads, client=client, seq=1,
                )
            except Exception:
                if time.monotonic() >= stop_at:
                    raise
                time.sleep(0.25)
            finally:
                stub.close()

    while True:
        resp = call_master("get_task", worker_id=args.worker_id)
        if resp.get("finished"):
            return 0
        task = resp.get("task")
        if not task or int(task.get("task_id", -1)) < 0:
            time.sleep(0.05)
            continue
        part = str(task["shard_name"])
        start, end = int(task["start"]), int(task["end"])
        stop_at = time.monotonic() + args.grace
        payloads = None
        while payloads is None:
            try:
                payloads = source.read(part, start, end)
            except Exception:
                if time.monotonic() >= stop_at:
                    raise
                time.sleep(0.05)
        per_shard: Dict[int, List[int]] = {}
        for payload in payloads:
            rid = int(json.loads(payload.decode())["id"])
            per_shard.setdefault(
                _shard_of(rid, len(ports)), []
            ).append(rid)
        for shard, ids in sorted(per_shard.items()):
            push_shard(
                shard, ids, [_grad_row(r) for r in ids],
                client=f"{part}:{start}:{end}:s{shard}",
            )
        call_master(
            "report_task_result",
            task_id=int(task["task_id"]),
            worker_id=args.worker_id,
            generation=int(resp.get("generation", 0)),
        )


class _WorkerFleet:
    """Spawn/SIGKILL/relaunch the drill's real worker processes."""

    def __init__(self, workdir: str, master_addr: str,
                 stream_dir: str, shard_ports: List[int]):
        self.workdir = workdir
        self.cmd_tail = [
            "--master_addr", master_addr,
            "--stream_dir", stream_dir,
            "--shards", ",".join(str(p) for p in shard_ports),
            "--grace", str(WORK_GRACE),
        ]
        self.procs: Dict[int, subprocess.Popen] = {}
        self._logs = []

    def spawn(self, worker_id: int) -> subprocess.Popen:
        log = open(os.path.join(
            self.workdir, f"worker{worker_id}-{len(self._logs)}.log"
        ), "w")
        self._logs.append(log)
        cmd = [
            sys.executable, "-m", "elasticdl_tpu.chaos.stream_drill",
            "work", "--worker_id", str(worker_id),
        ] + self.cmd_tail
        proc = subprocess.Popen(
            cmd, env=dict(os.environ, JAX_PLATFORMS="cpu"),
            cwd=_pkg_root(), stdout=log, stderr=subprocess.STDOUT,
        )
        self.procs[worker_id] = proc
        return proc

    def sigkill(self, worker_id: int):
        proc = self.procs[worker_id]
        os.kill(proc.pid, signal.SIGKILL)
        proc.wait(timeout=30)

    def join_all(self, timeout: float) -> Dict[int, int]:
        deadline = time.monotonic() + timeout
        codes = {}
        for worker_id, proc in self.procs.items():
            left = max(0.1, deadline - time.monotonic())
            try:
                codes[worker_id] = proc.wait(timeout=left)
            except subprocess.TimeoutExpired:
                codes[worker_id] = None
        return codes

    def stop_all(self):
        for proc in self.procs.values():
            if proc.poll() is None:
                try:
                    os.kill(proc.pid, signal.SIGKILL)
                except OSError:
                    pass
        for proc in self.procs.values():
            try:
                proc.wait(timeout=10)
            except Exception:
                pass
        for log in self._logs:
            log.close()


# ---- in-drill master incarnations -----------------------------------------


class _Master:
    """One in-process master incarnation over a real journal — fresh
    start or journal recovery, the same code paths master/main.py
    runs."""

    def __init__(self, journal_dir: str, stream_dir: str, port: int):
        from elasticdl_tpu.comm.rpc import RpcServer
        from elasticdl_tpu.data.stream import FileTailStream
        from elasticdl_tpu.master.journal import (
            MasterJournal,
            recover_master_state,
        )
        from elasticdl_tpu.master.servicer import (
            SERVICE_NAME,
            MasterServicer,
        )
        from elasticdl_tpu.master.stream_ingest import StreamIngestor
        from elasticdl_tpu.master.task_dispatcher import TaskDispatcher
        from elasticdl_tpu.observability.registry import MetricsRegistry

        self.registry = MetricsRegistry()
        self.journal = MasterJournal(journal_dir)
        self.dispatcher = TaskDispatcher(
            {}, records_per_task=RECORDS_PER_TASK, shuffle=False,
            streaming=True,
        )
        self.recovered = None
        if self.journal.has_state():
            self.recovered = recover_master_state(
                self.journal, self.dispatcher,
                metrics_registry=self.registry,
            )
        else:
            self.journal.open_generation()
            self.dispatcher.attach_journal(self.journal)
        self.servicer = MasterServicer(
            self.dispatcher, task_timeout_secs=30.0,
            journal=self.journal, generation=self.journal.generation,
        )
        if self.recovered is not None:
            self.servicer.model_version = self.recovered[
                "model_version"
            ]
            self.servicer.seed_task_start_times(
                list(self.dispatcher.doing_start_times())
            )
        self.ingestor = StreamIngestor(
            FileTailStream(stream_dir), self.dispatcher,
            max_todo=MAX_TODO, metrics_registry=self.registry,
        )
        self.server = RpcServer(
            f"localhost:{port}",
            {SERVICE_NAME: self.servicer.handlers()},
        ).start()
        self.ingestor.start(interval_secs=0.05)

    def crash(self):
        """Abandon the incarnation. The flock forces one concession to
        in-process simulation: the journal fd must close so the next
        incarnation can lock the dir (a real SIGKILL releases it for
        free) — no snapshot or graceful drain happens."""
        self.ingestor.stop()
        self.server.stop(grace=0)
        self.journal.close()

    def shutdown(self):
        self.ingestor.stop()
        self.server.stop(grace=2.0)
        self.journal.close()


def _journal_stream_fold(journal_dir: str) -> dict:
    """Cold fold of the journal's stream plane — what a recovering
    master (or the fsck lane) derives from the records alone."""
    from elasticdl_tpu.master.journal import (
        JOURNAL_FILE,
        REPORT,
        SNAPSHOT,
        STREAM,
        apply_stream_record,
        apply_stream_report_record,
        new_stream_state,
        normalize_stream_state,
        read_records,
    )

    state = new_stream_state()
    for _offset, _end, record in read_records(
        os.path.join(journal_dir, JOURNAL_FILE)
    ):
        if record["t"] == SNAPSHOT and record.get("stream") is not None:
            state = normalize_stream_state(record["stream"])
        elif record["t"] == STREAM:
            apply_stream_record(state, record)
        elif record["t"] == REPORT:
            apply_stream_report_record(state, record)
    return state


def _progress_view(progress: dict) -> dict:
    return {
        p: {"committed": int(part["committed"]),
            "next": int(part["next"]),
            "pending_ranges": len(part.get("pending") or {})}
        for p, part in sorted(progress.items())
    }


def _append_schedule(writer, upto: Dict[str, int], target: int):
    """Append one round-robin record per partition until ``target``."""
    appended = False
    for partition in PARTITIONS:
        offset = upto.get(partition, 0)
        if offset >= target:
            continue
        rid = _record_id(partition, offset)
        writer.append(
            partition, json.dumps({"id": rid}).encode(), fsync=False
        )
        upto[partition] = offset + 1
        appended = True
    return appended


def _pull_ids(port: int, ids: List[int]) -> np.ndarray:
    resp = _call_shard(
        port, "pull_rows", timeout=30.0, table=TABLE,
        ids=np.asarray(ids, np.int64),
    )
    return np.asarray(resp["rows"], np.float32)


def _check_journal(journal_dir: str) -> List[str]:
    sys.path.insert(0, os.path.join(_pkg_root(), "tools"))
    from check_journal import check_journal

    return check_journal(journal_dir)


# ---- scenario 1: the kill drill -------------------------------------------


def _pipeline_run(workdir: str, kill: bool) -> dict:
    """One full streaming pipeline run; ``kill=True`` runs the
    worker-SIGKILL + shard-SIGKILL + master-crash window."""
    from elasticdl_tpu.data.stream import StreamWriter

    label = "kill" if kill else "twin"
    root = os.path.join(workdir, label)
    stream_dir = os.path.join(root, "stream")
    journal_dir = os.path.join(root, "journal")
    os.makedirs(stream_dir, exist_ok=True)
    out = {"label": label, "events": [], "problems": []}

    shard_ports = _free_ports(NUM_SHARDS)
    (master_port,) = _free_ports(1)
    fleet = RowFleet(os.path.join(root, "rowfleet"))
    ckpt_dirs, wal_dirs = [], []
    for shard in range(NUM_SHARDS):
        ckpt = os.path.join(root, "row_ckpt", f"shard{shard}")
        wal = os.path.join(root, "row_wal", f"shard{shard}")
        ckpt_dirs.append(ckpt)
        wal_dirs.append(wal)
        # SGD: with one update per row, the final table is independent
        # of apply ORDER — Adam's per-table step counter would make the
        # kill run's different interleaving diverge from the twin even
        # with perfect exactly-once delivery.
        fleet.spawn(shard, shard_ports[shard], checkpoint_dir=ckpt,
                    push_log_dir=wal, ack="durable", group_ms=1.0,
                    optimizer="sgd")
    out["wal_dirs"] = list(wal_dirs)
    out["journal_dir"] = journal_dir

    writer = StreamWriter(stream_dir)
    upto: Dict[str, int] = {}
    # Seed enough records that the pipeline has work before workers
    # attach; the writer thread below keeps appending live.
    for _ in range(RECORDS_PER_TASK * 2):
        _append_schedule(writer, upto, RECORDS_PER_PARTITION)
    writer_done = threading.Event()

    def _writer_loop():
        while not writer_done.is_set():
            if not _append_schedule(
                writer, upto, RECORDS_PER_PARTITION
            ):
                return
            time.sleep(0.01)

    writer_thread = threading.Thread(
        target=_writer_loop, name=f"stream-writer-{label}", daemon=True
    )

    master = None
    workers = None
    try:
        for port in shard_ports:
            _wait_shard(port)
        master = _Master(journal_dir, stream_dir, master_port)
        workers = _WorkerFleet(
            root, f"localhost:{master_port}", stream_dir, shard_ports
        )
        for worker_id in range(NUM_WORKERS):
            workers.spawn(worker_id)
        writer_thread.start()

        def committed_total() -> int:
            return sum(
                int(p["committed"])
                for p in master.dispatcher.stream_progress().values()
            )

        deadline = time.monotonic() + DRILL_DEADLINE
        if kill:
            while committed_total() < KILL_AT_COMMITTED:
                if time.monotonic() > deadline:
                    raise TimeoutError(
                        f"never reached {KILL_AT_COMMITTED} committed "
                        f"records (at {committed_total()})"
                    )
                time.sleep(0.05)
            committed_at_kill = _progress_view(
                master.dispatcher.stream_progress()
            )
            out["committed_at_kill"] = committed_at_kill
            # THE window: worker 0, shard 0, and the master all die
            # before anything recovers.
            workers.sigkill(0)
            out["events"].append("worker 0 SIGKILL")
            fleet.sigkill(0)
            out["events"].append("shard 0 SIGKILL")
            master.crash()
            out["events"].append("master crashed (journal abandoned)")
            # Dead incarnation's WAL fsck BEFORE the relaunch replays
            # it (same discipline as the quake drill).
            out["dead_wal_fsck"] = _fsck_log(wal_dirs[0], ckpt_dirs[0])
            fleet.relaunch(0)
            _wait_shard(shard_ports[0])
            out["events"].append("shard 0 relaunched (WAL replayed)")
            master = _Master(journal_dir, stream_dir, master_port)
            resumed = _progress_view(
                master.dispatcher.stream_progress()
            )
            out["resumed_progress"] = resumed
            out["events"].append(
                "master recovered from journal "
                f"(generation {master.journal.generation})"
            )
            for partition, snap in committed_at_kill.items():
                got = resumed.get(partition, {}).get("committed", 0)
                if got < snap["committed"]:
                    out["problems"].append(
                        f"{partition}: recovered watermark {got} "
                        f"below the committed {snap['committed']} at "
                        "kill time"
                    )
            # The dead worker's leases must requeue (what the instance
            # manager does on pod death), or its in-flight ranges
            # would wedge the stream forever.
            master.dispatcher.recover_tasks(0)
            # Read-your-writes: every offset committed BEFORE the
            # kills must already be served back non-zero — acked means
            # durable on the row plane, across both SIGKILLs.
            ryw = {"checked": 0, "missing": 0}
            for partition, snap in committed_at_kill.items():
                ids = [
                    _record_id(partition, o)
                    for o in range(snap["committed"])
                ]
                per_shard: Dict[int, List[int]] = {}
                for rid in ids:
                    per_shard.setdefault(
                        _shard_of(rid, NUM_SHARDS), []
                    ).append(rid)
                for shard, shard_ids in per_shard.items():
                    rows = _pull_ids(shard_ports[shard], shard_ids)
                    ryw["checked"] += len(shard_ids)
                    zero = int(np.sum(~np.any(rows != 0.0, axis=1)))
                    ryw["missing"] += zero
            out["read_your_writes"] = ryw
            if ryw["missing"]:
                out["problems"].append(
                    f"read-your-writes violated: {ryw['missing']} of "
                    f"{ryw['checked']} committed offsets served zero "
                    "rows after the relaunch"
                )
            workers.spawn(0)
            out["events"].append("worker 0 relaunched")

        # Drain: every appended record committed, then close the
        # stream so the dispatcher finishes and workers exit.
        def all_committed() -> bool:
            progress = master.dispatcher.stream_progress()
            return all(
                int(progress.get(p, {}).get("committed", -1))
                == RECORDS_PER_PARTITION
                for p in PARTITIONS
            )

        while not all_committed():
            if time.monotonic() > deadline:
                raise TimeoutError(
                    "stream never fully committed: "
                    f"{_progress_view(master.dispatcher.stream_progress())}"
                )
            time.sleep(0.05)
        writer_done.set()
        master.ingestor.close()
        codes = workers.join_all(timeout=30.0)
        for worker_id, code in codes.items():
            if code != 0:
                out["problems"].append(
                    f"worker {worker_id} exited {code}, want 0"
                )
        out["final_progress"] = _progress_view(
            master.dispatcher.stream_progress()
        )
        out["stream_render"] = master.ingestor.render()
        out["backpressure_seconds"] = (
            master.ingestor.backpressure_seconds
        )
        master.shutdown()
        master = None
        out["journal_fold"] = _progress_view(
            {p: part for p, part in _journal_stream_fold(
                journal_dir
            )["partitions"].items()}
        )
        out["journal_fsck_errors"] = _check_journal(journal_dir)
        captures = []
        for shard in range(NUM_SHARDS):
            cap = _capture_shard(shard_ports[shard])
            captures.append(cap)
        out["push_counts"] = [c["push_count"] for c in captures]
        out["_captures"] = captures
        wal_fsck = []
        for shard in range(NUM_SHARDS):
            wal_fsck.append(
                dict(_fsck_log(wal_dirs[shard], ckpt_dirs[shard]),
                     dir=wal_dirs[shard])
            )
        out["wal_fsck"] = wal_fsck
    finally:
        writer_done.set()
        if writer_thread.is_alive():
            writer_thread.join(timeout=5.0)
        if workers is not None:
            workers.stop_all()
        if master is not None:
            try:
                master.shutdown()
            except Exception:
                pass
        fleet.stop_all()
        writer.close()
    return out


def _kill_scenario(workdir: str) -> dict:
    result = {"problems": []}
    killed = _pipeline_run(workdir, kill=True)
    twin = _pipeline_run(workdir, kill=False)
    for run in (killed, twin):
        result["problems"].extend(
            f"{run['label']}: {p}" for p in run["problems"]
        )

    # Byte-equality per shard against the kill-free twin.
    byte_problems = []
    for shard in range(NUM_SHARDS):
        byte_problems.extend(_tables_equal(
            killed["_captures"][shard]["tables"],
            twin["_captures"][shard]["tables"],
            f"shard {shard}",
        ))
    result["byte_equal"] = not byte_problems
    result["problems"].extend(byte_problems)
    if killed["push_counts"] != twin["push_counts"]:
        result["problems"].append(
            "applied push counts diverged from the twin "
            f"({killed['push_counts']} vs {twin['push_counts']}) — "
            "a push was lost or double-applied"
        )

    # Watermark bookkeeping: live vs journal fold, completeness,
    # contiguity (no pending ranges at the end).
    for run in (killed, twin):
        if run["final_progress"] != run["journal_fold"]:
            result["problems"].append(
                f"{run['label']}: journal stream fold disagrees with "
                f"the live dispatcher ({run['journal_fold']} vs "
                f"{run['final_progress']})"
            )
        for partition in PARTITIONS:
            part = run["final_progress"].get(partition, {})
            if part.get("committed") != RECORDS_PER_PARTITION:
                result["problems"].append(
                    f"{run['label']}: {partition} committed "
                    f"{part.get('committed')} != appended "
                    f"{RECORDS_PER_PARTITION}"
                )
            if part.get("pending_ranges"):
                result["problems"].append(
                    f"{run['label']}: {partition} finished with "
                    f"{part['pending_ranges']} uncommitted pending "
                    "ranges"
                )
        result["problems"].extend(
            f"{run['label']} journal fsck: {e}"
            for e in run["journal_fsck_errors"]
        )
        for wal in run["wal_fsck"]:
            result["problems"].extend(
                f"{run['label']} wal fsck {wal['dir']}: {e}"
                for e in wal["errors"]
            )
            if wal["records"] <= 0:
                result["problems"].append(
                    f"{run['label']} wal {wal['dir']}: no push "
                    "records — the WAL was not exercised"
                )
    dead = killed.get("dead_wal_fsck", {})
    result["problems"].extend(
        f"dead-incarnation wal fsck: {e}" for e in dead.get(
            "errors", ["missing"]
        )
    )
    for run in (killed, twin):
        run.pop("_captures", None)
    result["killed"] = killed
    result["twin"] = twin
    return result


# ---- scenario 2: coexistence under the gang scheduler ---------------------


def _coexist_scenario(workdir: str) -> dict:
    """Streaming tenant + batch tenant on one fleet: the batch job
    preempts, completes, and yields back; the watermark is monotone
    throughout and every stream offset lands exactly once."""
    from elasticdl_tpu.data.stream import FileTailStream, StreamWriter
    from elasticdl_tpu.master.journal import MasterJournal
    from elasticdl_tpu.master.scheduler import GangScheduler
    from elasticdl_tpu.master.servicer import MasterServicer
    from elasticdl_tpu.master.stream_ingest import StreamIngestor
    from elasticdl_tpu.master.task_dispatcher import TaskDispatcher
    from elasticdl_tpu.observability.registry import MetricsRegistry

    root = os.path.join(workdir, "coexist")
    stream_dir = os.path.join(root, "stream")
    journal_dir = os.path.join(root, "journal")
    os.makedirs(stream_dir, exist_ok=True)
    out = {
        "problems": [], "preemptions": 0, "resumes": 0,
        "watermark_samples": [], "applied": {}, "batch_applied": {},
        "dropped_leases": 0,
    }

    journal = MasterJournal(journal_dir)
    generation = journal.open_generation()
    sched = GangScheduler(slots_fn=lambda: NUM_WORKERS,
                          journal=journal)
    servicer = MasterServicer(
        TaskDispatcher({}, shuffle=False),  # single-job plane unused
        journal=journal, generation=generation, scheduler=sched,
    )

    def _preempt(job_id, entry):
        out["preemptions"] += 1

    def _resume(job_id, entry):
        out["resumes"] += 1

    # The streaming tenant enters the job table through the DEFAULT
    # dispatcher factory's stream branch — exactly how a submitted
    # spec-only job would.
    sched.submit(
        "stream-live",
        spec={"stream": True, "records_per_task": RECORDS_PER_TASK},
        priority=1, gang_size=NUM_WORKERS,
        preempt_cb=_preempt, resume_cb=_resume,
    )
    # The factory builds the dispatcher at ADMISSION, not submit.
    sched.tick()
    stream_disp = sched.dispatcher_of("stream-live")
    if stream_disp is None or not getattr(
        stream_disp, "is_streaming", False
    ):
        out["problems"].append(
            "scheduler's dispatcher factory did not build a "
            "streaming dispatcher from spec={'stream': True}"
        )
        journal.close()
        return out
    writer = StreamWriter(stream_dir)
    upto: Dict[str, int] = {}
    ingestor = StreamIngestor(
        FileTailStream(stream_dir), stream_disp, max_todo=MAX_TODO,
        metrics_registry=MetricsRegistry(),
    )

    batch_submitted = False
    batch_done_seen = False
    stream_closed = False
    finished_seen = False
    last_committed = 0
    pending = {w: None for w in range(NUM_WORKERS)}

    def committed_total() -> int:
        return sum(
            int(p["committed"])
            for p in stream_disp.stream_progress().values()
        )

    try:
        for step in range(1, CO_MAX_STEPS + 1):
            out["steps"] = step
            _append_schedule(writer, upto, CO_STREAM_RECORDS)
            ingestor.pump()
            for w in range(NUM_WORKERS):
                if pending[w] is not None:
                    continue
                resp = servicer.get_task({"worker_id": w})
                if resp.get("finished"):
                    finished_seen = True
                    continue
                task = resp.get("task")
                if task is None or int(task["task_id"]) < 0:
                    continue
                pending[w] = (str(resp.get("job", "")), task)
            if not batch_submitted and (
                committed_total() >= CO_PREEMPT_AT
            ):
                resp = servicer.submit_job({
                    "job": "batch-hi",
                    "spec": {
                        "shards": {"data": [
                            0, CO_BATCH_TASKS * CO_ROWS_PER_TASK,
                        ]},
                        "records_per_task": CO_ROWS_PER_TASK,
                        "num_epochs": 1, "seed": 0,
                    },
                    "priority": 10, "gang_size": NUM_WORKERS,
                })
                if not resp.get("accepted"):
                    out["problems"].append(
                        f"submit_job rejected: {resp}"
                    )
                batch_submitted = True
            sched.tick()
            states = {
                j: e["state"]
                for j, e in sched.render()["jobs"].items()
            }
            # A preempted gang's leases die with its pods, un-applied.
            for w in range(NUM_WORKERS):
                if (pending[w] is not None
                        and states.get(pending[w][0]) == "preempted"):
                    pending[w] = None
                    out["dropped_leases"] += 1
            for w in range(NUM_WORKERS):
                if pending[w] is None:
                    continue
                job, task = pending[w]
                tid = int(task["task_id"])
                if job == "stream-live":
                    key = (
                        f"{task['shard_name']}:{task['start']}:"
                        f"{task['end']}"
                    )
                    out["applied"][key] = (
                        out["applied"].get(key, 0) + 1
                    )
                else:
                    out["batch_applied"][tid] = (
                        out["batch_applied"].get(tid, 0) + 1
                    )
                servicer.report_task_result({
                    "task_id": tid, "worker_id": w, "job": job,
                    "generation": generation,
                })
                pending[w] = None
            # Watermark monotonicity sampled every step — ESPECIALLY
            # across the preemption window.
            total = committed_total()
            if total < last_committed:
                out["problems"].append(
                    f"watermark regressed: {last_committed} -> "
                    f"{total} at step {step}"
                )
            last_committed = total
            out["watermark_samples"].append(total)
            if states.get("batch-hi") == "done":
                batch_done_seen = True
            if (not stream_closed
                    and committed_total() == CO_STREAM_RECORDS
                    * len(PARTITIONS)
                    and upto.get(PARTITIONS[0], 0)
                    >= CO_STREAM_RECORDS):
                ingestor.close()
                stream_closed = True
            if states and all(
                s == "done" for s in states.values()
            ) and batch_submitted:
                break
        resp = servicer.get_task({"worker_id": 0})
        if resp.get("finished"):
            finished_seen = True
        out["backpressure_seconds"] = ingestor.backpressure_seconds
        out["render"] = sched.render()
        out["final_progress"] = _progress_view(
            stream_disp.stream_progress()
        )
    finally:
        journal.close()
        writer.close()

    states = {
        j: e.get("state") for j, e in out["render"]["jobs"].items()
    }
    out["states"] = states
    if out["preemptions"] < 1:
        out["problems"].append(
            "the batch job never preempted the streaming tenant"
        )
    if out["resumes"] < 1:
        out["problems"].append(
            "the streaming tenant was never resumed after preemption"
        )
    if not batch_done_seen or states.get("batch-hi") != "done":
        out["problems"].append("batch job did not complete")
    if states.get("stream-live") != "done":
        out["problems"].append(
            f"streaming job ended in state "
            f"{states.get('stream-live')!r}, want 'done' after "
            "close_stream + drain"
        )
    if not finished_seen:
        out["problems"].append(
            "servicer never reported finished after both jobs done"
        )
    dupes = {k: c for k, c in out["applied"].items() if c != 1}
    if dupes:
        out["problems"].append(
            f"stream ranges applied more than once: {dupes}"
        )
    # Exactly-once over the OFFSET SPACE: the applied ranges (task
    # sizes vary with tail arrival) must tile [0, end) per partition —
    # a gap is a lost ack, an overlap a double apply.
    for partition in PARTITIONS:
        ranges = sorted(
            (int(s), int(e))
            for k in out["applied"]
            for p, s, e in [k.rsplit(":", 2)]
            if p == partition
        )
        cursor = 0
        for start, end in ranges:
            if start != cursor:
                out["problems"].append(
                    f"{partition}: applied ranges {'overlap' if start < cursor else 'leave a gap'} "
                    f"at offset {cursor} (next range [{start}, {end}))"
                )
                break
            cursor = end
        else:
            if cursor != CO_STREAM_RECORDS:
                out["problems"].append(
                    f"{partition}: applied ranges cover [0, {cursor}),"
                    f" want [0, {CO_STREAM_RECORDS})"
                )
    if len(out["batch_applied"]) != CO_BATCH_TASKS or any(
        c != 1 for c in out["batch_applied"].values()
    ):
        out["problems"].append(
            f"batch tasks misapplied: {out['batch_applied']}"
        )
    for partition in PARTITIONS:
        part = out["final_progress"].get(partition, {})
        if part.get("committed") != CO_STREAM_RECORDS:
            out["problems"].append(
                f"{partition}: final watermark "
                f"{part.get('committed')} != {CO_STREAM_RECORDS}"
            )
    if out.get("backpressure_seconds", 0.0) <= 0.0:
        out["problems"].append(
            "backpressure never ticked while the streaming gang was "
            "preempted (todo should have filled to max_todo)"
        )
    monotone = all(
        b >= a for a, b in zip(out["watermark_samples"],
                               out["watermark_samples"][1:])
    )
    out["watermark_monotone"] = monotone
    out["journal_fsck_errors"] = _check_journal(journal_dir)
    out["problems"].extend(
        f"coexist journal fsck: {e}"
        for e in out["journal_fsck_errors"]
    )
    # Bound the sample list in the report.
    out["watermark_samples"] = out["watermark_samples"][-64:]
    out.pop("render", None)
    return out


# ---- entry ----------------------------------------------------------------


def run_drill(workdir: str, seed: int = 0) -> dict:
    report = {
        "drill": "stream_ingest",
        "seed": seed,
        "config": {
            "partitions": list(PARTITIONS),
            "records_per_partition": RECORDS_PER_PARTITION,
            "records_per_task": RECORDS_PER_TASK,
            "kill_at_committed": KILL_AT_COMMITTED,
            "max_todo": MAX_TODO,
            "workers": NUM_WORKERS,
            "shards": NUM_SHARDS,
            "coexist": {
                "stream_records": CO_STREAM_RECORDS,
                "batch_tasks": CO_BATCH_TASKS,
                "preempt_at": CO_PREEMPT_AT,
            },
        },
        "problems": [],
    }
    kill = _kill_scenario(workdir)
    report["kill"] = kill
    report["problems"].extend(kill["problems"])
    coexist = _coexist_scenario(workdir)
    report["coexist"] = coexist
    report["problems"].extend(coexist["problems"])
    report["passed"] = not report["problems"]
    return report


def main(argv=None) -> int:
    parser = argparse.ArgumentParser("elasticdl_tpu-stream-drill")
    sub = parser.add_subparsers(dest="cmd")
    work = sub.add_parser("work")
    work.add_argument("--worker_id", type=int, required=True)
    work.add_argument("--master_addr", required=True)
    work.add_argument("--stream_dir", required=True)
    work.add_argument("--shards", required=True)
    work.add_argument("--grace", type=float, default=WORK_GRACE)
    run = sub.add_parser("run")
    for p in (run, parser):
        p.add_argument("--seed", type=int, default=0)
        p.add_argument("--workdir")
        p.add_argument("--report", default="STREAM_DRILL.json")
    args = parser.parse_args(argv)
    if args.cmd == "work":
        return _work(args)
    if not args.workdir:
        parser.error("--workdir required")
    report = run_drill(args.workdir, args.seed)
    with open(args.report, "w") as fh:
        json.dump(report, fh, indent=2, sort_keys=True, default=str)
        fh.write("\n")
    logger.info(
        "stream drill: %s (%d problems); report %s",
        "PASS" if report["passed"] else "FAIL",
        len(report["problems"]), args.report,
    )
    return 0 if report["passed"] else 1


if __name__ == "__main__":
    sys.exit(main())
