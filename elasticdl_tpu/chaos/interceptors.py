"""Fault injection hooks threaded through the framework's seams.

One ``FaultInjector`` owns a ``FaultPlan`` and installs itself into:

- ``comm/rpc.py`` — client hook inside ``RpcStub.call`` (delay / drop
  / error / blackhole / kill before the request leaves) and server
  hook inside the ``_GenericService`` handler wrap (server-site delays
  and aborts, including row-service shard stalls by server tag);
- ``checkpoint/saver.py`` — post-save hook (corrupt the just-published
  version dir), post-restore hook (feeds the version-monotonicity
  invariant checker), and the shard-file fsync seam (``fsync_stall``
  slow-disk brownouts);
- ``storage/pushlog.py`` — group-commit fsync seam (``fsync_stall``
  stalls the WAL commit thread that durable-ack pushes wait on);
- ``master/instance_manager.py`` — observer on kill/relaunch events
  (recovery-latency timing for k8s-mode jobs);
- ``testing/cluster.MiniCluster`` — per-RPC callbacks on
  ``InProcessMaster`` so the no-network test path injects the same
  plan (``in_process_callbacks()``).

Every decision is driven by per-event call counters and per-event
seeded RNGs, so a sequential (single-worker) run replays bit-identical
fault schedules; ``injected`` is the deterministic record the chaos
report embeds. Wall-clock timing (kill→recovery latency) is kept in a
separate, explicitly non-deterministic log.
"""

import os
import random
import threading
import time
from typing import Dict, List, Optional

from elasticdl_tpu.chaos.faults import (
    BLACKHOLE,
    CORRUPT_CHECKPOINT,
    FSYNC_STALL,
    KILL_WORKER,
    MASTER_KILL,
    RPC_DELAY,
    RPC_DROP,
    RPC_ERROR,
    STALL_SHARD,
    FaultPlan,
)
from elasticdl_tpu.common.log_utils import get_logger

logger = get_logger("chaos")


class ChaosKill(BaseException):
    """Simulated pod death (SIGKILL / exit 137). BaseException on
    purpose: a real SIGKILL is not catchable, so no ``except
    Exception`` in the worker loop may swallow it — only the chaos
    runner (standing in for the instance manager watching pod events)
    handles it. ``finally`` blocks still run, which models the async
    checkpoint write that was already in flight landing on disk."""

    def __init__(self, worker_id: int, event_index: int):
        super().__init__(
            f"chaos: killed worker {worker_id} (event {event_index})"
        )
        self.worker_id = worker_id
        self.event_index = event_index


class FaultInjector:
    """Decides, per matching call/save, whether a plan event fires.

    Thread-safe; deterministic for sequential callers (the decision
    state is per-event counters + per-event ``random.Random`` seeded
    from ``plan.seed`` and the event index)."""

    def __init__(self, plan: FaultPlan, metrics_registry=None):
        self.plan = plan
        self._lock = threading.RLock()
        self._calls: Dict[int, int] = {}   # event idx -> matching calls
        self._fires: Dict[int, int] = {}   # event idx -> fires
        self._saves: Dict[int, int] = {}   # event idx -> matching saves
        self._rngs = {
            i: random.Random((int(plan.seed) << 8) ^ (i + 1))
            for i in range(len(plan.events))
        }
        # Deterministic record of every injected fault, in order.
        self.injected: List[dict] = []
        # Invariant checkers subscribe to save/restore observations.
        self._save_listeners: List[callable] = []
        self._restore_listeners: List[callable] = []
        # Wall-clock recovery log (NOT in the deterministic report
        # core): [{worker_id, new_id, latency_secs}].
        self.recoveries: List[dict] = []
        self._kill_times: Dict[int, float] = {}
        # Master-restart seam (ISSUE 5): the harness registers a
        # callable that plays the platform's restart-policy role —
        # tear down the master, rebuild it from its write-ahead
        # journal (master/journal.py), re-point the transport. Fired
        # OUTSIDE the injector lock (it rebuilds dispatchers). The
        # wall-clock log mirrors `recoveries` (timings-only section).
        self._master_restart: Optional[callable] = None
        self.master_restarts: List[dict] = []
        from elasticdl_tpu.observability import default_registry

        registry = metrics_registry or default_registry()
        self._m_injected = registry.counter(
            "chaos_faults_injected_total",
            "Faults fired by the chaos plan", ["kind"],
        )
        self._m_kills = registry.counter(
            "chaos_kills_total", "Simulated worker deaths",
        )
        self._m_recoveries = registry.counter(
            "chaos_recoveries_total",
            "Worker kill→relaunch recoveries completed",
        )
        self._m_recovery_secs = registry.histogram(
            "chaos_recovery_seconds",
            "Kill→replacement-running recovery latency",
        )
        self._m_master_kills = registry.counter(
            "chaos_master_kills_total",
            "Simulated master deaths (journal-replay restarts)",
        )

    # ---- install / uninstall -------------------------------------------

    def install(self) -> "FaultInjector":
        from elasticdl_tpu.checkpoint import saver as saver_mod
        from elasticdl_tpu.comm import rpc as rpc_mod
        from elasticdl_tpu.master import instance_manager as im_mod
        from elasticdl_tpu.storage import pushlog as pushlog_mod

        rpc_mod.set_chaos_hooks(
            client=self.client_hook, server=self.server_hook
        )
        saver_mod.set_chaos_hooks(
            post_save=self.on_save, post_restore=self.on_restore,
            fsync=self.fsync_hook,
        )
        pushlog_mod.set_chaos_hooks(fsync=self.fsync_hook)
        im_mod.set_chaos_observer(self.observe_instance_event)
        return self

    def uninstall(self):
        from elasticdl_tpu.checkpoint import saver as saver_mod
        from elasticdl_tpu.comm import rpc as rpc_mod
        from elasticdl_tpu.master import instance_manager as im_mod
        from elasticdl_tpu.storage import pushlog as pushlog_mod

        rpc_mod.set_chaos_hooks(None, None)
        saver_mod.set_chaos_hooks(None, None, None)
        pushlog_mod.set_chaos_hooks(None)
        im_mod.set_chaos_observer(None)

    def set_master_restart(self, fn: Optional[callable]):
        """Register the master-restart seam (the chaos runner's
        ``MiniCluster.restart_master``; in k8s the restart policy +
        journal recovery in master/main.py play this role)."""
        self._master_restart = fn

    def __enter__(self):
        return self.install()

    def __exit__(self, *exc):
        self.uninstall()
        return False

    # ---- core decision --------------------------------------------------

    def _should_fire(self, idx: int, event) -> bool:
        """Count this matching call against ``event`` and decide.
        Caller holds the lock."""
        if event.max_fires and self._fires.get(idx, 0) >= event.max_fires:
            return False
        n = self._calls.get(idx, 0) + 1
        self._calls[idx] = n
        if event.at_call > 0:
            lo = event.at_call
            hi = event.at_call + max(1, event.duration_calls)
            return lo <= n < hi
        return self._rngs[idx].random() < event.probability

    def _record(self, idx: int, event, **info):
        self._fires[idx] = self._fires.get(idx, 0) + 1
        entry = {"event": idx, "kind": event.kind,
                 "call": self._calls.get(idx, 0), **info}
        self.injected.append(entry)
        self._m_injected.labels(event.kind).inc()
        logger.warning("chaos fault fired: %s", entry)

    # ---- RPC hooks ------------------------------------------------------

    @staticmethod
    def _rpc_match(event, site: str, target: str, service: str,
                   method: str) -> bool:
        if event.site != site:
            return False
        if event.target and event.target not in (service, target):
            return False
        if event.method and event.method != method:
            return False
        return True

    def client_hook(self, service: str, method: str, request: dict):
        """Installed into ``RpcStub.call``; runs before each send
        attempt. May sleep, raise RpcError (drop/error), or raise
        ChaosKill."""
        from elasticdl_tpu.comm.rpc import RpcError

        action = None
        with self._lock:
            for idx, event in enumerate(self.plan.events):
                if event.kind == MASTER_KILL:
                    # Default boundary is get_task (a dispatch: the
                    # journal tail ends on a dispatch record);
                    # method="report_task_result" kills mid-lease so
                    # the recovered master must resolve the retried
                    # report against the replayed lease.
                    kill_method = event.method or "get_task"
                    if method != kill_method or (
                        event.target and event.target != service
                    ):
                        continue
                    if self._should_fire(idx, event):
                        self._record(idx, event, method=method)
                        self._m_master_kills.inc()
                        action = ("master_kill", idx)
                        break
                elif event.kind == KILL_WORKER:
                    # Default boundary is get_task (a clean task
                    # boundary: nothing leased, loss-equivalent
                    # recovery); event.method can move the death to
                    # e.g. report_task_result to strand a leased task
                    # (at-least-once re-train territory).
                    kill_method = event.method or "get_task"
                    if method != kill_method or (
                        event.target and event.target != service
                    ):
                        continue
                    wid = int(request.get("worker_id", -1))
                    if event.worker_id >= 0 and event.worker_id != wid:
                        continue
                    if self._should_fire(idx, event):
                        self._record(idx, event, worker_id=wid,
                                     method=method)
                        self._m_kills.inc()
                        self._kill_times[wid] = time.monotonic()
                        action = ChaosKill(wid, idx)
                        break
                elif event.kind in (RPC_DROP, RPC_ERROR, RPC_DELAY,
                                    BLACKHOLE):
                    if not self._rpc_match(
                        event, "client", "", service, method
                    ):
                        continue
                    if self._should_fire(idx, event):
                        self._record(idx, event, service=service,
                                     method=method, site="client")
                        if event.kind == RPC_DELAY:
                            action = ("sleep", event.delay_secs)
                        elif event.kind == RPC_ERROR:
                            action = RpcError(
                                f"chaos: injected {event.code} on "
                                f"{service}.{method}", code=event.code,
                            )
                        else:  # drop / blackhole
                            action = RpcError(
                                f"chaos: dropped {service}.{method}",
                                code=event.code,
                            )
                        break
        if action is None:
            return
        if isinstance(action, tuple) and action[0] == "master_kill":
            # The master's memory dies HERE — whatever the journal
            # holds is all the restart seam gets. The in-flight call
            # then fails UNAVAILABLE (the dead master never answered);
            # the worker's transport retry re-sends it against the
            # recovered incarnation.
            self._run_master_restart()
            raise RpcError(
                f"chaos: master killed during {service}.{method}",
                code="UNAVAILABLE",
            )
        if isinstance(action, tuple):
            time.sleep(action[1])
            return
        raise action

    def _run_master_restart(self):
        restart = self._master_restart
        if restart is None:
            logger.error(
                "chaos: master_kill fired but no restart seam is "
                "registered — the outage will never end"
            )
            return
        t0 = time.monotonic()
        restart()
        self.master_restarts.append({
            "latency_secs": time.monotonic() - t0,
        })

    def server_hook(self, tag: str, service: str, method: str,
                    request: dict):
        """Installed into the ``_GenericService`` handler wrap. Returns
        None (proceed) or ``(code, detail)`` to abort the call."""
        verdict = None
        delay = 0.0
        with self._lock:
            for idx, event in enumerate(self.plan.events):
                if event.kind == STALL_SHARD:
                    if tag != f"rowservice/{event.shard}":
                        continue
                    # Method filter: the brownout drill stalls only
                    # the push methods so serving reads on the same
                    # shard stay fast enough to measure shedding.
                    if event.method and event.method != method:
                        continue
                    if self._should_fire(idx, event):
                        self._record(idx, event, tag=tag, method=method)
                        delay = max(delay, event.delay_secs)
                elif event.kind in (RPC_DROP, RPC_ERROR, RPC_DELAY,
                                    BLACKHOLE):
                    if not self._rpc_match(
                        event, "server", tag, service, method
                    ):
                        continue
                    if self._should_fire(idx, event):
                        self._record(idx, event, service=service,
                                     method=method, site="server",
                                     tag=tag)
                        if event.kind == RPC_DELAY:
                            delay = max(delay, event.delay_secs)
                        else:
                            verdict = (
                                event.code,
                                f"chaos: injected {event.code} on "
                                f"{service}.{method}",
                            )
        if delay > 0:
            time.sleep(delay)
        return verdict

    # ---- storage fsync seams -------------------------------------------

    def fsync_hook(self, site: str):
        """Installed into the storage fsync seams: ``site`` is
        ``"pushlog"`` (WAL group commit, commit thread) or
        ``"checkpoint"`` (saver shard-file fsync). Sleeps through any
        matching ``fsync_stall`` window — a slow-disk brownout,
        counted per-seam like every other windowed event."""
        delay = 0.0
        with self._lock:
            for idx, event in enumerate(self.plan.events):
                if event.kind != FSYNC_STALL:
                    continue
                if event.target and event.target != site:
                    continue
                if self._should_fire(idx, event):
                    self._record(idx, event, site=site)
                    delay = max(delay, event.delay_secs)
        if delay > 0:
            time.sleep(delay)

    # ---- in-process (no-RPC) master path -------------------------------

    def in_process_callbacks(
        self, service: str = "elasticdl_tpu.Master"
    ) -> Dict[str, callable]:
        """Per-RPC callbacks for ``InProcessMaster`` so the direct-call
        test path injects the same plan the gRPC path would: each
        master RPC routes through ``client_hook`` with the servicer's
        service name."""
        def make(method):
            def cb(request):
                self.client_hook(service, method, request)
            return cb

        return {
            name: make(name)
            for name in ("get_task", "report_task_result",
                         "report_evaluation_metrics", "report_version")
        }

    # ---- checkpoint hooks ----------------------------------------------

    def on_save(self, checkpoint_dir: str, version: int, vdir: str):
        corrupted = []
        with self._lock:
            for idx, event in enumerate(self.plan.events):
                if event.kind != CORRUPT_CHECKPOINT:
                    continue
                if event.target and event.target not in checkpoint_dir:
                    continue
                if event.max_fires and (
                    self._fires.get(idx, 0) >= event.max_fires
                ):
                    continue
                n = self._saves.get(idx, 0) + 1
                self._saves[idx] = n
                if n != event.at_save:
                    continue
                fname = self._corrupt(vdir, event.corrupt_mode)
                if fname:
                    self._record(
                        idx, event, save=n, version=int(version),
                        mode=event.corrupt_mode,
                        # Relative path: reports must not leak the
                        # (run-specific) workdir.
                        file=f"{os.path.basename(vdir)}/{fname}",
                    )
                    corrupted.append(fname)
        for listener in self._save_listeners:
            listener(checkpoint_dir, version)

    @staticmethod
    def _corrupt(vdir: str, mode: str) -> Optional[str]:
        """Damage the first shard file of a version dir. ``truncate``
        keeps a decodable-looking prefix (tests the decode fallback),
        ``garbage`` rewrites the head so msgpack decodes a non-payload
        value (tests structural validation), ``delete`` removes the
        file (tests the shard-count validity check)."""
        shards = sorted(
            f for f in os.listdir(vdir) if f.endswith(".ckpt")
        )
        if not shards:
            return None
        path = os.path.join(vdir, shards[0])
        if mode == "delete":
            os.remove(path)
            return shards[0]
        with open(path, "rb") as fh:
            blob = fh.read()
        if mode == "truncate":
            blob = blob[: max(1, len(blob) // 2)]
        else:  # garbage
            blob = b"\x00CHAOS" + blob[7:]
        with open(path, "wb") as fh:
            fh.write(blob)
        return shards[0]

    def on_restore(self, checkpoint_dir: str, version: int):
        for listener in self._restore_listeners:
            listener(checkpoint_dir, version)

    def add_checkpoint_listener(self, on_save=None, on_restore=None):
        if on_save is not None:
            self._save_listeners.append(on_save)
        if on_restore is not None:
            self._restore_listeners.append(on_restore)

    # ---- recovery timing ------------------------------------------------

    def observe_instance_event(self, event: str, **info):
        """instance_manager chaos observer: time kill→relaunch."""
        if event in ("kill_worker", "worker_dead"):
            self.note_kill(info["worker_id"])
        elif event == "worker_relaunched":
            self.note_recovered(info["worker_id"], info.get("new_id", -1))

    def note_kill(self, worker_id: int):
        with self._lock:
            self._kill_times.setdefault(worker_id, time.monotonic())

    def note_recovered(self, worker_id: int, new_id: int):
        with self._lock:
            t0 = self._kill_times.pop(worker_id, None)
        if t0 is None:
            return
        latency = time.monotonic() - t0
        self.recoveries.append({
            "worker_id": int(worker_id),
            "new_id": int(new_id),
            "latency_secs": latency,
        })
        self._m_recoveries.inc()
        self._m_recovery_secs.observe(latency)

    # ---- report ---------------------------------------------------------

    def fault_counts(self) -> Dict[str, int]:
        out: Dict[str, int] = {}
        for entry in self.injected:
            out[entry["kind"]] = out.get(entry["kind"], 0) + 1
        return out

    def metric_families(self) -> dict:
        """The ``edl_tpu_chaos_*`` families reconstructed from the
        injector's own deterministic state (the live registry is
        process-global and accumulates across runs; the report must
        reflect THIS run only, byte-identically). Histogram families
        report only their deterministic ``count``."""
        counts = self.fault_counts()
        return {
            "edl_tpu_chaos_faults_injected_total": {
                "kind": counts
            },
            "edl_tpu_chaos_kills_total": counts.get(KILL_WORKER, 0),
            "edl_tpu_chaos_master_kills_total": counts.get(
                MASTER_KILL, 0
            ),
            "edl_tpu_chaos_recoveries_total": len(self.recoveries),
            "edl_tpu_chaos_recovery_seconds": {
                "count": len(self.recoveries)
            },
        }
