"""Live-reshard chaos drill: kills mid-migration and mid-cutover must
converge to ONE consistent shard map with no row lost or double-homed.

``make reshard-smoke`` (docs/sparse_path.md "Live resharding &
hot-row replication"):

1. **Kill the source shard mid-migration** — a 2-shard fleet under a
   seeded push schedule splits live onto a third shard; the source
   dies (simulated SIGKILL: server torn down, object discarded) from
   the migration chunk hook after the first chunks landed on the
   target. The relaunch restores the source from its checkpoint
   (rows + Adam slots + the shard map riding the checkpoint meta) and
   the surviving authority ``resume()``s the persisted migration
   record — a full idempotent re-copy — then finishes the cutover.
2. **Kill the authority mid-cutover** — the next split's controller
   dies BETWEEN persisting the flipped map and distributing it (the
   worst window: the world's truth moved but nobody was told). A
   fresh controller built from the state file ``resume()``s: it
   re-distributes the persisted epoch and releases the target.

After each scenario the *driver* pushes the remaining schedule (the
suffix past the restored checkpoint — modeling a trainer retrying
work the dead shard never durably acked; this drill's services run
without the write-ahead push log, and once ``--push_log_dir`` is on,
acked pushes replay from the shard's OWN WAL and re-driving them is
forbidden — see ``chaos/quake_drill.py`` and docs/chaos.md "Relaunch
contract"), and the final state must be **byte-equal to a fault-free
twin** driven by the same seeded schedule with the same (un-killed)
splits — rows, optimizer slots, across every shard. The row-conservation invariant spans
source, target, AND replicas: every id lives on exactly ONE home
shard (no loss, no double-homing), and every hot-row replica copy
matches its home's bytes. The authority state file is fsck'd by
``tools/check_reshard.py`` at the kill points (a half-moved range
must be detectable and resumable) and at the end (converged, no
migration in flight). Exits nonzero unless every bar holds.
Fast-lane equivalent: ``tests/test_reshard.py::test_reshard_drill_passes``.
"""

import argparse
import json
import os
import sys
import time

import numpy as np

from elasticdl_tpu.common.log_utils import get_logger

logger = get_logger("reshard_drill")

TABLE = "drill_rows"
DIM = 8
PUSHES = 30
PUSH_IDS = 48
ID_SPACE = 1_000_000
HOT_IDS = 6
SPLIT_AT = (10, 20)  # push index before each split


class DrillKill(RuntimeError):
    """Simulated process death raised from a chaos hook."""


def _schedule(seed: int):
    """Seeded (ids, grads) per push — ids spread across the bucket
    space (uniform over a large id space) plus a pinned hot set so
    replica designation has a signal. Identical for twin and faulted
    runs."""
    rng = np.random.RandomState(seed)
    hot = rng.choice(ID_SPACE, HOT_IDS, replace=False).astype(np.int64)
    out = []
    for _ in range(PUSHES):
        ids = np.unique(np.concatenate([
            rng.randint(0, ID_SPACE, PUSH_IDS).astype(np.int64), hot,
        ]))
        grads = rng.rand(ids.size, DIM).astype(np.float32)
        out.append((ids, grads))
    return hot, out


def _build_shard(workdir: str, run: str, idx: int, port: int = 0):
    from elasticdl_tpu.embedding.optimizer import (
        Adam,
        HostOptimizerWrapper,
    )
    from elasticdl_tpu.embedding.row_service import HostRowService
    from elasticdl_tpu.embedding.table import EmbeddingTable

    svc = HostRowService(
        {TABLE: EmbeddingTable(TABLE, DIM)},
        HostOptimizerWrapper(Adam(lr=0.01)),
    )
    # Sync writes + every push: a kill loses at most the in-flight
    # push (none, in this single-threaded drill) and restores are
    # deterministic — the tiered drill's discipline.
    svc.configure_checkpoint(
        os.path.join(workdir, run, f"shard{idx}_ckpt"),
        checkpoint_steps=1, delta_chain_max=3, async_write=False,
    )
    return svc.start(f"localhost:{port}")


class _Fleet:
    """One run's shards + authority + client, with relaunch support."""

    def __init__(self, workdir: str, run: str, seed: int):
        from elasticdl_tpu.master.row_reshard import (
            ReshardPolicy,
            ShardMapController,
        )

        self.workdir = workdir
        self.run = run
        self.shards = [
            _build_shard(workdir, run, i) for i in range(2)
        ]
        self.state_path = os.path.join(workdir, run, "shard_map.json")
        self.controller = ShardMapController(
            self.state_path,
            policy=ReshardPolicy(replica_min_pulls=2,
                                 replica_top_k=HOT_IDS,
                                 replica_count=1),
        )
        self.controller.bootstrap(self.addrs)
        self.engine = None

    @property
    def addrs(self):
        return [f"localhost:{s.port}" for s in self.shards]

    def client(self):
        from elasticdl_tpu.embedding.row_service import (
            make_remote_engine,
        )

        if self.engine is None:
            self.engine = make_remote_engine(
                ",".join(self.addrs), id_keys={TABLE: "ids"},
                retries=6, backoff_secs=0.1,
            )
        return self.engine

    def push(self, ids, grads):
        engine = self.client()
        engine.optimizer.apply_gradients(
            engine.tables[TABLE], ids, grads
        )

    def pull(self, ids):
        return self.client().tables[TABLE].get(ids)

    def add_shard(self) -> str:
        svc = _build_shard(self.workdir, self.run, len(self.shards))
        self.shards.append(svc)
        return f"localhost:{svc.port}"

    def kill_shard(self, idx: int):
        """Simulated SIGKILL: tear the server down without any drain;
        the object is discarded (in-memory state dies)."""
        self.shards[idx]._server.stop(None)

    def relaunch_shard(self, idx: int, port: int):
        """Replacement process: same checkpoint dir, same port."""
        for _ in range(40):
            try:
                self.shards[idx] = _build_shard(
                    self.workdir, self.run, idx, port=port
                )
                return
            except Exception:
                time.sleep(0.25)
        raise RuntimeError(f"could not rebind shard {idx} on {port}")

    def rebuild_controller(self):
        from elasticdl_tpu.master.row_reshard import (
            ReshardPolicy,
            ShardMapController,
        )

        self.controller.close()
        self.controller = ShardMapController(
            self.state_path,
            policy=ReshardPolicy(replica_min_pulls=2,
                                 replica_top_k=HOT_IDS,
                                 replica_count=1),
        )

    def stop(self):
        self.controller.close()
        if self.engine is not None:
            self.engine.close()
        for svc in self.shards:
            try:
                svc.stop(0)
            except Exception:
                pass


def _row_views(svc):
    return {
        name: view for name, view in svc.host_tables.items()
        if name not in ("__row_service_seqs__",
                        "__row_optimizer_steps__")
    }


def _capture_fleet(fleet: _Fleet):
    """Union of every row view across shards, merged + sorted: the
    cross-shard state the twin comparison runs over. Also returns the
    per-shard id sets for the single-homing check."""
    merged = {}
    homes = {}
    for s, svc in enumerate(fleet.shards):
        for name, view in _row_views(svc).items():
            ids, rows = view.to_arrays()
            merged.setdefault(name, []).append(
                (np.asarray(ids, np.int64), np.asarray(rows))
            )
            if name == TABLE:
                homes[s] = set(np.asarray(ids, np.int64).tolist())
    out = {}
    for name, parts in merged.items():
        ids = np.concatenate([p[0] for p in parts])
        rows = np.concatenate([p[1] for p in parts])
        order = np.argsort(ids, kind="stable")
        out[name] = (ids[order], rows[order])
    return out, homes


def _tables_equal(a, b):
    problems = []
    for name in sorted(set(a) | set(b)):
        if name not in a or name not in b:
            problems.append(f"{name}: present in only one run")
            continue
        ids_a, rows_a = a[name]
        ids_b, rows_b = b[name]
        if not np.array_equal(ids_a, ids_b):
            problems.append(
                f"{name}: id sets differ ({ids_a.size} vs {ids_b.size})"
            )
        elif not np.array_equal(
            np.asarray(rows_a, np.float32),
            np.asarray(rows_b, np.float32),
        ):
            problems.append(f"{name}: row bytes differ")
    return problems


def _conservation_problems(fleet: _Fleet, homes):
    """No id double-homed; replica copies byte-equal their homes."""
    problems = []
    seen = {}
    for s, ids in homes.items():
        for i in ids:
            if i in seen:
                problems.append(
                    f"id {i} homed on shards {seen[i]} AND {s}"
                )
            seen[i] = s
    m = fleet.controller.map
    for s, svc in enumerate(fleet.shards):
        store = svc._replica_store.get(TABLE, {})
        for i, entry in store.items():
            home = int(m.home_of_ids([i])[0])
            if home == s:
                problems.append(f"replica copy of {i} on its own home")
                continue
            want = fleet.shards[home]._tables[TABLE].get([i])[0]
            if not np.array_equal(entry[0], np.asarray(want,
                                                      np.float32)):
                problems.append(
                    f"replica copy of {i} on shard {s} diverged from "
                    f"home {home}"
                )
    return problems


def _fsck(state_path: str, expect_migration: bool):
    sys.path.insert(0, os.path.join(
        os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__)))), "tools",
    ))
    from check_reshard import check_reshard

    errors, report = check_reshard(state_path)
    if expect_migration and not report.get("migration_in_flight"):
        errors = errors + [
            "expected a resumable in-flight migration record"
        ]
    if not expect_migration and report.get("migration_in_flight"):
        errors = errors + ["migration record not cleared"]
    return errors, report


def _drive(fleet: _Fleet, schedule, lo: int, hi: int):
    for seq in range(lo, hi):
        ids, grads = schedule[seq]
        fleet.push(ids, grads)


def _run_twin(workdir, seed, hot, schedule):
    """Fault-free oracle: same schedule, same split points."""
    fleet = _Fleet(workdir, "twin", seed)
    try:
        _drive(fleet, schedule, 0, SPLIT_AT[0])
        for _ in range(4):
            fleet.pull(hot)  # hot signal for replica designation
        fleet.controller.update_replicas()
        fleet.controller.split(0, new_addr=fleet.add_shard())
        _drive(fleet, schedule, SPLIT_AT[0], SPLIT_AT[1])
        fleet.controller.split(1, new_addr=fleet.add_shard())
        _drive(fleet, schedule, SPLIT_AT[1], PUSHES)
        state, homes = _capture_fleet(fleet)
        problems = _conservation_problems(fleet, homes)
        return state, fleet.controller.map.to_json(), problems
    finally:
        fleet.stop()


def _run_faulted(workdir, seed, hot, schedule, twin_state, twin_map):
    from elasticdl_tpu.embedding import row_service
    from elasticdl_tpu.master import row_reshard

    result = {"scenarios": [], "passed": False, "problems": []}
    fleet = _Fleet(workdir, "faulted", seed)
    try:
        _drive(fleet, schedule, 0, SPLIT_AT[0])
        for _ in range(4):
            fleet.pull(hot)
        fleet.controller.update_replicas()

        # ---- scenario 1: source dies mid-migration ----
        fired = {"n": 0}

        def _kill_mid_migrate(_svc, _mig, _view, _chunk):
            fired["n"] += 1
            if fired["n"] == 2:
                raise DrillKill("source killed mid-migration")

        src_port = fleet.shards[0].port
        new_addr = fleet.add_shard()
        row_service.set_reshard_chaos_hooks(
            mid_migrate=_kill_mid_migrate
        )
        killed = False
        try:
            fleet.controller.split(0, new_addr=new_addr)
        except Exception:
            killed = True
        finally:
            row_service.set_reshard_chaos_hooks(mid_migrate=None)
        if not killed:
            result["problems"].append(
                "mid-migrate hook never fired (range too small?)"
            )
            return result
        fleet.kill_shard(0)
        errors, _ = _fsck(fleet.state_path, expect_migration=True)
        result["scenarios"].append({
            "scenario": "kill_source_mid_migration",
            "fsck_at_kill": errors,
        })
        result["problems"].extend(errors)
        fleet.relaunch_shard(0, src_port)
        fleet.controller.resume()
        _drive(fleet, schedule, SPLIT_AT[0], SPLIT_AT[1])

        # ---- scenario 2: authority dies mid-cutover ----
        def _kill_mid_cutover(_ctrl, _record):
            raise DrillKill("authority killed mid-cutover")

        new_addr = fleet.add_shard()
        row_reshard.set_reshard_chaos_hooks(
            mid_cutover=_kill_mid_cutover
        )
        killed = False
        try:
            fleet.controller.split(1, new_addr=new_addr)
        except DrillKill:
            killed = True
        finally:
            row_reshard.set_reshard_chaos_hooks(mid_cutover=None)
        if not killed:
            result["problems"].append("mid-cutover hook never fired")
            return result
        errors, _ = _fsck(fleet.state_path, expect_migration=True)
        result["scenarios"].append({
            "scenario": "kill_authority_mid_cutover",
            "fsck_at_kill": errors,
        })
        result["problems"].extend(errors)
        fleet.rebuild_controller()
        fleet.controller.resume()
        _drive(fleet, schedule, SPLIT_AT[1], PUSHES)

        # ---- convergence + conservation + byte equality ----
        state, homes = _capture_fleet(fleet)
        result["problems"].extend(_tables_equal(twin_state, state))
        result["problems"].extend(
            _conservation_problems(fleet, homes)
        )
        final_map = fleet.controller.map.to_json()
        if final_map["ranges"] != twin_map["ranges"]:
            result["problems"].append(
                "faulted run's final ranges differ from the twin's"
            )
        versions = set()
        for svc in fleet.shards:
            versions.add(svc._shard_map.version
                         if svc._shard_map else 0)
        if versions != {fleet.controller.map.version}:
            result["problems"].append(
                f"shards did not converge to one epoch: {versions}"
            )
        errors, _ = _fsck(fleet.state_path, expect_migration=False)
        result["problems"].extend(errors)
        result["final_map_version"] = fleet.controller.map.version
        result["passed"] = not result["problems"]
        return result
    finally:
        fleet.stop()


def run_drill(workdir: str, seed: int) -> dict:
    hot, schedule = _schedule(seed)
    twin_state, twin_map, twin_problems = _run_twin(
        workdir, seed, hot, schedule
    )
    report = {
        "drill": "live_reshard",
        "seed": seed,
        "config": {
            "table": TABLE, "dim": DIM, "pushes": PUSHES,
            "push_ids": PUSH_IDS, "id_space": ID_SPACE,
            "split_at": list(SPLIT_AT), "hot_ids": HOT_IDS,
        },
        "twin_problems": twin_problems,
    }
    faulted = _run_faulted(
        workdir, seed, hot, schedule, twin_state, twin_map
    )
    report.update(faulted)
    report["problems"] = twin_problems + faulted["problems"]
    report["passed"] = faulted["passed"] and not twin_problems
    return report


def main(argv=None) -> int:
    parser = argparse.ArgumentParser("elasticdl_tpu-reshard-drill")
    parser.add_argument("--seed", type=int, default=7)
    parser.add_argument("--workdir", required=True)
    parser.add_argument("--report", default="RESHARD_DRILL.json")
    args = parser.parse_args(argv)

    report = run_drill(args.workdir, args.seed)
    with open(args.report, "w") as fh:
        json.dump(report, fh, indent=2, sort_keys=True, default=str)
        fh.write("\n")
    logger.info(
        "reshard drill: %s (%d scenario(s))%s; report %s",
        "PASS" if report["passed"] else "FAIL",
        len(report.get("scenarios", [])),
        "" if report["passed"]
        else f" problems: {'; '.join(map(str, report['problems']))}",
        args.report,
    )
    return 0 if report["passed"] else 1


if __name__ == "__main__":
    sys.exit(main())
