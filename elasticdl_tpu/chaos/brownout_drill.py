"""Brownout drill: a slow-disk overload must degrade GRACEFULLY —
shed background work first, keep serving reads fast, cap retry
amplification — and a twin run with every control disabled must show
the inversion the controls exist to prevent.

``make brownout-smoke`` (docs/fault_tolerance.md "Graceful
degradation"):

A REAL 2-shard row-service fleet (subprocesses over localhost gRPC,
durable-ack push WAL) takes a mixed principal-tagged workload —
serving reads under a 500ms ambient deadline, training pushes,
replica-refresh background pulls, canary probes — through three
windows: an unstalled **baseline**, a **brownout** (an ``fsync_stall``
fault plan stalls every WAL group commit, so durable-ack pushes pin
worker threads — the slow-disk regime), and a **recovery** window
after the stall lifts.

The drill runs twice:

- **controlled** — admission control in front of each shard
  (``comm/overload.py`` priority tiers), client retry budgets, and
  deadline propagation all on. Gates: brownout serving p99 ≤ 1.5x the
  unstalled baseline (with an absolute floor so a noisy CI box cannot
  fail a sub-millisecond ratio), ≥ 90% of sheds land on background
  purposes (``BACKGROUND_PURPOSES`` + never serving_read), total retry
  amplification ≤ 2x offered load, and 100% goodput for every purpose
  within the recovery window.
- **uncontrolled** — admission off, ``set_controls_enabled(False)``
  (no budgets, no breakers), same workload, same stall. Gates invert:
  zero sheds (nothing protects the fleet), background retry
  amplification exceeds the 2x cap (unbudgeted timeout→retry storms),
  and serving p99 blows through the bound the controlled run meets —
  the priority inversion where background load starves the serving
  path.

The committed ``BROWNOUT_DRILL.json`` is validated by
``tools/check_overload.py`` (fsck kind "overload"). Latencies are
wall-clock, so the report is not byte-deterministic — the checker
gates on structure and the recorded verdicts, like the other
latency-bearing drills.
"""

import argparse
import json
import os
import socket
import subprocess
import sys
import threading
import time
from typing import Dict, List, Optional

import numpy as np

from elasticdl_tpu.common.log_utils import get_logger

logger = get_logger("brownout_drill")

TABLE = "brown_rows"
DIM = 8
VOCAB = 50_000
PULL_IDS = 32
PUSH_IDS = 24
NUM_SHARDS = 2

# Per-shard capacity. MAX_WORKERS == PUSHERS_PER_SHARD so the
# uncontrolled brownout genuinely saturates the worker pool (every
# thread pinned by a stalled durable-ack push), while the controlled
# run's admission gate (tier-1 threshold < limit) always leaves
# headroom for serving reads and cheap shed rejections.
MAX_WORKERS = 6
ADMISSION_LIMIT = 6
PUSHERS_PER_SHARD = 6
SERVING_PER_SHARD = 2
BACKGROUND_PER_SHARD = 5
CANARY_PER_SHARD = 1

# fsync_stall per group commit. Group commit acks whole batches, so
# push completions come in BURSTS one commit cycle (~ this delay)
# apart; the background per-attempt timeout sits well under it so an
# unbudgeted client visibly retry-storms while it waits for a burst.
STALL_DELAY_SECS = 0.6
GROUP_MS = 2.0
WARMUP_SECS = 1.0
BASELINE_SECS = 3.0
BROWNOUT_SECS = 6.0
SETTLE_SECS = 2.0            # > retry-after hints + breaker cooldown
RECOVERY_SECS = 3.0

SERVING_DEADLINE_SECS = 0.5  # ambient deadline on every serving read
BG_TIMEOUT_SECS = 0.1        # per-attempt timeout on background pulls
PUSH_TIMEOUT_SECS = 20.0
MAX_ATTEMPTS = {"serving_read": 3, "training": 8,
                "replica_refresh": 6, "canary": 6}
PACING_SECS = {"serving_read": 0.02, "training": 0.08,
               "replica_refresh": 0.02, "canary": 0.03}
PURPOSE_SALT = {"serving_read": 11, "training": 23,
                "replica_refresh": 37, "canary": 53}

MAX_P99_RATIO = 1.5
P99_ABS_FLOOR_SECS = 0.25    # ratio gate floor for sub-ms baselines
MAX_AMPLIFICATION = 2.0
MIN_BACKGROUND_SHED_FRAC = 0.9


def _pkg_root() -> str:
    return os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__)
    )))


def _free_ports(n: int) -> List[int]:
    ports, socks = [], []
    for _ in range(n):
        s = socket.socket()
        s.bind(("localhost", 0))
        ports.append(s.getsockname()[1])
        socks.append(s)
    for s in socks:
        s.close()
    return ports


# ---- `serve` subcommand: one real row-service shard ----------------------


def _serve(args) -> int:
    from elasticdl_tpu.chaos.faults import FaultPlan
    from elasticdl_tpu.chaos.interceptors import FaultInjector
    from elasticdl_tpu.comm import overload as wl_overload
    from elasticdl_tpu.comm.rpc import RpcServer
    from elasticdl_tpu.embedding.optimizer import SGD
    from elasticdl_tpu.embedding.row_service import (
        SERVICE_NAME,
        HostRowService,
    )
    from elasticdl_tpu.native.row_store import (
        make_host_optimizer,
        make_host_table,
    )
    from elasticdl_tpu.observability import default_registry

    svc = HostRowService(
        {TABLE: make_host_table(TABLE, DIM)},
        make_host_optimizer(SGD(lr=0.01)),
    )
    # Durable acks: the push RPC reply waits on the WAL fsync — the
    # seam the fsync_stall plan stalls, which is what pins handler
    # threads and builds the admission queue depth.
    svc.configure_push_log(
        args.push_log_dir, group_ms=args.push_log_group_ms,
        ack="durable",
    )
    box: Dict[str, FaultInjector] = {}

    def _stall(request: dict) -> dict:
        """Toggle the brownout: install/uninstall a FaultInjector for
        the plan the driver sends, so one server incarnation spans
        baseline → brownout → recovery."""
        if request.get("enable"):
            injector = FaultInjector(FaultPlan.from_dict(
                request["plan"]
            ))
            injector.install()
            box["injector"] = injector
            return {"ok": True}
        injector = box.pop("injector", None)
        fired = 0
        if injector is not None:
            injector.uninstall()
            fired = len(injector.injected)
        return {"ok": True, "fired": fired}

    def _metrics(_request: dict) -> dict:
        return {"metrics": default_registry().snapshot()}

    handlers = dict(svc.handlers())
    handlers["ping"] = lambda _req: {"ok": True, "pid": os.getpid()}
    handlers["drill_stall"] = _stall
    handlers["drill_metrics"] = _metrics
    admission = None
    if args.admission_limit > 0:
        admission = wl_overload.AdmissionController(
            args.admission_limit, tag=f"rowservice/{args.shard_id}"
        )
    server = RpcServer(
        f"localhost:{args.port}", {SERVICE_NAME: handlers},
        max_workers=args.max_workers,
        tag=f"rowservice/{args.shard_id}", admission=admission,
    ).start()
    svc._server = server
    logger.info("brownout shard %d serving on %d (pid %d, "
                "admission_limit=%d)", args.shard_id, server.port,
                os.getpid(), args.admission_limit)
    server.wait()
    return 0


# ---- driver: fleet + control-plane calls ---------------------------------


class _Fleet:
    def __init__(self, workdir: str, admission_limit: int):
        self.workdir = workdir
        os.makedirs(workdir, exist_ok=True)
        self.ports = _free_ports(NUM_SHARDS)
        self.procs: List[subprocess.Popen] = []
        self._logs = []
        for shard, port in enumerate(self.ports):
            cmd = [
                sys.executable, "-m",
                "elasticdl_tpu.chaos.brownout_drill", "serve",
                "--port", str(port), "--shard_id", str(shard),
                "--push_log_dir",
                os.path.join(workdir, f"s{shard}", "pushlog"),
                "--push_log_group_ms", str(GROUP_MS),
                "--max_workers", str(MAX_WORKERS),
                "--admission_limit", str(admission_limit),
            ]
            log = open(os.path.join(
                workdir, f"shard{shard}-{port}.log"
            ), "w")
            self._logs.append(log)
            self.procs.append(subprocess.Popen(
                cmd, env=dict(os.environ, JAX_PLATFORMS="cpu"),
                cwd=_pkg_root(), stdout=log,
                stderr=subprocess.STDOUT,
            ))

    def stop_all(self):
        for proc in self.procs:
            if proc.poll() is None:
                proc.terminate()
        for proc in self.procs:
            try:
                proc.wait(timeout=15)
            except Exception:
                proc.kill()
        for log in self._logs:
            log.close()


def _control_call(port: int, method: str, **fields) -> dict:
    """Driver control-plane RPC, tagged tier-0 so the admission gate
    never sheds the drill's own instrumentation."""
    from elasticdl_tpu.comm.rpc import RpcStub
    from elasticdl_tpu.embedding.row_service import SERVICE_NAME
    from elasticdl_tpu.observability import principal as wl_principal

    stub = RpcStub(f"localhost:{port}", SERVICE_NAME, max_retries=2)
    try:
        with wl_principal.pushed(job="brownout", component="drill",
                                 purpose="control"):
            return stub.call(method, timeout=30.0, **fields)
    finally:
        stub.close()


def _wait_shard(port: int, deadline_secs: float = 90.0):
    t0 = time.monotonic()
    last = None
    while time.monotonic() - t0 < deadline_secs:
        try:
            return _control_call(port, "ping")
        except Exception as exc:
            last = exc
            time.sleep(0.1)
    raise TimeoutError(f"shard on port {port} never served: {last}")


def _stall_plan(seed: int) -> dict:
    """Every WAL group commit sleeps STALL_DELAY_SECS while the
    brownout window is enabled (probability 1, unlimited fires — the
    window is bounded by the drill's enable/disable toggles)."""
    from elasticdl_tpu.chaos.faults import FaultEvent, FaultPlan

    return FaultPlan(events=[FaultEvent(
        kind="fsync_stall", target="pushlog",
        probability=1.0, delay_secs=STALL_DELAY_SECS, max_fires=0,
    )], seed=seed).to_dict()


def _shed_counts(port: int) -> Dict[str, int]:
    """overload_shed_total by purpose from one shard's live registry."""
    snap = _control_call(port, "drill_metrics")["metrics"]
    out: Dict[str, int] = {}
    for family in snap.get("families", []):
        if family.get("name") != "edl_tpu_overload_shed_total":
            continue
        for series in family.get("series", []):
            labels = series.get("labels") or ["unknown"]
            out[labels[0]] = (out.get(labels[0], 0)
                              + int(series.get("value", 0)))
    return out


def _shed_delta(before: Dict[str, int], after: Dict[str, int]
                ) -> Dict[str, int]:
    return {
        purpose: after.get(purpose, 0) - before.get(purpose, 0)
        for purpose in sorted(set(before) | set(after))
        if after.get(purpose, 0) - before.get(purpose, 0) > 0
    }


# ---- traffic mix ----------------------------------------------------------


class _PhaseStats:
    """Per-purpose offered/attempt/outcome accounting for one window."""

    def __init__(self):
        self.lock = threading.Lock()
        self.offered: Dict[str, int] = {}
        self.ok: Dict[str, int] = {}
        self.attempts: Dict[str, int] = {}
        self.codes: Dict[str, Dict[str, int]] = {}
        self.latencies: Dict[str, List[float]] = {}

    def record(self, purpose: str, ok: bool, attempts: int,
               secs: float, code: Optional[str]):
        with self.lock:
            self.offered[purpose] = self.offered.get(purpose, 0) + 1
            self.attempts[purpose] = (
                self.attempts.get(purpose, 0) + attempts
            )
            if ok:
                self.ok[purpose] = self.ok.get(purpose, 0) + 1
            elif code:
                per = self.codes.setdefault(purpose, {})
                per[code] = per.get(code, 0) + 1
            self.latencies.setdefault(purpose, []).append(secs)

    def summary(self) -> dict:
        with self.lock:
            out = {}
            for purpose in sorted(self.offered):
                offered = self.offered[purpose]
                lats = sorted(self.latencies.get(purpose, []))
                out[purpose] = {
                    "offered": offered,
                    "ok": self.ok.get(purpose, 0),
                    "attempts": self.attempts.get(purpose, 0),
                    "amplification": round(
                        self.attempts.get(purpose, 0) / offered, 3
                    ),
                    "failure_codes": dict(
                        self.codes.get(purpose, {})
                    ),
                    "p50_secs": round(_pct(lats, 0.5), 5),
                    "p99_secs": round(_pct(lats, 0.99), 5),
                }
            total_offered = sum(self.offered.values())
            total_attempts = sum(self.attempts.values())
            out["_total"] = {
                "offered": total_offered,
                "attempts": total_attempts,
                "amplification": round(
                    total_attempts / max(1, total_offered), 3
                ),
            }
            return out


def _pct(sorted_vals: List[float], q: float) -> float:
    if not sorted_vals:
        return 0.0
    return sorted_vals[min(len(sorted_vals) - 1,
                           int(q * len(sorted_vals)))]


def _one_op(stub, method: str, purpose: str, controls: bool,
            timeout: Optional[float], **fields):
    """One budgeted op through a max_retries=0 stub.

    The drill layers its OWN retry loop (so attempts are countable
    per purpose), which is exactly the ``max_retries=0`` layering
    contract from comm/rpc.py: the loop honors the shared per-service
    retry budget, the shed retry-after hint, and the ambient deadline
    — the same discipline as row_service._call_with_retry."""
    from elasticdl_tpu.comm import deadline as wl_deadline
    from elasticdl_tpu.comm import overload as wl_overload
    from elasticdl_tpu.comm.rpc import (
        EXPIRED_DETAIL,
        RETRYABLE_CODES,
        RpcError,
    )
    from elasticdl_tpu.embedding.row_service import SERVICE_NAME

    max_attempts = MAX_ATTEMPTS[purpose]
    attempts = 0
    delay = 0.05
    rng = np.random
    while True:
        attempts += 1
        try:
            stub.call(method, timeout=timeout, **fields)
            if controls:
                wl_overload.retry_budget_for(SERVICE_NAME).on_success()
            return True, attempts, None
        except RpcError as exc:
            code = exc.code
            retryable = (code in RETRYABLE_CODES
                         and EXPIRED_DETAIL not in str(exc)
                         and not wl_deadline.expired())
            if not retryable or attempts >= max_attempts:
                return False, attempts, code
            if controls and not wl_overload.retry_budget_for(
                SERVICE_NAME
            ).try_spend():
                return False, attempts, code
            hint = None
            if code == "RESOURCE_EXHAUSTED":
                hint = wl_overload.parse_retry_after(str(exc))
            sleep_for = (hint if hint is not None else delay) * (
                0.5 + rng.random()
            )
            left = wl_deadline.remaining()
            if left is not None:
                sleep_for = min(sleep_for, max(0.0, left))
            time.sleep(sleep_for)
            delay = min(delay * 2.0, 0.5)


def _traffic_thread(purpose: str, port: int, tid: int, seed: int,
                    controls: bool, wtag: str,
                    stop: threading.Event, stats: _PhaseStats):
    from elasticdl_tpu.comm import deadline as wl_deadline
    from elasticdl_tpu.comm.rpc import RpcStub
    from elasticdl_tpu.embedding.row_service import SERVICE_NAME
    from elasticdl_tpu.observability import principal as wl_principal

    rng = np.random.RandomState(
        seed * 1009 + PURPOSE_SALT[purpose] * 101 + tid
    )
    # max_retries=0: the drill's own loop in _one_op is the retry
    # policy (budgets must not be spent twice per failure).
    stub = RpcStub(f"localhost:{port}", SERVICE_NAME, max_retries=0)
    seq = 0
    try:
        while not stop.is_set():
            t0 = time.monotonic()
            with wl_principal.pushed(job="brownout",
                                     component="drill",
                                     purpose=purpose):
                if purpose == "training":
                    ids = np.unique(rng.randint(
                        0, VOCAB, PUSH_IDS
                    )).astype(np.int64)
                    grads = rng.rand(ids.size, DIM).astype(np.float32)
                    seq += 1
                    ok, attempts, code = _one_op(
                        stub, "push_row_grads", purpose, controls,
                        PUSH_TIMEOUT_SECS, table=TABLE, ids=ids,
                        grads=grads,
                        # The window tag keeps every window's
                        # (client, seq) stream fresh: reusing a
                        # client key across windows would replay
                        # seqs the server has already seen and the
                        # dedup map would drop the pushes before
                        # they ever touch the WAL (no durable wait
                        # -> no brownout).
                        client=f"bd-{wtag}-{port}-{tid}", seq=seq,
                    )
                elif purpose == "serving_read":
                    ids = np.unique(rng.randint(
                        0, VOCAB, PULL_IDS
                    )).astype(np.int64)
                    # The ambient deadline bounds the WHOLE op —
                    # every attempt's hop timeout derives from it and
                    # retries stop when it expires.
                    with wl_deadline.running_out(
                        SERVING_DEADLINE_SECS
                    ):
                        ok, attempts, code = _one_op(
                            stub, "pull_rows", purpose, controls,
                            None, table=TABLE, ids=ids,
                        )
                else:  # replica_refresh / canary background pulls
                    ids = np.unique(rng.randint(
                        0, VOCAB, PULL_IDS
                    )).astype(np.int64)
                    ok, attempts, code = _one_op(
                        stub, "pull_rows", purpose, controls,
                        BG_TIMEOUT_SECS, table=TABLE, ids=ids,
                    )
            stats.record(purpose, ok, attempts,
                         time.monotonic() - t0, code)
            time.sleep(PACING_SECS[purpose])
    finally:
        stub.close()


def _run_window(ports: List[int], secs: float, seed: int,
                controls: bool, wtag: str) -> _PhaseStats:
    stats = _PhaseStats()
    stop = threading.Event()
    threads = []
    mix = (("training", PUSHERS_PER_SHARD),
           ("serving_read", SERVING_PER_SHARD),
           ("replica_refresh", BACKGROUND_PER_SHARD),
           ("canary", CANARY_PER_SHARD))
    for port in ports:
        for purpose, count in mix:
            for tid in range(count):
                threads.append(threading.Thread(
                    target=_traffic_thread,
                    args=(purpose, port, tid, seed, controls, wtag,
                          stop, stats),
                    daemon=True,
                ))
    for t in threads:
        t.start()
    time.sleep(secs)
    stop.set()
    for t in threads:
        t.join(timeout=60.0)
    return stats


# ---- one run (controlled or uncontrolled) --------------------------------


def _run_mode(workdir: str, seed: int, controlled: bool) -> dict:
    from elasticdl_tpu.comm import overload as wl_overload

    mode = "controlled" if controlled else "uncontrolled"
    result = {"mode": mode, "problems": []}
    wl_overload.reset_retry_budgets()
    wl_overload.reset_breakers()
    fleet = _Fleet(
        os.path.join(workdir, mode),
        admission_limit=ADMISSION_LIMIT if controlled else 0,
    )
    restore_controls = wl_overload.controls_enabled()
    try:
        if not controlled:
            wl_overload.set_controls_enabled(False)
        for port in fleet.ports:
            _wait_shard(port)
        # Warmup: lazy init (channels, first group commit) off the
        # measured windows.
        _run_window(fleet.ports, WARMUP_SECS, seed, controlled,
                    "warm")

        logger.info("%s: baseline window (%.0fs)", mode,
                    BASELINE_SECS)
        baseline = _run_window(
            fleet.ports, BASELINE_SECS, seed + 1, controlled, "base"
        )
        result["baseline"] = baseline.summary()

        sheds_before = {
            port: _shed_counts(port) for port in fleet.ports
        }
        plan = _stall_plan(seed)
        for port in fleet.ports:
            _control_call(port, "drill_stall", enable=True, plan=plan)
        logger.info("%s: brownout window (%.0fs, fsync_stall %.2fs "
                    "per commit)", mode, BROWNOUT_SECS,
                    STALL_DELAY_SECS)
        brownout = _run_window(
            fleet.ports, BROWNOUT_SECS, seed + 2, controlled, "brown"
        )
        result["brownout"] = brownout.summary()
        stall_fired = 0
        for port in fleet.ports:
            resp = _control_call(port, "drill_stall", enable=False)
            stall_fired += int(resp.get("fired", 0))
        result["stall_fired"] = stall_fired
        if stall_fired <= 0:
            result["problems"].append(
                f"{mode}: fsync_stall never fired — no brownout "
                "actually happened"
            )
        sheds_after = {
            port: _shed_counts(port) for port in fleet.ports
        }
        sheds: Dict[str, int] = {}
        for port in fleet.ports:
            for purpose, n in _shed_delta(
                sheds_before[port], sheds_after[port]
            ).items():
                sheds[purpose] = sheds.get(purpose, 0) + n
        result["sheds"] = sheds

        time.sleep(SETTLE_SECS)
        logger.info("%s: recovery window (%.0fs)", mode,
                    RECOVERY_SECS)
        recovery = _run_window(
            fleet.ports, RECOVERY_SECS, seed + 3, controlled, "rec"
        )
        result["recovery"] = recovery.summary()
    finally:
        wl_overload.set_controls_enabled(restore_controls)
        fleet.stop_all()
    return result


# ---- gates ----------------------------------------------------------------


def _serving_bound(summary: dict) -> float:
    base_p99 = summary.get("serving_read", {}).get("p99_secs", 0.0)
    return max(MAX_P99_RATIO * base_p99, P99_ABS_FLOOR_SECS)


def evaluate_gates(controlled: dict, uncontrolled: dict) -> List[dict]:
    from elasticdl_tpu.comm.overload import BACKGROUND_PURPOSES

    gates = []

    def gate(name: str, passed: bool, observed, bound):
        gates.append({"name": name, "passed": bool(passed),
                      "observed": observed, "bound": bound})

    # 1. Serving p99 through the brownout stays near baseline.
    bound = round(_serving_bound(controlled["baseline"]), 5)
    p99 = controlled["brownout"].get(
        "serving_read", {}
    ).get("p99_secs", 0.0)
    gate("controlled_serving_p99", p99 <= bound, p99, bound)

    # 2. Sheds happened, and >= 90% landed on background purposes
    # (and none on serving reads).
    sheds = controlled.get("sheds", {})
    total = sum(sheds.values())
    background = sum(
        n for p, n in sheds.items() if p in BACKGROUND_PURPOSES
    )
    frac = background / total if total else 0.0
    gate("controlled_sheds_background_frac",
         total > 0 and frac >= MIN_BACKGROUND_SHED_FRAC
         and sheds.get("serving_read", 0) == 0,
         {"total": total, "background_frac": round(frac, 3),
          "serving_shed": sheds.get("serving_read", 0)},
         {"min_background_frac": MIN_BACKGROUND_SHED_FRAC,
          "serving_shed": 0})

    # 3. Retry amplification capped by the budget.
    amp = controlled["brownout"]["_total"]["amplification"]
    gate("controlled_amplification", amp <= MAX_AMPLIFICATION,
         amp, MAX_AMPLIFICATION)

    # 4. Goodput is 100% for every purpose within the recovery window.
    recovery = controlled["recovery"]
    losses = {
        p: {"offered": s["offered"], "ok": s["ok"]}
        for p, s in recovery.items()
        if p != "_total" and s["ok"] < s["offered"]
    }
    gate("controlled_recovery_goodput", not losses,
         losses or "100%", "100% per purpose")

    # 5. The no-control twin sheds nothing (there is no gate to shed).
    un_sheds = sum(uncontrolled.get("sheds", {}).values())
    gate("uncontrolled_no_sheds", un_sheds == 0, un_sheds, 0)

    # 6. ...and its unbudgeted background retries blow the 2x cap.
    un_bg_amp = max(
        (uncontrolled["brownout"].get(p, {}).get("amplification", 0.0)
         for p in BACKGROUND_PURPOSES), default=0.0,
    )
    gate("uncontrolled_background_amplification",
         un_bg_amp > MAX_AMPLIFICATION, un_bg_amp,
         {"exceeds": MAX_AMPLIFICATION})

    # 7. ...and serving inverts: its p99 blows through the bound the
    # controlled run meets (background load starving the serving
    # path).
    un_bound = round(_serving_bound(uncontrolled["baseline"]), 5)
    un_p99 = uncontrolled["brownout"].get(
        "serving_read", {}
    ).get("p99_secs", 0.0)
    gate("uncontrolled_serving_inversion", un_p99 > un_bound,
         un_p99, {"exceeds": un_bound})
    return gates


def run_drill(workdir: str, seed: int) -> dict:
    os.makedirs(workdir, exist_ok=True)
    logger.info("brownout drill: controlled run")
    controlled = _run_mode(workdir, seed, controlled=True)
    logger.info("brownout drill: uncontrolled (no-control) run")
    uncontrolled = _run_mode(workdir, seed, controlled=False)
    gates = evaluate_gates(controlled, uncontrolled)
    problems = list(controlled["problems"])
    problems += uncontrolled["problems"]
    problems += [
        f"gate {g['name']}: observed {g['observed']!r}, "
        f"bound {g['bound']!r}"
        for g in gates if not g["passed"]
    ]
    return {
        "drill": "brownout",
        "seed": int(seed),
        "config": {
            "table": TABLE, "dim": DIM, "vocab": VOCAB,
            "num_shards": NUM_SHARDS,
            "max_workers": MAX_WORKERS,
            "admission_limit": ADMISSION_LIMIT,
            "stall_delay_secs": STALL_DELAY_SECS,
            "serving_deadline_secs": SERVING_DEADLINE_SECS,
            "baseline_secs": BASELINE_SECS,
            "brownout_secs": BROWNOUT_SECS,
            "recovery_secs": RECOVERY_SECS,
            "max_p99_ratio": MAX_P99_RATIO,
            "p99_abs_floor_secs": P99_ABS_FLOOR_SECS,
            "max_amplification": MAX_AMPLIFICATION,
            "min_background_shed_frac": MIN_BACKGROUND_SHED_FRAC,
        },
        "runs": {"controlled": controlled,
                 "uncontrolled": uncontrolled},
        "gates": gates,
        "problems": problems,
        "passed": not problems,
    }


def main(argv=None) -> int:
    parser = argparse.ArgumentParser("elasticdl_tpu-brownout-drill")
    sub = parser.add_subparsers(dest="command", required=True)
    serve = sub.add_parser("serve")
    serve.add_argument("--port", type=int, required=True)
    serve.add_argument("--shard_id", type=int, default=0)
    serve.add_argument("--push_log_dir", required=True)
    serve.add_argument("--push_log_group_ms", type=float,
                       default=GROUP_MS)
    serve.add_argument("--max_workers", type=int, default=MAX_WORKERS)
    serve.add_argument("--admission_limit", type=int, default=0)

    run = sub.add_parser("run")
    run.add_argument("--seed", type=int, default=7)
    run.add_argument("--workdir", required=True)
    run.add_argument("--report", default="BROWNOUT_DRILL.json")
    args = parser.parse_args(argv)

    if args.command == "serve":
        return _serve(args)

    report = run_drill(args.workdir, args.seed)
    with open(args.report, "w") as fh:
        json.dump(report, fh, indent=2, sort_keys=True, default=str)
        fh.write("\n")
    for g in report["gates"]:
        logger.info("brownout gate %s: %s (observed %r, bound %r)",
                    g["name"], "PASS" if g["passed"] else "FAIL",
                    g["observed"], g["bound"])
    logger.info("brownout drill: %s; report %s",
                "PASS" if report["passed"] else "FAIL", args.report)
    return 0 if report["passed"] else 1


if __name__ == "__main__":
    sys.exit(main())
