"""Recovery invariants: what must hold after any fault schedule.

The paper's elasticity story (task re-queue + pod relaunch, no
checkpoint-restart of the job) makes four concrete promises that these
checkers turn into pass/fail verdicts:

1. **Exactly-once task accounting** — every record of every shard is
   counted complete exactly once per epoch: a kill must not lose a
   task (records short) and a requeue must not double-run one
   (records over). This is the dispatcher's core contract.
2. **Row conservation** — embedding rows materialized on the host/row
   tier survive worker death and shard relaunch: a row that existed at
   any kill still exists at the end (and after a checkpoint→restore
   relaunch cycle of the row service).
3. **Checkpoint version monotonicity** — saved versions strictly
   increase per directory, and every restore lands on a version no
   newer than the last save (a restore from the "future" means torn
   GC or clock-free version reuse).
4. **Loss-trajectory equivalence** — at equal data order, a faulted
   run ends bit-close to its fault-free twin: same final version,
   same final loss, same dense parameters. This is the end-to-end
   proof that recovery neither lost nor double-applied training.

Checkers return ``CheckResult`` (never raise) so a report can carry
every verdict; a failed invariant is a *finding*, not a crash.
"""

import dataclasses
import threading
from typing import Dict, List, Optional

import numpy as np

from elasticdl_tpu.common.constants import TaskType


@dataclasses.dataclass
class CheckResult:
    name: str
    passed: bool
    details: str = ""

    def to_dict(self) -> dict:
        return {
            "name": self.name,
            "passed": bool(self.passed),
            "details": self.details,
        }


class ExactlyOnceTaskAccounting:
    """No lost and no double-counted shards in the dispatcher.

    ``expected_records`` maps task type -> records per epoch;
    training expectations scale by ``num_epochs``. ``check`` reads the
    dispatcher's public counters plus its queue state, so a job that
    wedged (task stuck in ``doing`` because recovery was skipped)
    fails with the stuck task named rather than hanging the harness.
    """

    name = "exactly_once_task_accounting"

    def __init__(self, dispatcher, expected_records: Dict[str, int],
                 num_epochs: int = 1):
        self._d = dispatcher
        self._expected = dict(expected_records)
        self._epochs = int(num_epochs)

    def check(self) -> CheckResult:
        problems: List[str] = []
        if not self._d.finished():
            with self._d._lock:
                todo = len(self._d._todo)
                doing = sorted(
                    (tid, wid)
                    for tid, (_t, wid, _s) in self._d._doing.items()
                )
            problems.append(
                f"job did not drain: todo={todo} doing={doing} "
                "(lost task: leased but never reported or recovered?)"
            )
        completed = self._d.counters.total_records
        for task_type, per_epoch in sorted(self._expected.items()):
            want = per_epoch * (
                self._epochs if task_type == TaskType.TRAINING else 1
            )
            got = completed.get(task_type, 0)
            if got < want:
                problems.append(
                    f"{task_type}: {want - got} record(s) LOST "
                    f"(completed {got}, expected {want})"
                )
            elif got > want:
                problems.append(
                    f"{task_type}: {got - want} record(s) DOUBLE-"
                    f"counted (completed {got}, expected {want})"
                )
        failed = {
            k: v for k, v in self._d.counters.failed_records.items() if v
        }
        if failed:
            problems.append(f"records failed permanently: {failed}")
        if problems:
            return CheckResult(self.name, False, "; ".join(problems))
        return CheckResult(
            self.name, True,
            f"all records counted exactly once: "
            f"{dict(sorted(completed.items()))}",
        )


class RowConservation:
    """Embedding rows survive worker death and shard relaunch.

    The runner calls ``snapshot(label)`` at every kill (and before a
    row-service relaunch drill); ``check(final_tables)`` verifies every
    snapshotted row id still exists in the final tables and that the
    optimizer's slot tables carry the same id set as their base table
    (an orphaned or missing slot row means the optimizer state for
    that row silently reset)."""

    name = "embedding_row_conservation"

    def __init__(self):
        self._snapshots: List[dict] = []
        self._lock = threading.Lock()

    @staticmethod
    def _ids_of(tables) -> Dict[str, np.ndarray]:
        out = {}
        for name, table in (tables or {}).items():
            ids, _rows = table.to_arrays()
            out[name] = np.sort(np.asarray(ids, np.int64))
        return out

    def snapshot(self, label: str, tables):
        with self._lock:
            self._snapshots.append(
                {"label": label, "ids": self._ids_of(tables)}
            )

    def check(self, final_tables) -> CheckResult:
        final_ids = self._ids_of(final_tables)
        problems: List[str] = []
        for snap in self._snapshots:
            for tname, ids in snap["ids"].items():
                have = final_ids.get(tname)
                if have is None:
                    problems.append(
                        f"table {tname!r} (snapshot {snap['label']!r}) "
                        "missing from final tables"
                    )
                    continue
                lost = np.setdiff1d(ids, have)
                if lost.size:
                    problems.append(
                        f"table {tname!r}: {lost.size} row(s) lost "
                        f"since snapshot {snap['label']!r} "
                        f"(e.g. ids {lost[:5].tolist()})"
                    )
        if problems:
            return CheckResult(self.name, False, "; ".join(problems))
        rows = {t: int(ids.size) for t, ids in sorted(final_ids.items())}
        return CheckResult(
            self.name, True,
            f"{len(self._snapshots)} snapshot(s) conserved; "
            f"final rows {rows}",
        )


class CheckpointMonotonicity:
    """Saved versions strictly increase per checkpoint dir; every
    restore version is <= the newest save seen for that dir at restore
    time. Feed it through ``FaultInjector.add_checkpoint_listener``
    (the saver hooks report both sides)."""

    name = "checkpoint_version_monotonicity"

    def __init__(self):
        self._lock = threading.Lock()
        self._saves: Dict[str, List[int]] = {}
        self._restores: Dict[str, List[int]] = {}
        self._problems: List[str] = []

    def on_save(self, checkpoint_dir: str, version: int):
        with self._lock:
            log = self._saves.setdefault(checkpoint_dir, [])
            # Equal is allowed: a graceful-drain checkpoint_now() may
            # re-publish the version the interval already wrote (an
            # idempotent overwrite); only going BACKWARDS is torn.
            if log and version < log[-1]:
                self._problems.append(
                    f"{checkpoint_dir}: save version went backwards "
                    f"({log[-1]} -> {version})"
                )
            log.append(int(version))

    def on_restore(self, checkpoint_dir: str, version: int):
        with self._lock:
            saves = self._saves.get(checkpoint_dir, [])
            if saves and version > saves[-1]:
                self._problems.append(
                    f"{checkpoint_dir}: restored version {version} "
                    f"newer than last save {saves[-1]}"
                )
            self._restores.setdefault(checkpoint_dir, []).append(
                int(version)
            )

    def check(self) -> CheckResult:
        with self._lock:
            if self._problems:
                return CheckResult(
                    self.name, False, "; ".join(self._problems)
                )
            saves = sum(len(v) for v in self._saves.values())
            restores = sum(len(v) for v in self._restores.values())
        return CheckResult(
            self.name, True,
            f"{saves} save(s) monotone across "
            f"{len(self._saves)} dir(s); {restores} restore(s) sane",
        )


class MasterRestartEquivalence:
    """Journal replay reconstructs the dead master's dispatcher state
    (ISSUE 5).

    The restart seam calls ``observe`` with the crashing master's
    exported dispatcher state (its in-memory truth at the moment of
    death — the harness can see it; a real crash couldn't) and the
    recovered dispatcher's state after snapshot+tail replay. The two
    must be equivalent field for field: todo order, leases, task-id
    counter, retry budgets, record counters, the idempotence ledger,
    even the epoch-shuffle RNG. The generation fence must strictly
    increase per restart. ``worker_version`` is excluded: it is
    advisory (SSP observation only) and deliberately not journaled.

    Loss-trajectory equivalence and exactly-once accounting then prove
    the *end-to-end* consequence; this checker localizes a replay bug
    to the restart where state first diverged.
    """

    name = "master_restart_equivalence"

    _VOLATILE = ("worker_version",)

    def __init__(self, expected_restarts: int = 0):
        self._expected = int(expected_restarts)
        self._lock = threading.Lock()
        self._problems: List[str] = []
        self._restarts: List[dict] = []

    @classmethod
    def _normalize(cls, state: dict) -> dict:
        return {
            k: v for k, v in state.items() if k not in cls._VOLATILE
        }

    def observe(self, dead_state: dict, recovered_state: dict,
                old_generation: int, new_generation: int,
                replayed: int):
        with self._lock:
            index = len(self._restarts)
            self._restarts.append({
                "replayed": int(replayed),
                "generation": int(new_generation),
            })
            if new_generation <= old_generation:
                self._problems.append(
                    f"restart {index}: generation did not advance "
                    f"({old_generation} -> {new_generation})"
                )
            dead = self._normalize(dead_state)
            recovered = self._normalize(recovered_state)
            if dead != recovered:
                diverged = sorted(
                    k for k in set(dead) | set(recovered)
                    if dead.get(k) != recovered.get(k)
                )
                self._problems.append(
                    f"restart {index}: replay diverged from the dead "
                    f"master's state in field(s) {diverged}"
                )

    def check(self) -> CheckResult:
        with self._lock:
            if self._problems:
                return CheckResult(
                    self.name, False, "; ".join(self._problems)
                )
            if len(self._restarts) < self._expected:
                return CheckResult(
                    self.name, False,
                    f"only {len(self._restarts)} of {self._expected} "
                    "planned master kill(s) restarted — the seam "
                    "never fired",
                )
            detail = ", ".join(
                f"#{i}: gen {r['generation']} after {r['replayed']} "
                "record(s)"
                for i, r in enumerate(self._restarts)
            )
        return CheckResult(
            self.name, True,
            f"{len(self._restarts)} restart(s) recovered equivalent "
            f"dispatcher state ({detail})" if self._restarts
            else "no master restarts in this plan",
        )


class LossTrajectoryEquivalence:
    """Faulted run == fault-free twin at equal data order.

    ``baseline``/``observe`` take the job summary the runner builds:
    ``{"final_version": int, "final_loss": float,
    "leaves": {name: ndarray}}``. Comparison is allclose with a small
    tolerance — recovery replays the same ops in the same order, so on
    one host the trajectories should be bit-equal; the tolerance only
    absorbs reduction-order noise if a backend reorders."""

    name = "loss_trajectory_equivalence"

    def __init__(self, baseline: Optional[dict], atol: float = 1e-5):
        self._baseline = baseline
        self._faulted: Optional[dict] = None
        self._atol = float(atol)

    def observe(self, faulted: dict):
        self._faulted = faulted

    def check(self) -> CheckResult:
        if self._baseline is None:
            return CheckResult(
                self.name, True, "skipped: no fault-free twin run"
            )
        if self._faulted is None:
            return CheckResult(
                self.name, False, "faulted run produced no summary"
            )
        base, run = self._baseline, self._faulted
        problems: List[str] = []
        if run["final_version"] != base["final_version"]:
            problems.append(
                f"final version {run['final_version']} != twin "
                f"{base['final_version']} (training lost or repeated)"
            )
        b_loss, r_loss = base.get("final_loss"), run.get("final_loss")
        if (b_loss is None) != (r_loss is None):
            problems.append(
                f"final loss presence differs (twin={b_loss}, "
                f"faulted={r_loss})"
            )
        elif b_loss is not None and not np.isclose(
            r_loss, b_loss, atol=self._atol, rtol=0.0
        ):
            problems.append(
                f"final loss {r_loss:.8f} != twin {b_loss:.8f}"
            )
        base_leaves = base.get("leaves") or {}
        run_leaves = run.get("leaves") or {}
        if set(base_leaves) != set(run_leaves):
            problems.append("dense leaf sets differ")
        else:
            worst, worst_name = 0.0, ""
            for name, arr in base_leaves.items():
                diff = float(np.max(np.abs(
                    np.asarray(run_leaves[name], np.float64)
                    - np.asarray(arr, np.float64)
                ))) if np.asarray(arr).size else 0.0
                if diff > worst:
                    worst, worst_name = diff, name
            if worst > self._atol:
                problems.append(
                    f"dense params diverged: max |delta| {worst:.3e} "
                    f"at {worst_name!r}"
                )
        if problems:
            return CheckResult(self.name, False, "; ".join(problems))
        return CheckResult(
            self.name, True,
            f"version {run['final_version']} and "
            f"{len(run_leaves)} dense leaves match the twin",
        )
