"""``python -m elasticdl_tpu`` → the CLI (reference setup.py:33-35
console entry point ``elasticdl``)."""

import sys

from elasticdl_tpu.api.client import main

sys.exit(main())
