"""``python -m elasticdl_tpu`` → the CLI (reference setup.py:33-35
console entry point ``elasticdl``): ``train | evaluate | predict |
serve | route | chaos | trace | clean`` (``serve`` = the online
inference server, serving/server.py; ``route`` = the serving-fleet
router, serving/router.py; ``chaos`` = the fault-injection harness,
chaos/runner.py; ``trace`` = the distributed-tracing smoke →
Perfetto JSON, observability/trace_export.py)."""

import sys

from elasticdl_tpu.api.client import main

sys.exit(main())
