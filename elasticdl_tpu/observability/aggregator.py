"""Master-side cluster metrics: merge worker snapshots, age out leavers.

Workers piggyback registry snapshots on master-client RPCs they already
make (get_task / report_task_result / report_version) — no new RPC, no
scrape path into worker pods. ``ClusterMetrics`` keeps the latest
snapshot per worker id plus its arrival time; a worker that stops
reporting (preempted, scaled away on elastic resize) ages out after
``ttl_secs`` and its series vanish from ``/metrics``; the master's
recovery path removes it immediately.

``MetricsPlane`` is the whole master-side assembly: the master-local
registry (task dispatcher, checkpoint, straggler counters), the cluster
view, the ``/metrics`` HTTP endpoint, and the TensorBoard bridge that
mirrors selected cluster aggregates into the existing ``SummaryWriter``
so TensorBoard stays the human view.
"""

import threading
import time
from typing import Callable, Dict, Optional

from elasticdl_tpu.observability.exposition import (
    MetricsHTTPServer,
    render_prometheus,
)
from elasticdl_tpu.observability.registry import (
    MetricsRegistry,
    default_registry,
)


def _accumulate(snapshot: dict, totals: Dict[str, float],
                hist: Dict[str, list], include_gauges: bool):
    """Fold one snapshot into scalar accumulators: counters sum,
    histograms pool (sum, count). Gauges are point-in-time, so a
    departed worker's gauges must NOT linger — callers pass
    ``include_gauges=False`` for retired snapshots."""
    for family in snapshot.get("families", []):
        name = family["name"]
        kind = family["kind"]
        if kind == "gauge" and not include_gauges:
            continue
        for series in family.get("series", []):
            if kind == "histogram":
                acc = hist.setdefault(name, [0.0, 0])
                acc[0] += series["sum"]
                acc[1] += series["count"]
            else:
                totals[name] = totals.get(name, 0.0) + series["value"]


class ClusterMetrics:
    """Latest snapshot per worker id, with TTL-based aging.

    Departure does not lose history: a removed/expired worker's last
    snapshot is *retired*, and ``aggregate()`` keeps counting its
    counters and histogram totals so the TensorBoard-bridged cluster
    totals stay monotonic across elastic resizes. Its labeled series
    still vanish from ``/metrics`` (Prometheus handles departures via
    staleness; the scalar bridge can't). The snapshot's registry
    ``instance`` token disambiguates a reappearing worker id: same
    token → the live process flapped past the TTL, its cumulative
    values continue (un-retire); different token → a replacement
    process whose counters restarted, the old values fold into a
    permanent base."""

    def __init__(self, ttl_secs: float = 60.0):
        self.ttl_secs = float(ttl_secs)
        self._lock = threading.Lock()
        # worker_id -> (snapshot, monotonic arrival time)
        self._snapshots: Dict[int, tuple] = {}
        # worker_id -> last snapshot at departure (counters still owed
        # to the aggregate until the id reappears and is reconciled).
        self._retired: Dict[int, dict] = {}
        # (worker_id, instance token) -> (totals, hist): the latest
        # folded counter/histogram contribution of each replaced
        # process generation. Keyed per generation and REPLACED (not
        # added) on re-fold, so a stalled-but-alive old process
        # alternating reports with its replacement stays bounded — the
        # base always holds each generation's latest values exactly
        # once, and a generation that reports again (its cumulative
        # values now ride its live snapshot) drops its fold entry.
        self._folds: Dict[tuple, tuple] = {}
        # Memory bound under elastic churn: only the newest
        # _MAX_FOLDS_PER_WORKER generations stay individually keyed;
        # older ones (long dead — only a generation resurrected after
        # that many successors could double count, and none can, since
        # instance tokens die with their process) compact into one
        # permanent base.
        self._compacted_totals: Dict[str, float] = {}
        self._compacted_hist: Dict[str, list] = {}
        # Tokens whose fold was compacted (dict for insertion-order
        # eviction): if such a generation turns out to be stalled-but-
        # alive and reports again, its compacted contribution is
        # cancelled approximately (see ingest) instead of double
        # counting forever.
        self._compacted_tokens: Dict[tuple, None] = {}

    _MAX_FOLDS_PER_WORKER = 4
    _MAX_COMPACTED_TOKENS = 4096

    def _fold_locked(self, worker_id: int, snapshot: dict):
        """Record a replaced generation's counters/histograms in the
        base, replacing any earlier fold of the same generation, then
        compact this worker's oldest generations past the cap."""
        totals: Dict[str, float] = {}
        hist: Dict[str, list] = {}
        _accumulate(snapshot, totals, hist, include_gauges=False)
        self._folds[(worker_id, snapshot["instance"])] = (totals, hist)
        keys = [k for k in self._folds if k[0] == worker_id]
        for oldest in keys[:-self._MAX_FOLDS_PER_WORKER]:
            old_totals, old_hist = self._folds.pop(oldest)
            for name, value in old_totals.items():
                self._compacted_totals[name] = (
                    self._compacted_totals.get(name, 0.0) + value
                )
            for name, (h_sum, h_count) in old_hist.items():
                acc = self._compacted_hist.setdefault(name, [0.0, 0])
                acc[0] += h_sum
                acc[1] += h_count
            self._compacted_tokens[oldest] = None
            while len(self._compacted_tokens) > self._MAX_COMPACTED_TOKENS:
                self._compacted_tokens.pop(
                    next(iter(self._compacted_tokens))
                )

    @staticmethod
    def _key(worker_id):
        """Reporter key: workers stay ints; named components (the
        serving router's snapshot piggyback reports as ``router-N``)
        key by string. Sorting mixed keys always goes through
        ``key=str``."""
        if isinstance(worker_id, str):
            return worker_id
        return int(worker_id)

    def ingest(self, worker_id, snapshot: dict,
               now: Optional[float] = None):
        if not snapshot:
            return
        if not isinstance(worker_id, str) and worker_id < 0:
            return
        now = time.monotonic() if now is None else now
        wid = self._key(worker_id)
        token = snapshot.get("instance")
        with self._lock:
            retired = self._retired.pop(wid, None)
            if retired is not None:
                old = retired.get("instance")
                if old and token and old != token:
                    self._fold_locked(wid, retired)
                # Same (or unknown) instance: the retired snapshot's
                # values live on inside the new one — just un-retire.
            live = self._snapshots.get(wid)
            if live is not None:
                old = live[0].get("instance")
                if old and token and old != token:
                    # A relaunched worker reusing a still-live name
                    # (died and came back inside the TTL, before the
                    # master noticed): the dead process's counters must
                    # fold into the base, not be silently overwritten —
                    # the aggregate would regress — and its stale
                    # snapshot must not survive the replacement's.
                    self._fold_locked(wid, live[0])
            if token:
                # This generation's cumulative values now ride its live
                # snapshot; an earlier fold of it (the stalled-old-
                # process flap, or a fold-then-reappear) must not keep
                # counting on top.
                self._folds.pop((wid, token), None)
                if (wid, token) in self._compacted_tokens:
                    del self._compacted_tokens[(wid, token)]
                    # A generation already compacted into the permanent
                    # base turned out to be stalled-but-alive. Its
                    # exact compacted amounts are gone; cancel with the
                    # snapshot's CURRENT values (counters only grow, so
                    # they bound the compacted ones) — the residual
                    # error is one stall-window of growth, versus a
                    # permanent full double count.
                    neg_t: Dict[str, float] = {}
                    neg_h: Dict[str, list] = {}
                    _accumulate(snapshot, neg_t, neg_h,
                                include_gauges=False)
                    for name, value in neg_t.items():
                        self._compacted_totals[name] = (
                            self._compacted_totals.get(name, 0.0)
                            - value
                        )
                    for name, (h_sum, h_count) in neg_h.items():
                        acc = self._compacted_hist.setdefault(
                            name, [0.0, 0]
                        )
                        acc[0] -= h_sum
                        acc[1] -= h_count
            self._snapshots[wid] = (snapshot, now)

    def remove_worker(self, worker_id):
        """Immediate removal (master recovered the worker's tasks /
        elastic resize scaled it away) — don't wait for the TTL."""
        with self._lock:
            self._retire_locked(self._key(worker_id))

    def _retire_locked(self, worker_id: int):
        entry = self._snapshots.pop(worker_id, None)
        if entry is not None:
            self._retired[worker_id] = entry[0]

    def worker_ids(self):
        return sorted(self.snapshots(), key=str)

    def snapshots(self, now: Optional[float] = None) -> Dict[int, dict]:
        """Live snapshots; expired workers are retired as a side effect."""
        return {
            wid: snap
            for wid, (snap, _ts) in self.snapshot_entries(now).items()
        }

    def snapshot_entries(self, now: Optional[float] = None) -> Dict:
        """Live ``{reporter: (snapshot, arrival time)}`` — the arrival
        time is the time-series sampler's *fingerprint*: a reporter
        whose snapshot hasn't re-arrived since the last sample is
        skipped there, so its series go stale instead of flat-lining
        at the last piggybacked value (the TTL then removes it from
        /metrics entirely)."""
        now = time.monotonic() if now is None else now
        with self._lock:
            expired = [
                wid for wid, (_s, ts) in self._snapshots.items()
                if now - ts > self.ttl_secs
            ]
            for wid in expired:
                self._retire_locked(wid)
            return dict(self._snapshots)

    # ---- cross-worker scalar aggregates --------------------------------

    def aggregate(self) -> Dict[str, float]:
        """Sum counters/gauges and mean histograms across live workers,
        plus retired/replaced generations' counters/histograms (gauges
        excluded) — the scalar view the TensorBoard bridge mirrors."""
        live = self.snapshots()
        with self._lock:
            totals = dict(self._compacted_totals)
            hist = {
                k: list(v) for k, v in self._compacted_hist.items()
            }
            for fold_totals, fold_hist in self._folds.values():
                for name, value in fold_totals.items():
                    totals[name] = totals.get(name, 0.0) + value
                for name, (h_sum, h_count) in fold_hist.items():
                    acc = hist.setdefault(name, [0.0, 0])
                    acc[0] += h_sum
                    acc[1] += h_count
            retired = list(self._retired.values())
        for snapshot in retired:
            _accumulate(snapshot, totals, hist, include_gauges=False)
        for snapshot in live.values():
            _accumulate(snapshot, totals, hist, include_gauges=True)
        for name, (total, count) in hist.items():
            totals[f"{name}_count"] = totals.get(
                f"{name}_count", 0.0
            ) + count
            if count:
                totals[f"{name}_mean"] = total / count
        return totals


class MetricsPlane:
    """Master-side telemetry assembly: local registry + cluster view +
    exposition endpoint + TensorBoard bridge."""

    def __init__(self, registry: Optional[MetricsRegistry] = None,
                 ttl_secs: float = 60.0, summary_writer=None):
        from elasticdl_tpu.observability.profiler import ProfileStore
        from elasticdl_tpu.observability.tracing import TraceCollector

        self.registry = registry or default_registry()
        self.cluster = ClusterMetrics(ttl_secs)
        # Distributed-tracing collection: spans piggyback inside the
        # same worker snapshots the cluster view merges (a "spans" key
        # next to "families"); the collector dedups by span id.
        self.traces = TraceCollector()
        # Continuous-profiling collection: flame-table windows ride the
        # same snapshots under a "profiles" key (and the master's own
        # profiler folds in via pull_local); served on /profile.
        self.profiles = ProfileStore()
        # The SLO plane (optional, see enable_timeseries/enable_slo):
        # a time-series store periodically sampling this plane, and a
        # rule engine evaluated right after each sample.
        self.timeseries = None
        self.slo = None
        # TensorboardService (write_dict_to_summary) or SummaryWriter
        # (add_scalars) — both are duck-typed below; None = no bridge.
        self._summary_writer = summary_writer
        self._last_published = None
        self._http: Optional[MetricsHTTPServer] = None
        # Extra JSON routes registered by subsystems that come up
        # around the plane (e.g. the gang scheduler's /sched):
        # merged into _json_routes() and live-added to an already
        # started server.
        self._extra_routes: Dict[str, Callable] = {}
        # /healthz verdict callable (observability/prober.py healthz):
        # None keeps the exposition layer's static "ok" liveness body.
        self._health_fn: Optional[Callable[[], dict]] = None

    # ---- ingest / render ----------------------------------------------

    def ingest(self, worker_id: int, snapshot: dict):
        spans = snapshot.pop("spans", None) if snapshot else None
        if spans:
            self.traces.ingest(spans)
        profiles = snapshot.pop("profiles", None) if snapshot else None
        if profiles:
            self.profiles.ingest(str(worker_id), profiles)
        self.cluster.ingest(worker_id, snapshot)

    def remove_worker(self, worker_id):
        """Deliberate departure (scale-down drain, recovery dropping a
        dead id): retire from the cluster view AND forget the
        time-series — an intentional removal must not trip the absence
        rules meant for reporters that died unexpectedly."""
        self.cluster.remove_worker(worker_id)
        if self.timeseries is not None:
            self.timeseries.drop_source(str(worker_id))
        self.profiles.drop_source(str(worker_id))

    def render(self) -> str:
        return render_prometheus(
            self.registry.snapshot(), self.cluster.snapshots()
        )

    def render_openmetrics(self) -> str:
        """The OpenMetrics form (histogram exemplars included) served
        when a scraper's Accept asks for it — exemplars are illegal in
        the classic 0.0.4 text the default render emits."""
        return render_prometheus(
            self.registry.snapshot(), self.cluster.snapshots(),
            exemplars=True,
        )

    def trace_spans(self) -> list:
        """Collected spans: piggybacked worker spans ∪ this process's
        own flight-recorder ring (master dispatch spans never ride a
        report RPC), deduped by span id."""
        from elasticdl_tpu.observability import tracing

        merged = tracing.TraceCollector()
        merged.ingest(self.traces.spans())
        merged.ingest(tracing.recorder_spans())
        return merged.spans()

    def render_traces(self) -> dict:
        """JSON body for the ``/traces`` endpoint."""
        return {"spans": self.trace_spans()}

    # ---- SLO plane (observability/timeseries.py + slo.py) --------------

    def enable_timeseries(self, cadence_secs: float = 5.0, **kwargs):
        """Attach the master-side time-series store; sampled from the
        run-loop tick via ``slo_tick`` and served on ``/timeseries``."""
        from elasticdl_tpu.observability.timeseries import TimeSeriesStore

        self.timeseries = TimeSeriesStore(
            cadence_secs=cadence_secs, **kwargs
        )
        return self.timeseries

    def enable_slo(self, rules=None, incident_recorder=None, clock=None):
        """Attach the SLO engine over the (required) time-series store;
        evaluated after every sample, served on ``/alerts``."""
        from elasticdl_tpu.observability.slo import SLOEngine

        if self.timeseries is None:
            raise RuntimeError(
                "enable_timeseries() before enable_slo()"
            )
        kwargs = {"clock": clock} if clock is not None else {}
        self.slo = SLOEngine(
            self.timeseries, rules=rules,
            metrics_registry=self.registry,
            incident_recorder=incident_recorder, **kwargs,
        )
        return self.slo

    def sample_timeseries(self, now: Optional[float] = None) -> bool:
        """Feed one sample (if due) from the local registry + every
        live cluster reporter into the store. Reporter snapshots carry
        their arrival time as the staleness fingerprint."""
        if self.timeseries is None or not self.timeseries.due(now):
            return False
        sources = {"": (self.registry.snapshot(), None)}
        for wid, (snap, arrived) in \
                self.cluster.snapshot_entries().items():
            sources[str(wid)] = (snap, arrived)
        self.timeseries.sample(sources, now=now)
        return True

    def slo_tick(self, now: Optional[float] = None):
        """The master run-loop hook: sample if due, then evaluate the
        rules on fresh data. Cheap when not due (one clock read).
        Exception-contained: a malformed piggybacked snapshot (or any
        store/engine bug) must degrade telemetry, never crash the run
        loop that dispatches the job."""
        try:
            if self.sample_timeseries(now) and self.slo is not None:
                return self.slo.evaluate(now)
        except Exception:
            from elasticdl_tpu.common.log_utils import get_logger

            get_logger("metrics_plane").exception("slo tick failed")
        return None

    # ---- HTTP ----------------------------------------------------------

    def _json_routes(self):
        # Both routes resolve self.timeseries/self.slo at request time:
        # a plane enabled after serve() (tests, the drill harness)
        # still gets its endpoints.
        def timeseries_route(params: dict):
            if self.timeseries is None:
                return {"error": "time-series store disabled "
                                 "(--timeseries_secs 0)"}
            window = params.get("window")
            return self.timeseries.render(
                name=params.get("name"),
                window_secs=float(window) if window else None,
                tier=params.get("tier", "hot"),
            )

        def alerts_route(params: dict):
            if self.slo is None:
                return {"error": "SLO engine disabled", "rules": [],
                        "firing": []}
            return self.slo.render()

        def profile_route(params: dict):
            # /profile?component=<key>&window=<secs>[&base=<secs back>]
            # [&spans=0]: the flame view of one component (folded text
            # + pprof-style JSON), optionally differential against the
            # same-length window ending `base` seconds earlier, with
            # the component's trace spans folded in as `phases;...`
            # pseudo-stacks (device/phase attribution). No component =
            # the list of components with profile data.
            component = params.get("component")
            if component is None:
                self.profiles.pull_local()
                return {"components": self.profiles.components()}
            window = float(params.get("window") or 60.0)
            base = params.get("base")
            spans = None
            if params.get("spans", "1") != "0":
                spans = self.trace_spans()
            return self.profiles.render(
                component, window_secs=window,
                base_secs=float(base) if base else None,
                spans=spans,
            )

        def usage_route(params: dict):
            # /usage[?top=K]: fleet-wide per-principal usage — totals,
            # shares, per-purpose handler time, top-K consumers per
            # shard — folded from the master's own registry plus every
            # live reporter snapshot (observability/usage.py,
            # docs/observability.md "Workload attribution").
            top = params.get("top")
            return self.usage(top_k=int(top) if top else 5)

        routes = {"/timeseries": timeseries_route,
                  "/alerts": alerts_route,
                  "/profile": profile_route, "/usage": usage_route}
        routes.update(self._extra_routes)
        return routes

    def add_json_route(self, path: str, fn: Callable[[dict], dict]):
        """Mount ``fn(params) -> dict`` at ``path`` (e.g. ``/sched``).
        Works before OR after ``serve()``: the running server's route
        table is shared by reference, so the mount is live."""
        self._extra_routes[str(path)] = fn
        if self._http is not None:
            self._http._json_routes[str(path)] = fn

    def set_health(self, fn: Optional[Callable[[], dict]]):
        """Mount the aggregated ``/healthz`` verdict (a zero-arg
        callable returning a dict with an ``ok`` key — unhealthy
        serves HTTP 503). Live on an already-running server, like
        ``add_json_route``."""
        self._health_fn = fn
        if self._http is not None:
            self._http.set_health(fn)

    def usage(self, top_k: int = 5) -> dict:
        """The ``/usage`` body (also callable in-process: drills and
        tests read it without HTTP)."""
        from elasticdl_tpu.observability.usage import summarize_usage

        snapshots = {"": self.registry.snapshot()}
        for wid, snap in self.cluster.snapshots().items():
            snapshots[str(wid)] = snap
        return summarize_usage(snapshots, top_k=top_k)

    def serve(self, port: int = 0, host: str = "") -> MetricsHTTPServer:
        self._http = MetricsHTTPServer(
            self.render, port=port, host=host,
            traces=self.render_traces,
            json_routes=self._json_routes(),
            render_openmetrics=self.render_openmetrics,
            health=self._health_fn,
        ).start()
        return self._http

    @property
    def http_port(self) -> Optional[int]:
        return self._http.port if self._http else None

    def stop(self):
        if self._http is not None:
            self._http.stop()
            self._http = None
        # In-flight incident bundle writes must land before the
        # process that triggered them exits.
        if self.slo is not None and self.slo.incident_recorder \
                is not None:
            self.slo.incident_recorder.flush()

    # ---- TensorBoard bridge -------------------------------------------

    def set_summary_writer(self, writer):
        self._summary_writer = writer

    def publish_tensorboard(self, step: int):
        """Mirror cluster scalar aggregates (prefixed ``metrics/``) into
        the SummaryWriter; called from the master run-loop tick."""
        if self._summary_writer is None:
            return
        scalars = {
            f"metrics/{name}": value
            for name, value in self.cluster.aggregate().items()
        }
        if not scalars:
            return
        # The master calls this every poll tick; during idle stretches
        # (eval phases, stalled workers) step and aggregates sit still —
        # re-writing the identical frame each tick only bloats tfevents.
        if self._last_published == (int(step), scalars):
            return
        self._last_published = (int(step), scalars)
        add = getattr(self._summary_writer, "write_dict_to_summary", None)
        if add is not None:
            add(scalars, int(step))
        else:
            self._summary_writer.add_scalars(scalars, int(step))
