"""Chrome/Perfetto ``trace_event`` export + the ``elasticdl_tpu trace``
CLI.

``chrome_trace`` turns collected span dicts into the Chrome trace-event
JSON that https://ui.perfetto.dev (and chrome://tracing) loads: one
**pid per (role, instance)** — master, each worker, each row-service
shard, serving — one **tid per real thread**, and one complete (``X``)
event per span with the span/trace ids and attributes in ``args``.
Timestamps are the spans' monotonic ``t0`` normalized to the earliest
span; that is exact within one process (the MiniCluster harness and
every test) and per-process-relative across real pods (each process's
monotonic clock has its own epoch — cross-process skew is not
corrected, which Perfetto tolerates and the critical-path report never
depends on, since trees are linked by ids, not timestamps).

The CLI runs a small traced MiniCluster job (the same in-process
harness the chaos plane drives): recorder on, deepfm-host model with
its table behind a real localhost ``HostRowService`` — so the exported
JSON contains task trees crossing master → worker → row-service — then
writes the Perfetto file and prints the ``critical_path`` straggler
report. ``make trace-smoke`` validates the output with
``tools/check_trace.py``.
"""

import json
import os
from typing import Dict, List, Optional, Tuple

from elasticdl_tpu.common.log_utils import get_logger
from elasticdl_tpu.observability import critical_path, tracing

logger = get_logger("trace_export")

DEFAULT_TRACE_PATH = "TRACE.json"


# ---- Chrome trace-event rendering ---------------------------------------


def _track_name(role: str, instance: str) -> str:
    return role if instance in ("", "0") else f"{role}/{instance}"


def chrome_trace(spans: List[dict]) -> dict:
    """Spans → ``{"traceEvents": [...]}`` (Perfetto-loadable)."""
    if not spans:
        return {"traceEvents": [], "displayTimeUnit": "ms"}
    t_base = min(float(s.get("t0", 0.0)) for s in spans)
    pids: Dict[Tuple[str, str], int] = {}
    tids: Dict[Tuple[int, int], int] = {}
    events: List[dict] = []
    for s in spans:
        key = (str(s.get("role", "process")),
               str(s.get("instance", "0")))
        pid = pids.get(key)
        if pid is None:
            pid = pids[key] = len(pids) + 1
            events.append({
                "ph": "M", "name": "process_name", "pid": pid, "tid": 0,
                "args": {"name": _track_name(*key)},
            })
        tkey = (pid, int(s.get("tid", 0)))
        tid = tids.get(tkey)
        if tid is None:
            tid = 1 + sum(1 for k in tids if k[0] == pid)
            tids[tkey] = tid
            events.append({
                "ph": "M", "name": "thread_name", "pid": pid, "tid": tid,
                "args": {"name": f"thread-{tid}"},
            })
        args = {
            "trace_id": s.get("trace_id"),
            "span_id": s.get("span_id"),
            "parent_id": s.get("parent_id"),
        }
        attrs = s.get("attrs") or {}
        for name, value in attrs.items():
            args[str(name)] = value
        events.append({
            "ph": "X",
            "name": str(s.get("name", "span")),
            "cat": key[0],
            "ts": round((float(s.get("t0", 0.0)) - t_base) * 1e6, 3),
            "dur": round(float(s.get("dur", 0.0)) * 1e6, 3),
            "pid": pid,
            "tid": tid,
            "args": args,
        })
    return {"traceEvents": events, "displayTimeUnit": "ms"}


def export_chrome_trace(spans: List[dict], path: str) -> dict:
    """Write the Perfetto JSON for ``spans``; returns the trace dict."""
    trace = chrome_trace(spans)
    parent = os.path.dirname(os.path.abspath(path))
    os.makedirs(parent, exist_ok=True)
    with open(path, "w") as fh:
        json.dump(trace, fh, indent=1)
        fh.write("\n")
    logger.info(
        "wrote %d trace events to %s", len(trace["traceEvents"]), path
    )
    return trace


# ---- traced demo job ----------------------------------------------------

SPARSE_MODEL_DEF = "deepfm.deepfm_host.custom_model"
DENSE_MODEL_DEF = "mnist.mnist_functional.custom_model"


def run_traced_job(
    workdir: str,
    model: str = "sparse",
    num_workers: int = 2,
    records: int = 64,
    minibatch_size: int = 8,
    num_minibatches_per_task: int = 2,
    recorder_capacity: int = 16384,
    use_rpc: bool = True,
) -> List[dict]:
    """Run a MiniCluster job with the flight recorder installed and
    return every collected span (master TraceCollector ∪ process ring,
    deduped). ``sparse`` puts the embedding table behind a localhost
    ``HostRowService`` so pull spans cross a real RPC hop."""
    if model not in ("sparse", "dense"):
        raise ValueError(f"unknown trace model flavor {model!r}")
    from elasticdl_tpu.testing.cluster import MiniCluster
    from elasticdl_tpu.testing.data import (
        create_frappe_record_file,
        create_mnist_record_file,
        model_zoo_dir,
    )

    os.makedirs(workdir, exist_ok=True)
    data_path = os.path.join(workdir, "train.rec")
    if not os.path.exists(data_path):
        if model == "sparse":
            create_frappe_record_file(data_path, records, seed=11)
        else:
            create_mnist_record_file(data_path, records, seed=11)

    recorder = tracing.FlightRecorder(capacity=recorder_capacity)
    tracing.install_recorder(recorder)
    services = []
    cluster = None
    try:
        runner_factory = None
        if model == "sparse":
            from model_zoo.deepfm import deepfm_host
            from elasticdl_tpu.embedding import HostStepRunner
            from elasticdl_tpu.embedding.row_service import (
                make_remote_engine,
            )

            svc = deepfm_host.make_row_service()
            svc.start(tag="rowservice/0")
            services.append(svc)
            addr = f"localhost:{svc.port}"

            def runner_factory():
                # Synchronous applies: pulls/pushes happen on the worker
                # thread, so their RPC spans nest under the step span.
                return HostStepRunner(
                    make_remote_engine(
                        addr,
                        id_keys={
                            deepfm_host.TABLE_NAME:
                                deepfm_host.FEATURE_KEY
                        },
                    ),
                    async_apply=False,
                )

        cluster = MiniCluster(
            model_zoo=model_zoo_dir(),
            model_def=(
                SPARSE_MODEL_DEF if model == "sparse" else DENSE_MODEL_DEF
            ),
            training_data=data_path,
            minibatch_size=minibatch_size,
            num_minibatches_per_task=num_minibatches_per_task,
            num_workers=num_workers,
            use_rpc=use_rpc,
            step_runner_factory=runner_factory,
            metrics_report_secs=0.0,
        )
        cluster.run()
        # Piggybacked spans landed in the master collector; the process
        # ring still holds everything (one process) — merge and dedup.
        collector = tracing.TraceCollector(capacity=2 * recorder_capacity)
        collector.ingest(cluster.metrics_plane.trace_spans())
        collector.ingest(recorder.snapshot())
        return collector.spans()
    finally:
        tracing.uninstall_recorder()
        if cluster is not None:
            if cluster._server is not None:
                cluster._server.stop(0)
            cluster.stop()
        for svc in services:
            try:
                svc.stop(0)
            except Exception:
                pass


# ---- CLI ----------------------------------------------------------------


def _force_cpu_if_requested():
    """Same dance as chaos/runner.py: the container's sitecustomize may
    pin a TPU plugin over JAX_PLATFORMS=cpu."""
    if os.environ.get("JAX_PLATFORMS", "") == "cpu":
        import jax

        jax.config.update("jax_platforms", "cpu")


def main(argv=None) -> int:
    """``elasticdl_tpu trace <flags>``: run a traced in-process job,
    export Perfetto JSON, print the critical-path report."""
    import argparse
    import shutil
    import tempfile

    parser = argparse.ArgumentParser("elasticdl_tpu-trace")
    parser.add_argument("--out", default=DEFAULT_TRACE_PATH,
                        help="Perfetto trace_event JSON output path")
    parser.add_argument("--report", default="",
                        help="Also write the critical-path report JSON "
                             "here (default: print text only)")
    parser.add_argument("--model", choices=["sparse", "dense"],
                        default="sparse")
    parser.add_argument("--num_workers", type=int, default=2)
    parser.add_argument("--records", type=int, default=64)
    parser.add_argument("--minibatch_size", type=int, default=8)
    parser.add_argument("--num_minibatches_per_task", type=int, default=2)
    parser.add_argument("--recorder_spans", type=int, default=16384,
                        help="Flight-recorder ring capacity")
    parser.add_argument("--in_process", action="store_true",
                        help="Direct servicer calls instead of "
                             "localhost gRPC (spans stay connected; "
                             "RPC client/server spans disappear)")
    parser.add_argument("--workdir", default="",
                        help="Scratch dir (default: fresh tempdir, "
                             "removed afterwards)")
    args = parser.parse_args(argv)

    _force_cpu_if_requested()

    workdir = args.workdir
    cleanup = False
    if not workdir:
        workdir = tempfile.mkdtemp(prefix="edl_trace_")
        cleanup = True
    try:
        spans = run_traced_job(
            workdir,
            model=args.model,
            num_workers=args.num_workers,
            records=args.records,
            minibatch_size=args.minibatch_size,
            num_minibatches_per_task=args.num_minibatches_per_task,
            recorder_capacity=args.recorder_spans,
            use_rpc=not args.in_process,
        )
    finally:
        if cleanup:
            shutil.rmtree(workdir, ignore_errors=True)
    export_chrome_trace(spans, args.out)
    report = critical_path.analyze(spans)
    print(critical_path.render_report(report), end="")
    if args.report:
        with open(args.report, "w") as fh:
            json.dump(report, fh, indent=2, sort_keys=True)
            fh.write("\n")
    print(f"trace written to {args.out} "
          f"({len(spans)} spans; open at https://ui.perfetto.dev)")
    return 0


if __name__ == "__main__":
    import sys

    sys.exit(main())
