"""Unified telemetry plane (metrics registry → master aggregation →
Prometheus exposition).

The reference's only observability is per-phase wall-clock accumulators
and a TensorBoard sidecar (SURVEY §L1/§5). This subsystem gives every
layer a shared measurement substrate instead:

- ``registry``:    process-local counters / gauges / histograms
                   (labeled families, thread-safe) that the worker step
                   loop, ``common/timing.py``, the task dispatcher, the
                   embedding tier, and the checkpoint saver feed into;
- ``aggregator``:  the master-side cluster view — workers piggyback
                   registry snapshots on existing master-client RPCs,
                   the servicer merges them keyed by worker id, and
                   departed workers age out on elastic resize;
- ``exposition``:  Prometheus text format over a stdlib-only HTTP
                   endpoint (``/metrics`` + ``/healthz`` + ``/traces``)
                   plus a bridge mirroring selected aggregates into the
                   tfevents ``SummaryWriter`` so TensorBoard stays the
                   human view;
- ``tracing``:     distributed spans into a bounded flight recorder,
                   with trace context propagated through the RPC layer
                   (``comm/rpc.py``) and collected over the same
                   piggyback path as metrics snapshots;
- ``trace_export``: Chrome/Perfetto ``trace_event`` JSON export + the
                   ``elasticdl_tpu trace`` CLI;
- ``critical_path``: per-step critical-path and straggler-attribution
                   reports over collected span trees;
- ``profiler``:    the continuous-profiling plane — an always-on
                   sampling profiler folding Python stacks into
                   bounded flame tables, windows piggybacked to the
                   master's ``ProfileStore`` and served on
                   ``/profile`` (folded text, pprof-style JSON,
                   differential views, span-derived phase stacks);
- ``principal``:   the workload-attribution identity — a
                   ``{job, component, purpose}`` principal (closed
                   purpose enum) piggybacked on every RPC next to the
                   trace context, with a thread-local ambient stack
                   plus a process default so internal fan-outs
                   self-tag (docs/observability.md "Workload
                   attribution");
- ``usage``:       per-principal metering (requests, rows, bytes,
                   lock-hold, fsync-wait, cold-fault I/O) under
                   bounded label families, rolled up by the master's
                   ``/usage`` endpoint into who-pays shares and
                   per-shard top-K;
- ``timeseries``:  the master-side ring time-series store sampling the
                   registries above (counters as rates, gauges as-is,
                   histograms as rolling quantiles; hot + downsampled
                   cold retention tiers; ``/timeseries`` endpoint);
- ``slo``:         declarative SLO rules (multi-window burn rate,
                   threshold, absence/staleness) evaluated on the
                   master tick, ``/alerts`` + ``edl_tpu_alert_active``
                   gauges, and black-box incident bundles captured on
                   firing (``IncidentRecorder``).

Metric names follow ``edl_tpu_<layer>_<name>`` (docs/observability.md).
"""

from elasticdl_tpu.observability.aggregator import (  # noqa: F401
    ClusterMetrics,
    MetricsPlane,
)
from elasticdl_tpu.observability.exposition import (  # noqa: F401
    MetricsHTTPServer,
    render_prometheus,
)
from elasticdl_tpu.observability.principal import (  # noqa: F401
    Principal,
)
from elasticdl_tpu.observability.profiler import (  # noqa: F401
    ProfileStore,
    SamplingProfiler,
)
from elasticdl_tpu.observability.registry import (  # noqa: F401
    MetricsRegistry,
    default_registry,
)
from elasticdl_tpu.observability.slo import (  # noqa: F401
    IncidentRecorder,
    SLOEngine,
    SLORule,
    default_rules,
    load_rules,
)
from elasticdl_tpu.observability.timeseries import (  # noqa: F401
    TimeSeriesStore,
)
from elasticdl_tpu.observability.tracing import (  # noqa: F401
    FlightRecorder,
    TraceCollector,
    Tracer,
)
