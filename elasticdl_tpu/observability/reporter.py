"""Fold a standalone component's telemetry into the master's cluster
view.

Workers piggyback registry snapshots on RPCs they already make; a
process with no task loop (the serving router, a predict replica, a
row-service shard) has nothing to piggyback on, so this thread
periodically pushes the snapshot over the master's ``report_metrics``
RPC instead. The master keys it ``<component>-<id>`` — same TTL aging,
same exposition (``worker="router-0"`` / ``worker="serving-1"``
labels), same time-series sampling as any worker, which is what lets
master-side SLO rules (e.g. the default ``row-freshness`` rule over
the replicas' ``edl_tpu_row_freshness_seconds``) watch the whole fleet
(docs/observability.md "Time series").

Like the worker's piggyback, the snapshot carries the process's trace
spans (``spans`` key) and continuous-profiling windows (``profiles``
key) when a flight recorder / sampling profiler is installed — cursors
commit only on a CONFIRMED delivery, so spans/windows offered on a
failed report are re-offered next interval instead of being lost with
the outage they describe.
"""

import threading

from elasticdl_tpu.common.log_utils import get_logger

logger = get_logger("metrics_reporter")


class ComponentMetricsReporter(threading.Thread):
    """Daemon thread pushing this process's registry snapshot to the
    master every ``interval_secs``. Master unavailability degrades to
    a warning + channel rebuild (a refused gRPC channel can wedge
    permanently in-container — the PR 5/6 lesson), never an error in
    the component itself."""

    def __init__(self, master_addr: str, component: str,
                 component_id: int = 0, interval_secs: float = 15.0,
                 registry=None):
        super().__init__(
            daemon=True, name=f"{component}-metrics-report"
        )
        from elasticdl_tpu.observability import default_registry

        self._master_addr = master_addr
        self._component = str(component)
        self._component_id = int(component_id)
        self._interval = max(0.5, float(interval_secs))
        self._registry = registry or default_registry()
        self._stop = threading.Event()
        self._stub = None
        self._span_cursor = 0
        self._profile_cursor = 0
        self.reports_sent = 0
        # Decorrelated-jitter backoff after failed reports: a master
        # failover fails EVERY component's report at the same instant,
        # and per-interval retries in lockstep would stampede the
        # promoted standby (comm/rpc.decorrelated_jitter). Reset on
        # the first confirmed delivery.
        self._retry_delay = 0.0

    def send_once(self):
        from elasticdl_tpu.comm.rpc import RpcStub
        from elasticdl_tpu.observability import profiler, tracing

        if self._stub is None:
            self._stub = RpcStub(
                self._master_addr, "elasticdl_tpu.Master"
            )
        snapshot = self._registry.snapshot()
        spans, span_offer = tracing.spans_since(self._span_cursor)
        if spans:
            snapshot["spans"] = spans
        windows, profile_offer = profiler.windows_since(
            self._profile_cursor
        )
        if windows:
            snapshot["profiles"] = windows
        try:
            from elasticdl_tpu.observability import principal

            # Telemetry pushes are control-plane chatter, tagged as
            # such so the master's usage meter never files them under
            # a workload (the reporter thread has no ambient
            # principal of its own).
            with principal.pushed(component=self._component,
                                  purpose="control"):
                self._stub.call(
                    "report_metrics", component=self._component,
                    component_id=self._component_id,
                    metrics=snapshot,
                )
            # Confirmed delivery: advance past what this report
            # carried (the master dedups re-offers anyway — by span id
            # and by window (seq, t0) — but the cursors keep re-sends
            # bounded).
            self._span_cursor = span_offer
            self._profile_cursor = profile_offer
            self.reports_sent += 1
            self._retry_delay = 0.0
        except Exception as exc:
            from elasticdl_tpu.comm.rpc import decorrelated_jitter

            self._retry_delay = decorrelated_jitter(
                self._retry_delay,
                base=0.5, cap=self._interval,
            )
            logger.warning(
                "%s-%d master metrics report failed (backing off "
                "%.2fs extra): %s",
                self._component, self._component_id,
                self._retry_delay, exc,
            )
            try:
                # The rebuild also rotates a multi-address master
                # target (failover re-resolve).
                self._stub.reconnect()
            except Exception:
                self._stub = None

    def run(self):
        # The jittered extra delay decorrelates the fleet's retries
        # after an outage hits everyone at once.
        while not self._stop.wait(self._interval + self._retry_delay):
            self.send_once()

    def stop(self):
        self._stop.set()
