"""Master-side time-series store: the telemetry plane's memory.

The metrics registry (PR 1) answers *now*, the tracing plane (PR 4)
answers *inside one task* — this module answers *over time*: a bounded
in-memory store that periodically samples the registries the master
already holds (its own ``MetricsRegistry`` plus every piggybacked
worker/router snapshot in ``ClusterMetrics``) and keeps the result in
ring buffers cheap enough to run forever:

- **counters** are stored as per-interval deltas (rendered as rates) —
  a restarted process's counter reset reads as a fresh delta, never a
  negative spike;
- **gauges** are stored as-is;
- **histograms** are stored as per-interval ``(count, sum, bucket)``
  deltas, from which rolling window quantiles (p50/p99/...) and
  fraction-over-threshold SLIs are derived on demand — the inputs the
  SLO engine's burn-rate rules (``observability/slo.py``) need.

Two retention tiers bound memory: a **hot** tier holding every sample
(default 720 points ≈ one hour at the 5 s cadence) and a **cold** tier
holding one downsampled point per ``cold_resolution_secs`` (default
1440 × 60 s = one day): gauges keep mean/min/max, counters keep the
summed delta, histograms keep the flushed interval's p50/p99.

Staleness is first-class: a reporter that stops piggybacking snapshots
must make its series go *stale*, not flat-line — ``ClusterMetrics``
keeps serving the last snapshot until the TTL retires it, so the
sampler skips any source whose snapshot *fingerprint* (arrival time)
has not advanced since the previous sample. ``last_seen`` therefore
freezes the moment the reporter goes silent, which is what the SLO
absence rules key on.

The master serves the store on ``GET /timeseries`` next to
``/metrics`` (``?name=<prefix>&window=<secs>&tier=hot|cold``);
``tools/dump_metrics.py --watch`` makes it terminal-friendly.
"""

import threading
import time
from collections import deque
from typing import Callable, Dict, List, Optional, Tuple

COUNTER = "counter"
GAUGE = "gauge"
HISTOGRAM = "histogram"

# Sampling the whole cluster view is an O(series) python loop on the
# master tick — the unit-test pin (<1ms at default cadence) assumes the
# series population stays bounded. New series past the cap are dropped
# (counted on ``dropped_series``), never silently re-keyed.
DEFAULT_MAX_SERIES = 4096


def quantile_from_buckets(bucket_ubs: Tuple[float, ...],
                          bucket_deltas: List[float],
                          q: float,
                          total: Optional[float] = None) -> float:
    """Nearest-rank quantile estimate from per-bucket observation
    counts (NON-cumulative, matching ``registry`` snapshots): the
    upper bound of the bucket containing the q-th observation.

    ``total`` is the TRUE observation count (the histogram's ``count``
    delta) — observations above the top bucket land in no bucket at
    all, only in ``count``, so ranking against the in-bucket sum alone
    would blind the quantile to the overflow regime entirely (a
    300s-stale freshness histogram with a 120s top bucket would report
    p99=0). A rank past the buckets SATURATES at the last bucket
    bound: the honest reading is "at least this", and it stays
    JSON-safe (``json.dumps`` would emit the non-standard ``Infinity``
    token strict parsers reject)."""
    in_buckets = float(sum(bucket_deltas))
    total = in_buckets if total is None else max(float(total),
                                                in_buckets)
    if total <= 0 or not bucket_ubs:
        return 0.0
    rank = q * total
    seen = 0.0
    for ub, n in zip(bucket_ubs, bucket_deltas):
        seen += float(n)
        if seen >= rank:
            return float(ub)
    return float(bucket_ubs[-1])


class _Series:
    """One sampled series: hot ring of raw samples + cold ring of
    downsampled points + staleness bookkeeping.

    Hot point shapes (tuples, kept tiny on purpose):
      counter:   ``(t, dt, delta)``
      gauge:     ``(t, value)``
      histogram: ``(t, dt, count_d, sum_d, buckets_d)``

    The append path is the sampler's hot loop (every series of every
    reporter, every cadence) and is pinned <1ms per master tick by a
    unit test — per-point work is one ring append plus an integer
    bucket compare; cold-tier aggregation happens once per resolution
    bucket by scanning the hot ring's tail at flush time, never per
    point.
    """

    __slots__ = ("family", "kind", "labels", "source", "bucket_ubs",
                 "points", "cold", "prev", "last_seen", "_cold_bucket")

    def __init__(self, family: str, kind: str, labels: Dict[str, str],
                 source: str, bucket_ubs: Tuple[float, ...],
                 hot_capacity: int, cold_capacity: int):
        self.family = family
        self.kind = kind
        self.labels = labels
        self.source = source
        self.bucket_ubs = bucket_ubs
        self.points = deque(maxlen=hot_capacity)
        self.cold = deque(maxlen=cold_capacity)
        self.prev = None       # last raw cumulative (counter/histogram)
        self.last_seen = 0.0   # wall time of the newest appended point
        self._cold_bucket = None  # resolution bucket of the ring tail

    def key(self) -> str:
        label_text = ",".join(
            f"{k}={v}" for k, v in sorted(self.labels.items())
        )
        key = self.family
        if label_text:
            key += "{%s}" % label_text
        if self.source:
            key += f"@{self.source}"
        return key

    # ---- append --------------------------------------------------------

    def _maybe_flush_cold(self, t: float, resolution: float):
        """Called BEFORE appending a point: when ``t`` enters a new
        cold-resolution bucket, aggregate the previous bucket's points
        (still the ring tail) into one cold point."""
        bucket = int(t // resolution)
        prev_bucket = self._cold_bucket
        if bucket == prev_bucket:
            return
        self._cold_bucket = bucket
        if prev_bucket is None:
            return
        lo = prev_bucket * resolution
        tail = []
        for point in reversed(self.points):
            if point[0] < lo:
                break
            tail.append(point)
        if tail:
            self._flush_cold((prev_bucket + 1) * resolution, tail)

    def _flush_cold(self, t_end: float, tail: List[tuple]):
        if self.kind == GAUGE:
            values = [p[1] for p in tail]
            self.cold.append((
                t_end, sum(values) / len(values), min(values),
                max(values),
            ))
        elif self.kind == COUNTER:
            dt = sum(p[1] for p in tail)
            self.cold.append((t_end, dt, sum(p[2] for p in tail)))
        else:
            dt = sum(p[1] for p in tail)
            count_d = sum(p[2] for p in tail)
            sum_d = sum(p[3] for p in tail)
            buckets_d = [0.0] * len(self.bucket_ubs)
            for point in tail:
                for i, b in enumerate(point[4]):
                    buckets_d[i] += b
            self.cold.append((
                t_end, dt, count_d, sum_d,
                quantile_from_buckets(self.bucket_ubs, buckets_d, 0.50,
                                      total=count_d),
                quantile_from_buckets(self.bucket_ubs, buckets_d, 0.99,
                                      total=count_d),
            ))

    def append_scalar(self, t: float, value: float, dt: float,
                      cold_resolution: float):
        if self.kind == COUNTER:
            prev = self.prev
            self.prev = value
            if prev is None:
                self.last_seen = t
                return
            # dt must be PER-SERIES: a reporter piggybacking every 15s
            # against a 5s sampler is skipped on unchanged fingerprints,
            # so its delta spans since ITS last ingested sample — the
            # global inter-sample interval would inflate its rate 3x.
            if self.last_seen > 0 and t > self.last_seen:
                dt = t - self.last_seen
            delta = value - prev
            if delta < 0:
                # Counter reset (process restart): the new cumulative
                # value IS the growth since the reset.
                delta = value
            if delta == 0:
                # Idle counter: a zero-delta point adds nothing to any
                # window sum — skip it (liveness rides last_seen).
                self.last_seen = t
                return
            self._maybe_flush_cold(t, cold_resolution)
            self.points.append((t, dt, delta))
        else:
            self._maybe_flush_cold(t, cold_resolution)
            self.points.append((t, value))
        self.last_seen = t

    def append_hist(self, t: float, dt: float, count: float, total: float,
                    buckets: List[float], cold_resolution: float):
        prev = self.prev
        self.prev = (count, total, buckets)
        if prev is None:
            self.last_seen = t
            return
        # Per-series dt, same rationale as append_scalar.
        if self.last_seen > 0 and t > self.last_seen:
            dt = t - self.last_seen
        count_d = count - prev[0]
        if count_d == 0 and total == prev[1]:
            # Idle histogram (the steady-state majority): nothing to
            # add to any window — skip the point entirely.
            self.last_seen = t
            return
        if count_d < 0 or len(buckets) != len(prev[2]):
            # Histogram reset (process restart / bucket change): treat
            # the new cumulative values as the interval's growth.
            count_d, sum_d = count, total
            buckets_d = list(buckets)
        else:
            sum_d = total - prev[1]
            buckets_d = [b - p for b, p in zip(buckets, prev[2])]
        self._maybe_flush_cold(t, cold_resolution)
        self.points.append((t, dt, count_d, sum_d, buckets_d))
        self.last_seen = t

    # ---- render --------------------------------------------------------

    def render_points(self, window: Optional[float], now: float,
                      tier: str = "hot",
                      points: Optional[List[tuple]] = None,
                      cold: Optional[List[tuple]] = None) -> List[list]:
        """JSON-safe points. Hot: gauges ``[t, value]``, counters
        ``[t, rate]``, histograms ``[t, rate, mean]``. Cold: gauges
        ``[t, mean, min, max]``, counters ``[t, rate]``, histograms
        ``[t, rate, p50, p99]``.

        ``points``/``cold`` override the live deques — the store's
        ``render`` passes copies taken under its lock, because
        iterating the live deque races the sampler's appends
        (RuntimeError: deque mutated during iteration)."""
        hot_points = self.points if points is None else points
        cold_points = self.cold if cold is None else cold
        cutoff = (now - window) if window else None
        out = []
        if tier == "cold":
            for point in cold_points:
                if cutoff is not None and point[0] < cutoff:
                    continue
                if self.kind == GAUGE:
                    t, mean, mn, mx = point
                    out.append([t, mean, mn, mx])
                elif self.kind == COUNTER:
                    t, dt, delta = point
                    out.append([t, delta / dt if dt > 0 else 0.0])
                else:
                    t, dt, count_d, _sum_d, p50, p99 = point
                    out.append([
                        t, count_d / dt if dt > 0 else 0.0, p50, p99,
                    ])
            return out
        for point in hot_points:
            if cutoff is not None and point[0] < cutoff:
                continue
            if self.kind == GAUGE:
                out.append([point[0], point[1]])
            elif self.kind == COUNTER:
                t, dt, delta = point
                out.append([t, delta / dt if dt > 0 else 0.0])
            else:
                t, dt, count_d, sum_d, _buckets = point
                out.append([
                    t, count_d / dt if dt > 0 else 0.0,
                    sum_d / count_d if count_d > 0 else 0.0,
                ])
        return out


class TimeSeriesStore:
    """Bounded in-memory time series over registry snapshots.

    ``sample(sources)`` ingests ``{source: (snapshot, fingerprint)}``
    — source ``""`` is the master-local registry, others are cluster
    reporters keyed the way ``ClusterMetrics`` keys them (worker ids,
    ``router-N``). A source whose fingerprint matches the previous
    sample is skipped entirely: piggybacked snapshots linger in the
    cluster view until the TTL retires them, and re-appending the same
    snapshot would flat-line a dead reporter instead of letting its
    series go stale. ``fingerprint=None`` always samples (the local
    registry is live by definition).
    """

    def __init__(self, cadence_secs: float = 5.0,
                 hot_capacity: int = 720,
                 cold_resolution_secs: float = 60.0,
                 cold_capacity: int = 1440,
                 max_series: int = DEFAULT_MAX_SERIES,
                 clock: Callable[[], float] = time.time):
        self.cadence_secs = float(cadence_secs)
        self.hot_capacity = int(hot_capacity)
        self.cold_resolution_secs = float(cold_resolution_secs)
        self.cold_capacity = int(cold_capacity)
        self.max_series = int(max_series)
        self._clock = clock
        self._lock = threading.Lock()
        self._series: Dict[Tuple, _Series] = {}
        self._source_fingerprints: Dict[str, object] = {}
        self._last_sample_at: Optional[float] = None
        self.sample_count = 0
        self.dropped_series = 0
        self.last_sample_cost_secs = 0.0

    # ---- sampling ------------------------------------------------------

    def due(self, now: Optional[float] = None) -> bool:
        now = self._clock() if now is None else now
        return (self._last_sample_at is None
                or now - self._last_sample_at >= self.cadence_secs)

    def sample(self, sources: Dict[str, tuple],
               now: Optional[float] = None) -> int:
        now = self._clock() if now is None else now
        cost_t0 = time.monotonic()
        prev_at = self._last_sample_at
        dt = (now - prev_at) if prev_at is not None else self.cadence_secs
        if dt <= 0:
            dt = self.cadence_secs
        updated = 0
        with self._lock:
            self._last_sample_at = now
            for source, entry in sources.items():
                snapshot, fingerprint = entry
                if not snapshot:
                    continue
                if fingerprint is not None:
                    if self._source_fingerprints.get(source) \
                            == fingerprint:
                        continue
                    self._source_fingerprints[source] = fingerprint
                updated += self._ingest_snapshot_locked(
                    str(source), snapshot, now, dt
                )
        self.sample_count += 1
        self.last_sample_cost_secs = time.monotonic() - cost_t0
        return updated

    def _ingest_snapshot_locked(self, source: str, snapshot: dict,
                                now: float, dt: float) -> int:
        # The sampler's hot loop — every series of every reporter each
        # cadence, pinned <1ms per tick by a unit test. Keys come
        # straight from the snapshot's label-value list (registry
        # label values are already strings in declaration order), so
        # the steady state per series is one dict hit + one append.
        updated = 0
        series_map = self._series
        resolution = self.cold_resolution_secs
        for family in snapshot.get("families", ()):
            name = family.get("name")
            kind = family.get("kind")
            if not name or kind not in (COUNTER, GAUGE, HISTOGRAM):
                continue
            is_hist = kind == HISTOGRAM
            for series in family.get("series", ()):
                values = series.get("labels")
                skey = (name, source, tuple(values) if values else ())
                entry = series_map.get(skey)
                if entry is None:
                    if len(series_map) >= self.max_series:
                        self.dropped_series += 1
                        continue
                    entry = series_map[skey] = _Series(
                        name, kind,
                        dict(zip(family.get("labelnames", ()),
                                 values or ())),
                        source,
                        tuple(family.get("buckets", ()))
                        if is_hist else (),
                        self.hot_capacity, self.cold_capacity,
                    )
                if is_hist:
                    buckets = series.get("buckets", ())
                    if len(buckets) != len(entry.bucket_ubs):
                        # Bucket config changed across a process
                        # restart: keep quantile bounds in step with
                        # the new points.
                        entry.bucket_ubs = tuple(
                            family.get("buckets", ())
                        )
                    entry.append_hist(
                        now, dt, series.get("count", 0),
                        series.get("sum", 0.0), buckets, resolution,
                    )
                else:
                    entry.append_scalar(
                        now, series.get("value", 0.0), dt, resolution,
                    )
                updated += 1
        return updated

    def drop_source(self, source: str) -> int:
        """Forget every series of one reporter — the DELIBERATE
        departure path (autoscaler drain, master recovery dropping a
        dead id). Without this, a scaled-away worker's frozen series
        would trip the absence rules meant for reporters that died
        unexpectedly. Returns the number of series dropped."""
        source = str(source)
        with self._lock:
            keys = [k for k in self._series if k[1] == source]
            for key in keys:
                del self._series[key]
            self._source_fingerprints.pop(source, None)
        return len(keys)

    # ---- selection -----------------------------------------------------

    def _match_locked(self, family: str,
                      labels: Optional[Dict[str, str]] = None,
                      source: Optional[str] = None) -> List[_Series]:
        out = []
        for entry in self._series.values():
            if entry.family != family:
                continue
            if source is not None and entry.source != source:
                continue
            if labels and any(
                entry.labels.get(k) != str(v) for k, v in labels.items()
            ):
                continue
            out.append(entry)
        return out

    # ---- window reductions (the SLO engine's inputs) -------------------

    def window_hist(self, family: str, window_secs: float,
                    labels: Optional[Dict[str, str]] = None,
                    source: Optional[str] = None,
                    now: Optional[float] = None):
        """Summed histogram deltas over the trailing window across all
        matching series: ``(count, sum, bucket_deltas, bucket_ubs)``.
        ``bucket_deltas`` is None when no matching histogram exists."""
        now = self._clock() if now is None else now
        cutoff = now - float(window_secs)
        count = 0.0
        total = 0.0
        deltas: Optional[List[float]] = None
        ubs: Tuple[float, ...] = ()
        with self._lock:
            for entry in self._match_locked(family, labels, source):
                if entry.kind != HISTOGRAM:
                    continue
                if len(entry.bucket_ubs) > len(ubs):
                    ubs = entry.bucket_ubs
                for t, _dt, count_d, sum_d, buckets_d in entry.points:
                    if t < cutoff:
                        continue
                    count += count_d
                    total += sum_d
                    if deltas is None:
                        deltas = list(buckets_d)
                        continue
                    # Points in one window can carry different bucket
                    # counts: a process restarted with changed bucket
                    # config appends new-length points into the same
                    # ring (append_hist treats that as a reset). Grow
                    # and add up to each point's own length — the
                    # reduction must degrade, not IndexError the rule
                    # blind across the restart it should survive.
                    if len(buckets_d) > len(deltas):
                        deltas.extend(
                            [0.0] * (len(buckets_d) - len(deltas))
                        )
                    for i, b in enumerate(buckets_d):
                        deltas[i] += b
        return count, total, deltas, ubs

    def window_quantile(self, family: str, window_secs: float, q: float,
                        labels: Optional[Dict[str, str]] = None,
                        source: Optional[str] = None,
                        now: Optional[float] = None,
                        ) -> Tuple[float, float]:
        """(quantile estimate, observation count) over the window."""
        count, _total, deltas, ubs = self.window_hist(
            family, window_secs, labels, source, now
        )
        if not deltas or count <= 0:
            return 0.0, 0.0
        return quantile_from_buckets(ubs, deltas, q, total=count), count

    def window_counter_delta(self, family: str, window_secs: float,
                             labels: Optional[Dict[str, str]] = None,
                             source: Optional[str] = None,
                             now: Optional[float] = None,
                             ) -> Tuple[float, int]:
        """(summed counter delta, point count) over the window."""
        now = self._clock() if now is None else now
        cutoff = now - float(window_secs)
        delta = 0.0
        n = 0
        with self._lock:
            for entry in self._match_locked(family, labels, source):
                if entry.kind != COUNTER:
                    continue
                for t, _dt, d in entry.points:
                    if t < cutoff:
                        continue
                    delta += d
                    n += 1
        return delta, n

    def gauge_values(self, family: str, window_secs: float,
                     labels: Optional[Dict[str, str]] = None,
                     source: Optional[str] = None,
                     now: Optional[float] = None) -> List[float]:
        """Every gauge point in the window across matching series,
        in TIME order — the autoscaler's trend input, and what makes
        the threshold rule's ``last`` aggregation mean "newest
        observation", not "final point of whichever series the store
        happened to create last"."""
        now = self._clock() if now is None else now
        cutoff = now - float(window_secs)
        out = []
        with self._lock:
            for entry in self._match_locked(family, labels, source):
                if entry.kind != GAUGE:
                    continue
                out.extend(
                    (t, v) for t, v in entry.points if t >= cutoff
                )
        out.sort(key=lambda tv: tv[0])
        return [v for _t, v in out]

    def last_seen(self, family: str,
                  labels: Optional[Dict[str, str]] = None,
                  source: Optional[str] = None) -> Dict[str, float]:
        """series key -> wall time of its newest point (frozen once
        the reporter goes silent; the absence rules' input)."""
        with self._lock:
            return {
                entry.key(): entry.last_seen
                for entry in self._match_locked(family, labels, source)
                if entry.last_seen > 0
            }

    # ---- endpoint / bundle rendering -----------------------------------

    def series_names(self) -> List[str]:
        with self._lock:
            return sorted(e.key() for e in self._series.values())

    def render(self, name: Optional[str] = None,
               window_secs: Optional[float] = None,
               tier: str = "hot",
               now: Optional[float] = None) -> dict:
        """JSON body for ``GET /timeseries`` (and the incident bundle's
        series window): ``name`` is a family-name prefix filter."""
        now = self._clock() if now is None else now
        tier = tier if tier in ("hot", "cold") else "hot"
        series = {}
        # Deque copies taken under the lock: a /timeseries GET (or an
        # incident writer) rendering concurrently with the sampler's
        # appends must not iterate a mutating deque.
        with self._lock:
            entries = [
                (e, list(e.points), list(e.cold))
                for e in self._series.values()
                if not name or e.family.startswith(name)
            ]
        for entry, hot_copy, cold_copy in entries:
            points = entry.render_points(
                window_secs, now, tier, points=hot_copy, cold=cold_copy
            )
            if not points:
                continue
            series[entry.key()] = {
                "kind": entry.kind,
                "family": entry.family,
                "source": entry.source,
                "last_seen": entry.last_seen,
                "points": points,
            }
        return {
            "now": now,
            "tier": tier,
            "cadence_secs": self.cadence_secs,
            "window_secs": window_secs,
            "series": series,
        }
