"""Per-principal usage metering and the fleet-wide ``/usage`` view.

The metering half runs in the serving paths (the RPC server wrap and
the row-service handlers) and turns each request's ambient principal
(``principal.py``) into labeled counter increments on the process
registry:

======================================  ===============================
family (``edl_tpu_`` prefixed)          meaning
======================================  ===============================
``usage_requests_total``                requests served, by principal
                                        and method
``usage_rows_total``                    rows moved (pull + push +
                                        ingest + replica), by principal
                                        and method
``usage_bytes_total``                   payload bytes moved, same axes
``usage_lock_hold_seconds_total``       row-service table-lock hold
                                        time, by principal
``usage_fsync_wait_seconds_total``      durable-ack fsync wait
                                        (push-log group commit), by
                                        principal
``usage_cold_fault_rows_total``         rows faulted from the cold
``usage_cold_fault_seconds_total``      tier + the fault I/O time, by
                                        principal
``usage_handler_seconds``               handler wall time histogram,
                                        by purpose and method (bounded
                                        axes; feeds SLO-per-purpose
                                        burn rules and the drill's
                                        non-``unknown`` share gate)
======================================  ===============================

Label cardinality is bounded: ``purpose`` is the closed enum,
``component`` is one of a handful of process roles, and ``job`` — the
one free-form axis — folds to ``__other__`` once ``MAX_JOBS`` distinct
values have been seen (``fold_job``; profiler-style overflow bucket),
so a job-id churn storm cannot grow the registry without bound.

The aggregation half (``summarize_usage``) runs at the master's
metrics plane: it merges the ``usage_*`` families across every
reporter snapshot plus the master's own registry into per-principal
totals, shares, and top-K consumers per shard — the ``/usage``
endpoint's body and the substrate the fair-share scheduler PR will
arbitrate with (ROADMAP).

Families resolve through ``default_registry()`` per call, like
``rpc._retry_counter`` — a dict hit, and a test's registry reset can't
strand cached series.
"""

import time
from threading import Lock
from typing import Dict, List, Optional

from elasticdl_tpu.observability import principal as _principal
from elasticdl_tpu.observability.registry import default_registry

OTHER_JOB = "__other__"
# Default job-label budget; a multi-tenant fleet raises it via
# --usage_max_jobs / set_max_jobs (a legitimately multi-job master
# must not fold real tenants into __other__).
MAX_JOBS = 32
_max_jobs = MAX_JOBS


def set_max_jobs(n: Optional[int]):
    """Override the job-label fold budget (``--usage_max_jobs``).
    ``None`` or 0 restores the ``MAX_JOBS`` default. Raising the cap
    takes effect immediately; lowering it does not un-admit jobs
    already granted a series (their budget is spent)."""
    global _max_jobs
    _max_jobs = int(n) if n else MAX_JOBS
    if _max_jobs <= 0:
        _max_jobs = MAX_JOBS


def max_jobs() -> int:
    return _max_jobs

# Handler-time buckets: 100µs .. 5s — RPC handlers, not jobs.
HANDLER_BUCKETS = (0.0001, 0.0005, 0.001, 0.005, 0.01, 0.05,
                   0.1, 0.5, 1.0, 5.0)

_PRINCIPAL_LABELS = ["job", "component", "purpose"]

# job-fold state, keyed to the registry generation so a reset starts a
# fresh budget (the folded-to series died with the families).
_fold_lock = Lock()
_fold_generation = -1
_fold_jobs: set = set()


def fold_job(job: str, registry=None) -> str:
    """Bound the free-form job label: the first ``max_jobs()`` distinct
    values pass through (default ``MAX_JOBS``; --usage_max_jobs
    raises it), everything after folds to ``__other__``. ``unknown``
    and ``__other__`` ride free (absence/overflow values must never
    consume budget)."""
    global _fold_generation, _fold_jobs
    job = str(job)
    if job == _principal.UNKNOWN or job == OTHER_JOB:
        return job
    registry = registry if registry is not None else default_registry()
    with _fold_lock:
        if registry.generation != _fold_generation:
            _fold_generation = registry.generation
            _fold_jobs = set()
        if job in _fold_jobs:
            return job
        if len(_fold_jobs) < _max_jobs:
            _fold_jobs.add(job)
            return job
        return OTHER_JOB


def _labels(principal: Optional["_principal.Principal"]):
    if principal is None:
        principal = _principal.NOBODY
    return (fold_job(principal.job), principal.component,
            principal.purpose)


def _requests():
    return default_registry().counter(
        "usage_requests_total",
        "RPCs served, by workload principal and method",
        _PRINCIPAL_LABELS + ["method"],
    )


def _rows():
    return default_registry().counter(
        "usage_rows_total",
        "Embedding rows moved, by workload principal and method",
        _PRINCIPAL_LABELS + ["method"],
    )


def _bytes():
    return default_registry().counter(
        "usage_bytes_total",
        "Payload bytes moved, by workload principal and method",
        _PRINCIPAL_LABELS + ["method"],
    )


def _lock_hold():
    return default_registry().counter(
        "usage_lock_hold_seconds_total",
        "Row-service table-lock hold time, by workload principal",
        _PRINCIPAL_LABELS,
    )


def _fsync_wait():
    return default_registry().counter(
        "usage_fsync_wait_seconds_total",
        "Durable-ack fsync wait (push-log group commit), by workload "
        "principal",
        _PRINCIPAL_LABELS,
    )


def _fault_rows():
    return default_registry().counter(
        "usage_cold_fault_rows_total",
        "Rows faulted in from the cold tier, by workload principal",
        _PRINCIPAL_LABELS,
    )


def _fault_seconds():
    return default_registry().counter(
        "usage_cold_fault_seconds_total",
        "Cold-tier fault I/O time, by workload principal",
        _PRINCIPAL_LABELS,
    )


def _handler_seconds():
    return default_registry().histogram(
        "usage_handler_seconds",
        "RPC handler wall time, by purpose and method (bounded axes "
        "for SLO-per-purpose burn rules)",
        ["purpose", "method"],
        buckets=HANDLER_BUCKETS,
    )


def meter_request(principal, method: str, seconds: float):
    """One served request: count it and observe handler wall time.
    Called by the generic RPC server wrap (``comm/rpc.py``) — covers
    the master and the row tier uniformly."""
    if not _principal.enabled():
        return
    labels = _labels(principal)
    _requests().labels(*labels, str(method)).inc()
    _handler_seconds().labels(labels[2], str(method)).observe(
        float(seconds)
    )


def meter_rows(principal, method: str, rows: int = 0,
               nbytes: int = 0):
    if not _principal.enabled():
        return
    labels = _labels(principal)
    if rows:
        _rows().labels(*labels, str(method)).inc(int(rows))
    if nbytes:
        _bytes().labels(*labels, str(method)).inc(int(nbytes))


def meter_lock_hold(principal, seconds: float):
    if not _principal.enabled():
        return
    _lock_hold().labels(*_labels(principal)).inc(float(seconds))


def meter_fsync_wait(principal, seconds: float):
    if not _principal.enabled():
        return
    _fsync_wait().labels(*_labels(principal)).inc(float(seconds))


def meter_cold_fault(principal, rows: int, seconds: float):
    if not _principal.enabled():
        return
    labels = _labels(principal)
    if rows:
        _fault_rows().labels(*labels).inc(int(rows))
    _fault_seconds().labels(*labels).inc(float(seconds))


# ---- /usage aggregation -------------------------------------------------

_NS = "edl_tpu_"
_COUNTER_KEYS = {
    _NS + "usage_requests_total": "requests",
    _NS + "usage_rows_total": "rows",
    _NS + "usage_bytes_total": "bytes",
    _NS + "usage_lock_hold_seconds_total": "lock_hold_seconds",
    _NS + "usage_fsync_wait_seconds_total": "fsync_wait_seconds",
    _NS + "usage_cold_fault_rows_total": "cold_fault_rows",
    _NS + "usage_cold_fault_seconds_total": "cold_fault_seconds",
}
_HANDLER_FAMILY = _NS + "usage_handler_seconds"
_SHARE_KEYS = ("requests", "rows", "bytes", "lock_hold_seconds",
               "fsync_wait_seconds")


def _zero_totals() -> dict:
    out = {key: 0.0 for key in _COUNTER_KEYS.values()}
    out["handler_seconds"] = 0.0
    return out


def summarize_usage(snapshots: Dict[str, dict], top_k: int = 5) -> dict:
    """Fold ``usage_*`` families from reporter snapshots (reporter key
    -> ``registry.snapshot()`` form; the master passes its own registry
    under key ``""``) into the ``/usage`` body:

    - ``principals``: per-``(job, component, purpose)`` totals across
      the fleet plus each metric's share of its fleet total;
    - ``purposes``: handler-seconds by purpose with shares, and the
      ``attributed_handler_share`` (non-``unknown`` fraction — the
      drill's 95% gate reads this);
    - ``shards``: per-reporter top-K principals by bytes (requests as
      tiebreak) — who is hammering which shard;
    - ``totals``: the fleet-wide sums.
    """
    per_principal: Dict[tuple, dict] = {}
    per_purpose: Dict[str, float] = {}
    per_shard: Dict[str, Dict[tuple, dict]] = {}

    for reporter, snapshot in sorted(
            snapshots.items(), key=lambda kv: str(kv[0])):
        families = (snapshot or {}).get("families") or []
        shard_acc = per_shard.setdefault(str(reporter), {})
        for family in families:
            name = family.get("name")
            labelnames = family.get("labelnames") or []
            if name in _COUNTER_KEYS:
                key = _COUNTER_KEYS[name]
                for series in family.get("series") or []:
                    labels = dict(zip(labelnames,
                                      series.get("labels") or []))
                    who = (labels.get("job", _principal.UNKNOWN),
                           labels.get("component", _principal.UNKNOWN),
                           labels.get("purpose", _principal.UNKNOWN))
                    value = float(series.get("value") or 0.0)
                    acc = per_principal.setdefault(who, _zero_totals())
                    acc[key] += value
                    sacc = shard_acc.setdefault(who, _zero_totals())
                    sacc[key] += value
            elif name == _HANDLER_FAMILY:
                for series in family.get("series") or []:
                    labels = dict(zip(labelnames,
                                      series.get("labels") or []))
                    purpose = labels.get("purpose", _principal.UNKNOWN)
                    secs = float(series.get("sum") or 0.0)
                    per_purpose[purpose] = (
                        per_purpose.get(purpose, 0.0) + secs
                    )

    totals = _zero_totals()
    for acc in per_principal.values():
        for key in _COUNTER_KEYS.values():
            totals[key] += acc[key]
    handler_total = sum(per_purpose.values())
    totals["handler_seconds"] = handler_total

    # handler_seconds is purpose-axis only (the histogram is
    # deliberately job-free): principal rows carry the counter axes,
    # the purposes block carries handler time.
    principals: List[dict] = []
    for who in sorted(per_principal):
        acc = per_principal[who]
        share = {
            key: (acc[key] / totals[key]) if totals[key] else 0.0
            for key in _SHARE_KEYS
        }
        principals.append({
            "principal": {"job": who[0], "component": who[1],
                          "purpose": who[2]},
            **{key: acc[key] for key in _COUNTER_KEYS.values()},
            "share": share,
        })
    principals.sort(
        key=lambda row: (-row["bytes"], -row["requests"],
                         str(row["principal"]))
    )

    purposes = {
        purpose: {
            "handler_seconds": secs,
            "share": (secs / handler_total) if handler_total else 0.0,
        }
        for purpose, secs in sorted(per_purpose.items())
    }
    unknown_secs = per_purpose.get(_principal.UNKNOWN, 0.0)
    attributed_share = (
        (handler_total - unknown_secs) / handler_total
        if handler_total else 0.0
    )

    shards = {}
    for reporter, acc_by_who in per_shard.items():
        rows = []
        for who, acc in acc_by_who.items():
            if not any(acc[key] for key in _COUNTER_KEYS.values()):
                continue
            rows.append({
                "principal": {"job": who[0], "component": who[1],
                              "purpose": who[2]},
                "requests": acc["requests"],
                "rows": acc["rows"],
                "bytes": acc["bytes"],
                "lock_hold_seconds": acc["lock_hold_seconds"],
            })
        if not rows:
            continue
        rows.sort(key=lambda row: (-row["bytes"], -row["requests"],
                                   str(row["principal"])))
        shards[reporter] = {"top": rows[:int(top_k)]}

    return {
        "now": time.time(),
        "totals": totals,
        "principals": principals,
        "purposes": purposes,
        "attributed_handler_share": attributed_share,
        "shards": shards,
    }
