"""Prometheus text-format rendering + the stdlib-only /metrics endpoint.

``render_prometheus`` turns registry snapshots (``registry.snapshot()``
dicts) into Prometheus exposition format 0.0.4: one ``# HELP`` /
``# TYPE`` pair per family, escaped label values, and cumulative
histogram ``_bucket``/``_sum``/``_count`` series with ``le`` labels.
Worker snapshots get a ``worker="<id>"`` label so the cluster view
keeps per-worker series apart (and a departed worker's series simply
stop appearing once the aggregator ages it out).

``MetricsHTTPServer`` serves ``/metrics`` and ``/healthz`` from a
``http.server.ThreadingHTTPServer`` on a daemon thread — no new
dependency, ephemeral-port friendly (``port=0``), scrapeable by real
Prometheus or ``tools/dump_metrics.py``. With a ``traces`` callable it
also serves ``/traces``: the process flight recorder / master trace
collection as JSON, for ``tools/dump_metrics.py --traces``.
"""

import json
import threading
import urllib.parse
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, Dict, Optional

from elasticdl_tpu.common.log_utils import get_logger

logger = get_logger("metrics_http")

CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"
# Exemplars are only legal in the OpenMetrics wire format — a classic
# 0.0.4 parser rejects the mid-line `#` — so /metrics serves them only
# to clients that ASK via Accept (exactly Prometheus's negotiation),
# terminated by the mandatory `# EOF`.
OPENMETRICS_CONTENT_TYPE = (
    "application/openmetrics-text; version=1.0.0; charset=utf-8"
)


def _escape_help(text: str) -> str:
    return text.replace("\\", r"\\").replace("\n", r"\n")


def _escape_label_value(value: str) -> str:
    return (
        value.replace("\\", r"\\").replace('"', r"\"").replace("\n", r"\n")
    )


def _format_value(value: float) -> str:
    if value == float("inf"):
        return "+Inf"
    if value == float("-inf"):
        return "-Inf"
    if float(value) == int(value) and abs(value) < 1e15:
        return str(int(value))
    return repr(float(value))


def _label_str(labelnames, labelvalues, extra: Dict[str, str]) -> str:
    parts = [
        f'{name}="{_escape_label_value(str(value))}"'
        for name, value in list(zip(labelnames, labelvalues))
        + sorted(extra.items())
    ]
    return "{%s}" % ",".join(parts) if parts else ""


def _exemplar_suffix(series: dict, index: int) -> str:
    """OpenMetrics exemplar rendering for one bucket line:
    `` # {trace_id="..."} value timestamp`` — how a scrape links a
    histogram bucket to one concrete trace (docs/observability.md
    "Continuous profiling & exemplars"). Empty when the series carries
    no exemplar for that bucket."""
    exemplars = series.get("exemplars")
    if not exemplars:
        return ""
    entry = exemplars.get(str(index))
    if not entry:
        return ""
    value, trace_id, ts = entry
    return (
        f' # {{trace_id="{_escape_label_value(str(trace_id))}"}}'
        f" {_format_value(float(value))} {float(ts):.3f}"
    )


def _render_series(lines, family: dict, series: dict,
                   extra: Dict[str, str], exemplars: bool = False):
    name = family["name"]
    labelnames = family.get("labelnames", [])
    values = series.get("labels", [])
    if family["kind"] == "histogram":
        cumulative = 0
        for i, (ub, n) in enumerate(
            zip(family["buckets"], series["buckets"])
        ):
            cumulative += n
            le = {"le": _format_value(ub)}
            suffix = _exemplar_suffix(series, i) if exemplars else ""
            lines.append(
                f"{name}_bucket"
                f"{_label_str(labelnames, values, {**extra, **le})}"
                f" {cumulative}{suffix}"
            )
        suffix = (
            _exemplar_suffix(series, len(family["buckets"]))
            if exemplars else ""
        )
        lines.append(
            f"{name}_bucket"
            f"{_label_str(labelnames, values, {**extra, 'le': '+Inf'})}"
            f" {series['count']}{suffix}"
        )
        lines.append(
            f"{name}_sum{_label_str(labelnames, values, extra)}"
            f" {_format_value(series['sum'])}"
        )
        lines.append(
            f"{name}_count{_label_str(labelnames, values, extra)}"
            f" {series['count']}"
        )
    else:
        lines.append(
            f"{name}{_label_str(labelnames, values, extra)}"
            f" {_format_value(series['value'])}"
        )


def render_prometheus(
    local_snapshot: Optional[dict] = None,
    worker_snapshots: Optional[Dict[int, dict]] = None,
    exemplars: bool = False,
) -> str:
    """Render the master-local snapshot plus per-worker snapshots.

    Families appearing in several snapshots (every worker instruments
    the same code) emit ONE ``# HELP``/``# TYPE`` header; worker series
    carry a ``worker`` label, master-local series none.

    ``exemplars=True`` renders captured histogram exemplars as
    OpenMetrics bucket-line suffixes — ONLY legal on the OpenMetrics
    content type (the /metrics handler negotiates via Accept); the
    classic 0.0.4 rendering must stay exemplar-free or standard
    Prometheus parsers reject the whole scrape.
    """
    # family name -> (family dict, [(series, extra_labels)])
    merged: Dict[str, tuple] = {}

    def _ingest(snapshot: dict, extra: Dict[str, str]):
        for family in snapshot.get("families", []):
            entry = merged.get(family["name"])
            if entry is None:
                entry = merged[family["name"]] = (family, [])
            for series in family.get("series", []):
                entry[1].append((family, series, extra))

    if local_snapshot:
        _ingest(local_snapshot, {})
    # key=str: reporter keys mix worker ints with named components
    # ("router-0") since the snapshot piggyback grew beyond workers.
    for worker_id in sorted(worker_snapshots or {}, key=str):
        _ingest(worker_snapshots[worker_id], {"worker": str(worker_id)})

    lines = []
    for name in sorted(merged):
        family, series_list = merged[name]
        lines.append(f"# HELP {name} {_escape_help(family.get('help', ''))}")
        lines.append(f"# TYPE {name} {family['kind']}")
        for owning_family, series, extra in series_list:
            _render_series(lines, owning_family, series, extra,
                           exemplars=exemplars)
    return "\n".join(lines) + "\n"


class _Handler(BaseHTTPRequestHandler):
    # Populated per-server via functools.partial-style subclassing in
    # MetricsHTTPServer.start().
    render: Callable[[], str] = staticmethod(lambda: "")
    # OpenMetrics rendering (with exemplars) served when the client's
    # Accept names it; None = classic only.
    render_openmetrics: Optional[Callable[[], str]] = None
    traces: Optional[Callable[[], dict]] = None
    # path -> callable(query_params_dict) -> JSON-able object; how the
    # SLO plane mounts /timeseries and /alerts without this module
    # knowing either (docs/observability.md).
    json_routes: Dict[str, Callable[[dict], object]] = {}
    # Mutable holder {"fn": callable or None}: when set, /healthz
    # serves fn()'s JSON verdict with HTTP 200/503 on its "ok" key —
    # how the synthetic-probe plane (observability/prober.py) turns
    # the static liveness endpoint into an aggregated readiness
    # verdict. Holder (not a bare callable) so it can be mounted on a
    # server that already started, like json_routes.
    health: Dict[str, Optional[Callable[[], dict]]] = {}

    def _reply(self, body: bytes, content_type: str):
        self.send_response(200)
        self.send_header("Content-Type", content_type)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def do_GET(self):  # noqa: N802 (BaseHTTPRequestHandler API)
        path, _, query = self.path.partition("?")
        routes = type(self).json_routes
        if path == "/metrics":
            om = type(self).render_openmetrics
            accept = self.headers.get("Accept", "") or ""
            try:
                if om is not None and "openmetrics" in accept:
                    body = (om() + "# EOF\n").encode("utf-8")
                    self._reply(body, OPENMETRICS_CONTENT_TYPE)
                    return
                body = type(self).render().encode("utf-8")
            except Exception as exc:
                self.send_error(500, f"{type(exc).__name__}: {exc}")
                return
            self._reply(body, CONTENT_TYPE)
        elif path == "/traces" and type(self).traces is not None:
            try:
                body = json.dumps(type(self).traces()).encode("utf-8")
            except Exception as exc:
                self.send_error(500, f"{type(exc).__name__}: {exc}")
                return
            self._reply(body, "application/json")
        elif path in routes:
            params = {
                k: v[-1]
                for k, v in urllib.parse.parse_qs(query).items()
            }
            try:
                body = json.dumps(routes[path](params)).encode("utf-8")
            except Exception as exc:
                self.send_error(500, f"{type(exc).__name__}: {exc}")
                return
            self._reply(body, "application/json")
        elif path == "/healthz":
            health_fn = type(self).health.get("fn")
            if health_fn is None:
                self._reply(b"ok\n", "text/plain; charset=utf-8")
                return
            try:
                verdict = health_fn()
            except Exception as exc:
                self.send_error(500, f"{type(exc).__name__}: {exc}")
                return
            body = json.dumps(verdict).encode("utf-8")
            # An unhealthy verdict must be machine-visible from the
            # status line alone (load balancers, kubelet probes).
            status = 200 if verdict.get("ok", True) else 503
            self.send_response(status)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)
        else:
            known = ", ".join(
                ["/metrics", "/traces", "/healthz"] + sorted(routes)
            )
            self.send_error(404, f"try {known}")

    def log_message(self, fmt, *args):
        logger.debug("metrics http: " + fmt, *args)


class MetricsHTTPServer:
    """``/metrics`` + ``/healthz`` on a daemon thread.

    ``render`` is a zero-arg callable returning the exposition text
    (typically ``MetricsPlane.render``); evaluated per scrape so gauges
    with pull-time callbacks stay live.
    """

    def __init__(self, render: Callable[[], str], port: int = 0,
                 host: str = "",
                 traces: Optional[Callable[[], dict]] = None,
                 json_routes: Optional[
                     Dict[str, Callable[[dict], object]]] = None,
                 render_openmetrics: Optional[
                     Callable[[], str]] = None,
                 health: Optional[Callable[[], dict]] = None):
        self._render = render
        self._render_openmetrics = render_openmetrics
        self._traces = traces
        self._json_routes = dict(json_routes or {})
        self._health = {"fn": health}
        self._host = host
        self._requested_port = int(port)
        self._httpd: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None

    def start(self) -> "MetricsHTTPServer":
        handler = type("_BoundHandler", (_Handler,), {
            "render": staticmethod(self._render),
            "render_openmetrics": (
                staticmethod(self._render_openmetrics)
                if self._render_openmetrics is not None else None
            ),
            "traces": (
                staticmethod(self._traces)
                if self._traces is not None else None
            ),
            "json_routes": self._json_routes,
            "health": self._health,
        })
        self._httpd = ThreadingHTTPServer(
            (self._host, self._requested_port), handler
        )
        self._httpd.daemon_threads = True
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True,
            name="metrics-http",
        )
        self._thread.start()
        logger.info("/metrics serving on port %d", self.port)
        return self

    def set_health(self, fn: Optional[Callable[[], dict]]):
        """(Re)mount the /healthz verdict callable — live on a running
        server (the holder dict is shared by reference)."""
        self._health["fn"] = fn

    @property
    def port(self) -> int:
        return self._httpd.server_address[1] if self._httpd else 0

    def stop(self):
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
