"""Process-local metrics registry: counters, gauges, histograms.

Prometheus-shaped but dependency-free: a registry holds labeled metric
*families*; a family with label names yields per-label-value *series*
via ``labels()``; a family without labels is itself the series. All
mutation goes through one registry lock — instrumented paths are RPC
handlers and per-step host code, where a lock acquisition is noise.

``snapshot()`` returns a plain-dict form (msgpack/json-safe, no numpy)
that workers piggyback on master-client RPCs; the master merges
snapshots into the cluster view (``aggregator.ClusterMetrics``) and
renders them as Prometheus text (``exposition.render_prometheus``).

Families are idempotent per registry: re-declaring the same name
returns the existing family (instrumented classes may be constructed
many times per process, e.g. one ``TaskDispatcher`` per test), but a
kind/labelnames mismatch raises — two call sites disagreeing about a
metric is a bug, not a merge.
"""

import threading
import time
import uuid
from typing import Callable, Dict, Optional, Sequence, Tuple

COUNTER = "counter"
GAUGE = "gauge"
HISTOGRAM = "histogram"

# Lazily bound to tracing.current_trace_id on the first exemplar-enabled
# observation (tracing imports nothing from here, so the import is
# safe; lazy keeps registry import-light for the many modules that
# never enable exemplars).
_ambient_trace_id: Optional[Callable[[], Optional[str]]] = None


def _trace_id_now() -> Optional[str]:
    global _ambient_trace_id
    fn = _ambient_trace_id
    if fn is None:
        from elasticdl_tpu.observability.tracing import current_trace_id

        fn = _ambient_trace_id = current_trace_id
    return fn()

# Default latency buckets (seconds): 100µs .. ~2min, roughly 3x apart —
# spans a single fused device step up to a straggling task.
DEFAULT_BUCKETS = (
    0.0001, 0.0005, 0.001, 0.005, 0.01, 0.05, 0.1, 0.5,
    1.0, 5.0, 15.0, 60.0, 120.0,
)


class _Series:
    """One (family, label values) time series."""

    def __init__(self, family: "MetricFamily"):
        self._family = family
        self._lock = family._lock
        self.value = 0.0
        self._fn: Optional[Callable[[], float]] = None
        if family.kind == HISTOGRAM:
            self.bucket_counts = [0] * len(family.buckets)
            self.sum = 0.0
            self.count = 0
            # Exemplars (opt-in per family): bucket index -> (value,
            # trace_id, unix ts) of the latest trace-linked observation
            # landing there — OpenMetrics-shaped, O(1) per observe, so
            # an alert's "p99 burned" can name one concrete offending
            # trace (docs/observability.md "Continuous profiling &
            # exemplars"). Index len(buckets) = the +Inf overflow.
            self.exemplars: Dict[int, tuple] = {}

    # ---- counter / gauge ----------------------------------------------

    def inc(self, amount: float = 1.0):
        if self._family.kind == COUNTER and amount < 0:
            raise ValueError("counters only go up")
        with self._lock:
            self.value += amount

    def dec(self, amount: float = 1.0):
        if self._family.kind != GAUGE:
            raise ValueError("dec() is gauge-only")
        with self._lock:
            self.value -= amount

    def set(self, value: float):
        if self._family.kind != GAUGE:
            raise ValueError("set() is gauge-only")
        with self._lock:
            self.value = float(value)

    def set_function(self, fn: Callable[[], float]):
        """Pull-time gauge: ``fn`` is evaluated at snapshot. Re-binding
        replaces the callback (latest instance wins — long processes
        construct instrumented objects repeatedly)."""
        if self._family.kind != GAUGE:
            raise ValueError("set_function() is gauge-only")
        with self._lock:
            self._fn = fn

    # ---- histogram -----------------------------------------------------

    def observe(self, value: float, trace_id: Optional[str] = None):
        """``trace_id`` links this observation to a trace (exemplar-
        enabled families only). None falls back to the thread's
        innermost open span — call sites whose span already closed
        pass the id explicitly."""
        if self._family.kind != HISTOGRAM:
            raise ValueError("observe() is histogram-only")
        value = float(value)
        with self._lock:
            idx = len(self._family.buckets)
            for i, ub in enumerate(self._family.buckets):
                if value <= ub:
                    self.bucket_counts[i] += 1
                    idx = i
                    break
            self.sum += value
            self.count += 1
            if self._family.exemplars:
                if trace_id is None:
                    trace_id = _trace_id_now()
                if trace_id:
                    self.exemplars[idx] = (
                        value, str(trace_id), time.time()
                    )

    # ---- snapshot ------------------------------------------------------

    def _snapshot_locked(self, label_values: Tuple[str, ...]) -> dict:
        if self._family.kind == HISTOGRAM:
            out = {
                "labels": list(label_values),
                "buckets": list(self.bucket_counts),
                "sum": float(self.sum),
                "count": int(self.count),
            }
            if self.exemplars:
                # str keys: the snapshot must stay msgpack/json-safe
                # end to end (piggyback RPCs, incident bundles).
                out["exemplars"] = {
                    str(i): [float(v), tid, float(ts)]
                    for i, (v, tid, ts) in self.exemplars.items()
                }
            return out
        value = self.value
        if self._fn is not None:
            try:
                value = float(self._fn())
            except Exception:
                # A dead callback (its object got collected mid-test)
                # must not poison the whole snapshot.
                value = self.value
        return {"labels": list(label_values), "value": float(value)}


class MetricFamily:
    """A named metric with fixed label names; ``labels()`` yields the
    per-label-value series. With no label names the family proxies its
    single series (``family.inc()`` etc. work directly)."""

    def __init__(self, registry: "MetricsRegistry", name: str, kind: str,
                 help_text: str, labelnames: Sequence[str],
                 buckets: Sequence[float] = DEFAULT_BUCKETS,
                 exemplars: bool = False):
        self.name = name
        self.kind = kind
        self.help = help_text
        self.labelnames = tuple(labelnames)
        self.buckets = tuple(sorted(float(b) for b in buckets))
        # Exemplar capture (histograms only): opt-in because every
        # enabled observe pays a thread-local read; idempotent
        # re-declaration ORs the flag (several call sites may declare
        # one family, any of them opting in wins).
        self.exemplars = bool(exemplars) and kind == HISTOGRAM
        self._lock = registry._lock
        self._series: Dict[Tuple[str, ...], _Series] = {}
        if not self.labelnames:
            self._series[()] = _Series(self)

    def labels(self, *values, **kv) -> _Series:
        if kv:
            if values:
                raise ValueError("pass label values or kwargs, not both")
            values = tuple(kv[name] for name in self.labelnames)
        values = tuple(str(v) for v in values)
        if len(values) != len(self.labelnames):
            raise ValueError(
                f"{self.name}: expected labels {self.labelnames}, "
                f"got {values}"
            )
        with self._lock:
            series = self._series.get(values)
            if series is None:
                series = self._series[values] = _Series(self)
            return series

    # Label-less proxying.
    def inc(self, amount: float = 1.0):
        self.labels().inc(amount)

    def dec(self, amount: float = 1.0):
        self.labels().dec(amount)

    def set(self, value: float):
        self.labels().set(value)

    def set_function(self, fn: Callable[[], float]):
        self.labels().set_function(fn)

    def observe(self, value: float, trace_id: Optional[str] = None):
        self.labels().observe(value, trace_id=trace_id)

    def snapshot(self) -> dict:
        with self._lock:
            out = {
                "name": self.name,
                "kind": self.kind,
                "help": self.help,
                "labelnames": list(self.labelnames),
                "series": [
                    series._snapshot_locked(values)
                    for values, series in sorted(self._series.items())
                ],
            }
            if self.kind == HISTOGRAM:
                out["buckets"] = list(self.buckets)
            return out


class MetricsRegistry:
    """A set of metric families sharing one namespace and lock.

    ``namespace`` prefixes every family name (``worker_step_seconds`` →
    ``edl_tpu_worker_step_seconds``) so the naming scheme lives in one
    place instead of at forty call sites.
    """

    def __init__(self, namespace: str = "edl_tpu"):
        self.namespace = namespace
        self._lock = threading.RLock()
        self._families: Dict[str, MetricFamily] = {}
        # Identifies this registry's lifetime in snapshots: a replacement
        # worker process reuses the departed one's worker id, and the
        # master tells "same process, counters continuous" from "new
        # process, counters restarted" by this token, not the id.
        self._instance = uuid.uuid4().hex
        # Bumped on reset(): callers caching resolved series (hot-path
        # instrumentation like RpcStub) compare this to notice the
        # families were dropped and must be re-resolved.
        self.generation = 0

    def _family(self, name: str, kind: str, help_text: str,
                labelnames: Sequence[str],
                buckets: Sequence[float] = DEFAULT_BUCKETS,
                exemplars: bool = False) -> MetricFamily:
        full = f"{self.namespace}_{name}" if self.namespace else name
        with self._lock:
            family = self._families.get(full)
            if family is not None:
                if (family.kind != kind
                        or family.labelnames != tuple(labelnames)):
                    raise ValueError(
                        f"metric {full} re-declared as {kind}"
                        f"{tuple(labelnames)}; existing is {family.kind}"
                        f"{family.labelnames}"
                    )
                if (kind == HISTOGRAM and family.buckets
                        != tuple(sorted(float(b) for b in buckets))):
                    raise ValueError(
                        f"histogram {full} re-declared with buckets "
                        f"{tuple(buckets)}; existing is {family.buckets}"
                    )
                if exemplars and kind == HISTOGRAM:
                    family.exemplars = True
                return family
            family = MetricFamily(
                self, full, kind, help_text, labelnames, buckets,
                exemplars=exemplars,
            )
            self._families[full] = family
            return family

    def counter(self, name: str, help_text: str = "",
                labelnames: Sequence[str] = ()) -> MetricFamily:
        return self._family(name, COUNTER, help_text, labelnames)

    def gauge(self, name: str, help_text: str = "",
              labelnames: Sequence[str] = ()) -> MetricFamily:
        return self._family(name, GAUGE, help_text, labelnames)

    def histogram(self, name: str, help_text: str = "",
                  labelnames: Sequence[str] = (),
                  buckets: Sequence[float] = DEFAULT_BUCKETS,
                  exemplars: bool = False) -> MetricFamily:
        return self._family(name, HISTOGRAM, help_text, labelnames,
                            buckets, exemplars=exemplars)

    def snapshot(self) -> dict:
        """Plain-dict snapshot of every family (msgpack/json-safe) —
        what workers ship to the master."""
        with self._lock:
            families = list(self._families.values())
            instance = self._instance
        return {
            "instance": instance,
            "families": [f.snapshot() for f in families],
        }

    def reset(self):
        """Drop every family (test isolation for the shared default).
        Rotates the instance token: post-reset counters restart at zero,
        which downstream must treat like a process replacement."""
        with self._lock:
            self._families.clear()
            self._instance = uuid.uuid4().hex
            self.generation += 1


_DEFAULT = MetricsRegistry()


def default_registry() -> MetricsRegistry:
    """The process-wide registry every layer instruments by default —
    one worker per process in production, so per-process is per-worker;
    tests needing isolation construct their own ``MetricsRegistry``."""
    return _DEFAULT
